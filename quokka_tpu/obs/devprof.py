"""Device-time & roofline efficiency plane.

The obs stack can say where host wall-time went (``obs/critpath.py``), what
memory was held (``obs/memplane.py``) and how many rows moved
(``obs/opstats.py``) — but not whether the device was *busy* or *efficient*.
This module closes that gap with three pieces:

1. **Per-program static cost ledger.**  At AOT compile time
   ``runtime/compileplane.acquire`` hands the freshly compiled executable to
   :func:`record_cost`, which extracts XLA's static cost figures
   (``compiled.cost_analysis()``: flops, bytes accessed, output bytes) and
   persists them in a ``<artifact>.cost.json`` sidecar next to the AOT
   executable, keyed by the same program signature.  A cache hit replays the
   sidecar via :func:`load_cost` — no recompile, no re-analysis.

2. **Calibrated peaks.**  :func:`calibrate` micro-benchmarks peak achievable
   FLOP/s (MXU-shaped matmul) and memory bandwidth (streaming elementwise
   add) once per backend fingerprint — the exact ``ops/strategy.py``
   pattern — and persists ``{peak_flops_s, peak_bw_bytes_s}`` under
   ``<cache>/devprof/<fingerprint>.json``.  A profile written by a foreign
   fingerprint (different host, jax version, device kind/count) is rejected
   wholesale, like every other persisted profile in the tree.

3. **Runtime attribution, ZERO new host syncs.**  Every program dispatch
   funnels through :func:`on_dispatch`, which charges the program's *static*
   flops/bytes to the thread-local current operator that ``obs/opstats.py``
   already maintains.  Joining those charges against opstats' measured wall
   seconds per operator yields achieved-FLOP/s, achieved bandwidth,
   arithmetic intensity and roofline-efficiency %% — attached to the opstats
   snapshot (:func:`attach`), rendered by ``explain()`` / ``bench.py
   --measure`` / ``/status``, and exported as ``quokka_devprof_*``
   Prometheus families.  No figure here ever reads a device value.

At query GC :func:`on_query_finished` persists the observed per-source scan
seconds and the query's achieved bandwidth into the same profile, which is
what lets ``planner/cost.py`` convert rows×bytes estimates into *predicted
device seconds* (``CostModel.estimate_seconds``: measured program seconds >
roofline prediction > hint) — ROADMAP item 2's feedback loop reasoning in
seconds instead of abstract bytes.

Env knobs (README "Device profiling & roofline"):

- ``QK_DEVPROF``: unset/1 -> profiling on; ``0`` -> everything off.
- ``QK_EFF_FLOOR``: roofline-efficiency fraction below which explain()
  flags an operator (default 0.05).
- ``QK_DEVPROF_DIR``: profile directory; empty string disables
  persistence; unset -> ``<cache>/devprof``.
- ``QK_DEVPROF_CALIBRATE``: ``0`` -> ``ensure_calibrated`` will not run
  the micro-benchmarks (loads an existing profile only).
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from quokka_tpu import config

_PROFILE_VERSION = 1
_COST_VERSION = 1

# process-wide state: static program costs, per-(query, actor) attribution,
# per-program dispatch tallies, and the calibrated-peaks profile
_lock = threading.Lock()
_costs: Dict[Any, Dict[str, float]] = {}
_attr: Dict[Tuple[str, int], List[float]] = {}
_prog_disp: Dict[Any, int] = {}
_qgauges: Dict[str, List[str]] = {}
_peaks: Optional[Dict[str, Any]] = None
_calib_state = "unloaded"


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """``QK_DEVPROF``: unset/1 -> on; ``0`` -> the whole plane off."""
    return os.environ.get("QK_DEVPROF", "1") != "0"


def eff_floor() -> float:
    """``QK_EFF_FLOOR``: roofline-efficiency fraction below which an
    operator is flagged in explain() (default 0.05)."""
    try:
        return float(os.environ.get("QK_EFF_FLOOR", 0.05))
    except ValueError:
        return 0.05


def _dir() -> Optional[str]:
    """Profile directory; QK_DEVPROF_DIR='' disables persistence (the
    tests' default via conftest), unset falls back to <cache>/devprof."""
    d = os.environ.get("QK_DEVPROF_DIR")
    if d is not None:
        return d or None
    root = config.CACHE_ROOT
    return os.path.join(root, "devprof") if root else None


def _fingerprint() -> str:
    from quokka_tpu.runtime import compileplane

    return compileplane.backend_fingerprint()


def _profile_path() -> Optional[str]:
    d = _dir()
    return os.path.join(d, f"{_fingerprint()}.json") if d else None


# ---------------------------------------------------------------------------
# Calibration profile: load / validate / persist (strategy.py discipline)
# ---------------------------------------------------------------------------


def _valid_profile(data: Any) -> bool:
    if not isinstance(data, dict):
        return False
    if data.get("version") != _PROFILE_VERSION:
        return False
    if data.get("fingerprint") != _fingerprint():
        return False
    for k in ("peak_flops_s", "peak_bw_bytes_s"):
        v = data.get(k)
        if not isinstance(v, (int, float)) or not math.isfinite(v) or v <= 0:
            return False
    if not isinstance(data.get("sources", {}), dict):
        return False
    return True


def _load_profile(path: Optional[str]) -> Optional[Dict[str, Any]]:
    """Wholesale rejection: a corrupt, versioned-away or foreign-fingerprint
    profile is ignored entirely (never partially trusted)."""
    if not path:
        return None
    try:
        with open(path) as f:
            data = json.load(f)
        if not _valid_profile(data):
            raise ValueError("invalid devprof profile")
        return data
    except (OSError, ValueError):
        return None


def _persist_profile(data: Dict[str, Any]) -> None:
    path = _profile_path()
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except OSError as e:
        from quokka_tpu import obs

        obs.diag(f"devprof: profile persist failed: {e}")


def _install(prof: Optional[Dict[str, Any]]) -> None:
    """Adopt a profile in-process and mirror the peaks onto gauges."""
    global _peaks, _calib_state
    with _lock:
        if prof is not None:
            _peaks = prof
        _calib_state = "loaded"
    if prof is not None:
        from quokka_tpu import obs

        obs.REGISTRY.gauge("devprof.peak_flops").set(prof["peak_flops_s"])
        obs.REGISTRY.gauge("devprof.peak_bw_bytes").set(
            prof["peak_bw_bytes_s"])


def _ensure_loaded() -> None:
    with _lock:
        if _calib_state == "loaded":
            return
        path = _profile_path()
    # file I/O strictly outside the lock (QK025)
    _install(_load_profile(path))


def peaks() -> Optional[Dict[str, Any]]:
    """The installed calibration profile, lazily loaded from disk; None
    until calibrate() has run for this backend fingerprint."""
    _ensure_loaded()
    with _lock:
        return _peaks


def planning_bw() -> Optional[float]:
    """Bandwidth figure the planner's seconds conversion uses: the observed
    achieved bandwidth once real queries have run, else the calibrated
    peak.  None when uncalibrated (the cost model then stays on its hint
    rung)."""
    p = peaks()
    if p is None:
        return None
    v = p.get("observed_bw_bytes_s")
    if isinstance(v, (int, float)) and math.isfinite(v) and v > 0:
        return float(v)
    return float(p["peak_bw_bytes_s"])


def measured_source_seconds(sig: str) -> Optional[Tuple[float, float]]:
    """(seconds, bytes) recorded for a source signature by a previous run
    of the same scan, or None — the cost model's ``seconds(measured)``
    rung."""
    p = peaks()
    if p is None:
        return None
    row = p.get("sources", {}).get(sig)
    if not isinstance(row, dict):
        return None
    s, b = row.get("seconds"), row.get("bytes")
    if (isinstance(s, (int, float)) and math.isfinite(s) and s > 0
            and isinstance(b, (int, float)) and b >= 0):
        return float(s), float(b)
    return None


def _time_best(fn, reps: int = 3) -> float:
    import time

    fn()  # warm: compile + first dispatch
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def calibrate(force: bool = False) -> Dict[str, Any]:
    """Micro-benchmark peak FLOP/s and memory bandwidth for this backend
    fingerprint, install the profile in-process and persist it.  Idempotent
    per fingerprint unless forced."""
    if not force:
        existing = peaks()
        if existing is not None:
            return existing
    import jax
    import jax.numpy as jnp

    timings: Dict[str, float] = {}
    # peak FLOP/s: square matmul (2*n^3 flops) — the MXU-shaped workload
    n = 256
    a = jnp.ones((n, n), dtype=jnp.float32)
    mm = jax.jit(lambda x, y: x @ y)
    t_mm = _time_best(lambda: mm(a, a).block_until_ready())
    timings["matmul_s"] = t_mm
    peak_flops = (2.0 * n ** 3) / max(t_mm, 1e-9)
    # peak bandwidth: streaming elementwise add (read 2 arrays, write 1)
    m = 1 << 21
    v = jnp.ones((m,), dtype=jnp.float32)
    add = jax.jit(lambda x, y: x + y)
    t_add = _time_best(lambda: add(v, v).block_until_ready())
    timings["stream_s"] = t_add
    peak_bw = (3.0 * 4.0 * m) / max(t_add, 1e-9)

    prof: Dict[str, Any] = {
        "version": _PROFILE_VERSION,
        "fingerprint": _fingerprint(),
        "peak_flops_s": peak_flops,
        "peak_bw_bytes_s": peak_bw,
        "timings_s": timings,
        "sources": {},
    }
    # carry observations forward across re-calibration
    prev = peaks()
    if prev is not None:
        prof["sources"] = dict(prev.get("sources", {}))
        if "observed_bw_bytes_s" in prev:
            prof["observed_bw_bytes_s"] = prev["observed_bw_bytes_s"]
    _install(prof)
    _persist_profile(prof)
    return prof


def ensure_calibrated() -> Dict[str, Any]:
    """Load-or-calibrate once: the bench/smoke entry point.  Honors
    ``QK_DEVPROF_CALIBRATE=0`` (load an existing profile only — the skip
    that keeps unit tests deterministic)."""
    p = peaks()
    if p is not None:
        return p
    if (not enabled()
            or os.environ.get("QK_DEVPROF_CALIBRATE", "1") == "0"):
        return {}
    return calibrate()


def reset() -> None:
    """Forget everything in-process (tests): costs, attribution, profile."""
    global _peaks, _calib_state
    with _lock:
        _costs.clear()
        _attr.clear()
        _prog_disp.clear()
        _qgauges.clear()
        _peaks = None
        _calib_state = "unloaded"


# ---------------------------------------------------------------------------
# Per-program static costs
# ---------------------------------------------------------------------------


def extract_cost(compiled) -> Optional[Dict[str, float]]:
    """Static cost figures from a compiled executable's
    ``cost_analysis()``.  jax returns a list of per-program dicts whose
    keys are XLA metric names (``'flops'``, ``'bytes accessed'``,
    ``"bytes accessedout{}"`` for output bytes); absent/negative entries
    read as 0."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None

    def _num(key: str) -> float:
        v = ca.get(key)
        if isinstance(v, (int, float)) and math.isfinite(float(v)) and v > 0:
            return float(v)
        return 0.0

    return {
        "flops": _num("flops"),
        "bytes": _num("bytes accessed"),
        "out_bytes": _num("bytes accessedout{}"),
    }


def _cost_sidecar(path: str) -> str:
    return path + ".cost.json"


def record_cost(key, compiled, path: Optional[str] = None) -> None:
    """Compile-time hook: ledger the executable's static costs under its
    program signature and persist the sidecar next to the AOT artifact."""
    if not enabled():
        return
    cost = extract_cost(compiled)
    if cost is None:
        return
    with _lock:
        _costs[key] = cost
    from quokka_tpu import obs

    obs.REGISTRY.counter("devprof.programs_costed").inc()
    if path:
        try:
            tmp = f"{_cost_sidecar(path)}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"version": _COST_VERSION, **cost}, f)
            os.replace(tmp, _cost_sidecar(path))
        except OSError as e:
            obs.diag(f"devprof: cost sidecar persist failed: {e}")


def load_cost(key, path: str) -> bool:
    """AOT-cache-hit hook: replay the persisted cost sidecar (no recompile,
    no re-analysis).  Missing/corrupt sidecars (artifacts predating this
    plane) simply leave the program uncosted."""
    if not enabled():
        return False
    with _lock:
        if key in _costs:
            return True
    try:
        with open(_cost_sidecar(path)) as f:
            data = json.load(f)
        if (not isinstance(data, dict)
                or data.get("version") != _COST_VERSION):
            raise ValueError("invalid cost sidecar")
        cost = {k: float(data[k])
                for k in ("flops", "bytes", "out_bytes")}
        if any(not math.isfinite(v) or v < 0 for v in cost.values()):
            raise ValueError("invalid cost figures")
    except (OSError, ValueError, KeyError, TypeError):
        return False
    with _lock:
        _costs[key] = cost
    from quokka_tpu import obs

    obs.REGISTRY.counter("devprof.programs_costed").inc()
    return True


def program_cost(key) -> Optional[Dict[str, float]]:
    with _lock:
        c = _costs.get(key)
        return dict(c) if c else None


def costs_snapshot() -> List[Dict[str, Any]]:
    """Every costed program: signature hash, static figures, arithmetic
    intensity, lifetime dispatch count (for /status and the smoke)."""
    from quokka_tpu.runtime import compileplane

    with _lock:
        items = [(k, dict(c), _prog_disp.get(k, 0))
                 for k, c in _costs.items()]
    out = []
    for key, cost, disp in items:
        out.append({
            "sig": compileplane.key_hash(key),
            "flops": cost["flops"],
            "bytes": cost["bytes"],
            "out_bytes": cost["out_bytes"],
            "intensity": (cost["flops"] / cost["bytes"]
                          if cost["bytes"] > 0 else None),
            "dispatches": disp,
        })
    out.sort(key=lambda r: (-r["flops"], r["sig"]))
    return out


# ---------------------------------------------------------------------------
# Runtime attribution (the dispatch hot path)
# ---------------------------------------------------------------------------


def on_dispatch(key) -> None:
    """Charge one program dispatch's static flops/bytes to the current
    operator (opstats' thread-local marker).  Dict lookups + float adds
    under a short lock — never a device read."""
    if not enabled():
        return
    cost = _costs.get(key)  # GIL-atomic read; missing -> uncosted program
    if cost is None:
        return
    from quokka_tpu.obs import opstats

    cur = getattr(opstats._CUR, "key", None)
    with _lock:
        _prog_disp[key] = _prog_disp.get(key, 0) + 1
        if cur is not None:
            slot = _attr.get((cur[0], cur[1]))
            if slot is None:
                slot = _attr[(cur[0], cur[1])] = [0.0, 0.0, 0.0, 0]
            slot[0] += cost["flops"]
            slot[1] += cost["bytes"]
            slot[2] += cost["out_bytes"]
            slot[3] += 1


# ---------------------------------------------------------------------------
# Roofline math
# ---------------------------------------------------------------------------


def roofline(flops: float, nbytes: float, seconds: Optional[float],
             peak_flops: Optional[float], peak_bw: Optional[float]
             ) -> Dict[str, Optional[float]]:
    """Achieved rates + roofline efficiency for one (cost, seconds) pair.

    Efficiency = achieved / attainable, where attainable =
    ``min(peak_flops, intensity * peak_bw)`` — the classic roofline: a
    memory-bound program (low intensity) is judged against the bandwidth
    ceiling, a compute-bound one against the FLOP ceiling.  A program with
    no flops at all (pure data movement) is judged purely on bandwidth.
    None when nothing is attributable or peaks are uncalibrated."""
    intensity = flops / nbytes if nbytes > 0 else None
    if seconds is None or seconds <= 0 or (flops <= 0 and nbytes <= 0):
        return {"intensity": intensity, "achieved_flops_s": None,
                "achieved_bw_s": None, "efficiency": None}
    af = flops / seconds if flops > 0 else 0.0
    ab = nbytes / seconds if nbytes > 0 else 0.0
    eff: Optional[float] = None
    if peak_flops and peak_bw:
        if flops > 0:
            attainable = peak_flops
            if intensity is not None:
                attainable = min(peak_flops, intensity * peak_bw)
            eff = af / attainable if attainable > 0 else None
        else:
            eff = ab / peak_bw
    return {"intensity": intensity,
            "achieved_flops_s": af if flops > 0 else None,
            "achieved_bw_s": ab if nbytes > 0 else None,
            "efficiency": eff}


# ---------------------------------------------------------------------------
# Snapshot attachment + query lifecycle
# ---------------------------------------------------------------------------


def attach(qid: str, snap: Dict[str, Any]) -> None:
    """Join the query's per-operator attribution against opstats' measured
    wall seconds and attach the ``efficiency`` section to the snapshot
    (explain/bench/status all read it from there).  Also mirrors each
    operator's roofline efficiency onto a per-query gauge for /metrics."""
    if not enabled():
        return
    prof = peaks()
    pf = prof.get("peak_flops_s") if prof else None
    pb = prof.get("peak_bw_bytes_s") if prof else None
    with _lock:
        acc = {k[1]: list(v) for k, v in _attr.items() if k[0] == qid}
    rows: List[Dict[str, Any]] = []
    gnames: List[str] = []
    for op in snap.get("operators", []):
        slot = acc.get(op.get("actor"))
        if slot is None:
            continue
        flops, nbytes, out_b, disp = slot
        rl = roofline(flops, nbytes, op.get("time_s"), pf, pb)
        row = {
            "actor": op.get("actor"),
            "op": op.get("op"),
            "time_s": op.get("time_s"),
            "flops": flops,
            "bytes": nbytes,
            "out_bytes": out_b,
            "program_dispatches": disp,
            **rl,
        }
        row["flagged"] = (rl["efficiency"] is not None
                          and rl["efficiency"] < eff_floor())
        rows.append(row)
        if rl["efficiency"] is not None:
            from quokka_tpu import obs

            name = f"devprof.eff.{qid}.a{op.get('actor')}"
            obs.REGISTRY.gauge(name).set(rl["efficiency"])
            gnames.append(name)
    rows.sort(key=lambda r: -(r["time_s"] or 0.0))
    snap["efficiency"] = {
        "peaks": ({"fingerprint": prof["fingerprint"],
                   "peak_flops_s": pf, "peak_bw_bytes_s": pb}
                  if prof else None),
        "operators": rows,
    }
    if gnames:
        with _lock:
            _qgauges[qid] = sorted(set(_qgauges.get(qid, []) + gnames))


def on_query_finished(qid: str, plan_fp: Optional[str],
                      snap: Dict[str, Any]) -> None:
    """Query-GC hook (rides ``opstats.on_query_gc``): drop the per-query
    attribution + gauges and persist the run's observations — per-source
    scan seconds (the seconds(measured) rung) and the achieved bandwidth
    (the seconds(roofline) conversion factor) — into the calibration
    profile.  Never raises; persistence is best-effort."""
    with _lock:
        acc = {k[1]: list(v) for k, v in _attr.items() if k[0] == qid}
        for k in [k for k in _attr if k[0] == qid]:
            del _attr[k]
        gnames = _qgauges.pop(qid, [])
    if gnames:
        from quokka_tpu import obs

        obs.REGISTRY.remove(*gnames)
    if not enabled():
        return
    prof = peaks()
    if prof is None or not _dir():
        return
    # observations from the final snapshot: input operators carry the
    # source signature their measured cardinalities persist under — the
    # same key cost.source_signature computes at plan time
    sources: Dict[str, Dict[str, float]] = {}
    tot_bytes = tot_s = 0.0
    for op in snap.get("operators", []):
        t = op.get("time_s")
        if isinstance(t, (int, float)) and t > 0:
            slot = acc.get(op.get("actor"))
            if slot is not None:
                tot_bytes += slot[1]
                tot_s += t
            sig = op.get("src_sig")
            if sig and op.get("kind") == "input":
                b = op.get("bytes_in") or 0
                sources[str(sig)] = {"seconds": float(t), "bytes": float(b)}
    if not sources and tot_s <= 0:
        return
    path = _profile_path()
    cur = _load_profile(path) or prof
    merged = dict(cur)
    merged_sources = dict(cur.get("sources", {}))
    for sig, row in sources.items():
        prev = merged_sources.get(sig)
        runs = (prev.get("runs", 0) if isinstance(prev, dict) else 0) + 1
        merged_sources[sig] = {**row, "runs": runs}
    merged["sources"] = merged_sources
    if tot_s > 0 and tot_bytes > 0:
        obs_bw = tot_bytes / tot_s
        prev_bw = merged.get("observed_bw_bytes_s")
        if isinstance(prev_bw, (int, float)) and prev_bw > 0:
            obs_bw = 0.5 * prev_bw + 0.5 * obs_bw
        merged["observed_bw_bytes_s"] = obs_bw
    _install(merged)
    _persist_profile(merged)


def summary() -> Dict[str, Any]:
    """Compact process-level digest for /status."""
    prof = peaks()
    with _lock:
        ncost = len(_costs)
        ndisp = sum(_prog_disp.values())
    return {
        "enabled": enabled(),
        "calibrated": prof is not None,
        "fingerprint": prof["fingerprint"] if prof else None,
        "peak_flops_s": prof["peak_flops_s"] if prof else None,
        "peak_bw_bytes_s": prof["peak_bw_bytes_s"] if prof else None,
        "observed_bw_bytes_s": (prof or {}).get("observed_bw_bytes_s"),
        "programs_costed": ncost,
        "program_dispatches": ndisp,
    }
