"""Memory-plane smoke: the ledger accounts, reconciles, and feeds admission.

    python -m quokka_tpu.obs.mem_smoke          (or: make mem-smoke)

One process, three proofs over a seeded Q3-shaped join+aggregate submitted
through the QueryService:

1. **clean GC** — after the query finishes, the ledger holds ZERO entries
   charged to its query id (no MemLeakError, ``mem.leaked`` counter flat),
   the finish-time footprint snapshot shows a nonzero measured peak, and
   the per-query gauges are gone from the registry (no resurrection);
2. **reconciliation** — a controlled post-GC device transfer (bridge +
   BatchCache, the ledgered choke points) must agree with what
   ``jax.live_arrays()`` actually reports, within ``QK_MEM_RECONCILE``
   (default 10%), both measured as deltas from ``set_baseline()``;
3. **measured admission** — a second submission of the SAME plan must be
   charged the measured ``peak_bytes`` persisted under the plan
   fingerprint, not the reader ``size_hint()`` guess the first run used.

Exit nonzero on any violation, with the observed figures printed.
"""

from __future__ import annotations

import gc
import os
import sys
import tempfile


def _make_tables(tmp: str, seed: int = 20260805):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    n_fact, n_dim = 200_000, 20_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "grp": r.integers(0, 64, n_dim).astype(np.int64),
    })
    fp = os.path.join(tmp, "fact.parquet")
    dp = os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fp, row_group_size=1 << 16)
    pq.write_table(dim, dp)
    return fp, dp


def _query(ctx, fp, dp):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fp)
    dim = ctx.read_parquet(dp)
    return (
        fact.filter(col("flag") < 3)
        .join(dim, left_on="fk", right_on="pk")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def _reconcile_proof(tolerance: float):
    """Controlled residency through the ledgered choke points vs jax's own
    live-array accounting.  The transfer shape is warmed FIRST so the
    baseline window contains data buffers only, not freshly-baked jit
    constants."""
    import numpy as np
    import pyarrow as pa

    from quokka_tpu.obs import memplane
    from quokka_tpu.ops import bridge
    from quokka_tpu.runtime.cache import BatchCache, _batch_nbytes

    r = np.random.default_rng(7)
    table = pa.table({
        "a": r.integers(0, 1 << 40, 300_000).astype(np.int64),
        "b": r.standard_normal(300_000),
    })
    warm = bridge.arrow_to_device(table)  # compiles the pack kernels
    del warm
    gc.collect()

    memplane.LEDGER.set_baseline()
    batch = bridge.arrow_to_device(table)
    cache = BatchCache(owner="memsmoke")
    name = (0, 0, 0, 1, 0, 0)
    cache.put(name, batch)
    rec = memplane.LEDGER.reconcile(tolerance=tolerance)
    tracked = _batch_nbytes(batch)
    cache.gc([name])
    memplane.LEDGER.drop_query("memsmoke")
    del batch
    gc.collect()
    return rec, tracked


def main() -> int:
    from quokka_tpu import QuokkaContext, obs
    from quokka_tpu.obs import memplane
    from quokka_tpu.service import QueryService

    profile_dir = tempfile.mkdtemp(prefix="qk-memprofile-")
    saved = os.environ.get("QK_MEMPROFILE_DIR")
    os.environ["QK_MEMPROFILE_DIR"] = profile_dir
    try:
        with tempfile.TemporaryDirectory(prefix="qk-mem-smoke-") as tmp:
            fp, dp = _make_tables(tmp)
            leaked0 = obs.REGISTRY.snapshot().get("mem.leaked", 0)
            with QueryService(pool_size=2) as svc:
                h1 = svc.submit(_query(QuokkaContext(), fp, dp))
                rows = h1.to_arrow(timeout=600)
                assert rows.num_rows > 0, "smoke query returned no rows"
                qid = h1.query_id
                est1 = h1._s.est_bytes
                plan_fp = h1._s.graph.plan_fp

                # -- proof 1: clean GC ------------------------------------
                mem = h1.memory_stats()
                snap = obs.REGISTRY.snapshot()
                leaked = snap.get("mem.leaked", 0) - leaked0
                entries = memplane.LEDGER.entry_count(qid)
                print(f"mem-smoke: query {qid} peak_bytes="
                      f"{mem['peak_bytes']} live_after_gc="
                      f"{memplane.LEDGER.live_bytes(qid)} "
                      f"leaked_entries={leaked} ledger_entries={entries}")
                if mem["peak_bytes"] <= 0:
                    print("mem-smoke: FAIL — finish-time footprint shows "
                          "zero peak; the runtime tracked nothing",
                          file=sys.stderr)
                    return 1
                if leaked or entries:
                    print(f"mem-smoke: FAIL — {leaked} leaked / {entries} "
                          f"surviving ledger entries after namespace GC",
                          file=sys.stderr)
                    return 1
                if f"mem.live_bytes.{qid}" in snap:
                    print("mem-smoke: FAIL — per-query memory gauges "
                          "survived the namespace GC", file=sys.stderr)
                    return 1

                # -- proof 2: ledger vs jax.live_arrays -------------------
                tol = memplane.reconcile_tolerance()
                rec, tracked = _reconcile_proof(tol)
                print(f"mem-smoke: reconcile ledger={rec['ledger_bytes']} "
                      f"jax={rec['jax_bytes']} drift="
                      f"{rec['drift_frac']:.4f} (tol {tol:.2f}, "
                      f"tracked_batch={tracked})")
                if rec["available"] and not rec["within"]:
                    print(f"mem-smoke: FAIL — ledger drifts "
                          f"{rec['drift_frac']:.1%} from jax.live_arrays() "
                          f"(tolerance {tol:.0%})", file=sys.stderr)
                    return 1

                # -- proof 3: measured admission --------------------------
                measured = memplane.measured_footprint(plan_fp)
                if not measured:
                    print(f"mem-smoke: FAIL — no measured footprint "
                          f"persisted for plan {plan_fp!r} under "
                          f"{profile_dir}", file=sys.stderr)
                    return 1
                h2 = svc.submit(_query(QuokkaContext(), fp, dp))
                est2 = h2._s.est_bytes
                h2.result(timeout=600)
                print(f"mem-smoke: admission est first={est1} "
                      f"second={est2} measured={measured}")
                if est2 != max(int(measured), 1 << 20):
                    print(f"mem-smoke: FAIL — second admission charged "
                          f"{est2}, expected the measured footprint "
                          f"{measured}", file=sys.stderr)
                    return 1
                if est2 >= est1:
                    print(f"mem-smoke: FAIL — measured admission ({est2}) "
                          f"did not beat the size_hint estimate ({est1}) "
                          "on this deliberately tiny plan",
                          file=sys.stderr)
                    return 1
    finally:
        if saved is None:
            os.environ.pop("QK_MEMPROFILE_DIR", None)
        else:
            os.environ["QK_MEMPROFILE_DIR"] = saved
    print("mem-smoke: OK — clean GC, ledger reconciles with jax, second "
          "admission used the measured footprint")
    return 0


if __name__ == "__main__":
    sys.exit(main())
