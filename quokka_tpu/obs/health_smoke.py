"""Health-plane smoke: live progress runs monotone 0→1, /health degrades
under an injected fault and recovers, and none of it costs a host sync.

    python -m quokka_tpu.obs.health_smoke      (or: make health-smoke)

One process, four proofs over two queries submitted through a live
QueryService with its metrics sidecar up:

1. **monotone progress** — polling ``QueryHandle.progress()`` through each
   run yields a nondecreasing fraction that ends pinned at exactly 1.0;
   the first (cold) query estimates on the ``size_hint`` basis, the second
   (same plan, profile now persisted) on the measured ``cardprofile``
   basis and produces at least one finite ETA while live;
2. **endpoints** — ``/status?format=json`` carries the per-session
   progress columns, and ``/history`` has accumulated samples with derived
   counter rates;
3. **degrade + recover** — an injected per-edge skew gauge above
   QK_SKEW_RATIO flips ``/health`` to degraded with ``channel_skew``
   firing (``alert.channel_skew`` counter bumped); clearing the gauge and
   re-evaluating recovers it to ok;
4. **zero added syncs** — the whole run, progress polling included, adds
   ZERO ``shuffle.host_syncs``.

Exit nonzero on any violation, with the observed figures printed.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request


def _make_tables(tmp: str, seed: int = 20260807):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    n_fact, n_dim = 200_000, 20_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "grp": r.integers(0, 64, n_dim).astype(np.int64),
    })
    fp = os.path.join(tmp, "fact.parquet")
    dp = os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fp, row_group_size=1 << 14)
    pq.write_table(dim, dp)
    return fp, dp


def _query(ctx, fp, dp):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fp)
    dim = ctx.read_parquet(dp)
    return (
        fact.filter(col("flag") < 3)
        .join(dim, left_on="fk", right_on="pk")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def _poll_to_done(handle):
    """Poll progress until the query finishes; returns the fraction series
    (including the final snapshot) plus the bases and ETAs seen."""
    fracs, bases, etas = [], set(), []
    while not handle.done:
        p = handle.progress()
        if p is not None:
            fracs.append(p["fraction"])
            bases.add(p["basis"])
            if p["eta_s"] is not None:
                etas.append(p["eta_s"])
        time.sleep(0.01)
    handle.wait(600)
    final = handle.progress()
    if final is not None:
        fracs.append(final["fraction"])
        bases.add(final["basis"])
    return fracs, bases, etas, final


def _fetch(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def main() -> int:  # noqa: C901 — linear proof script, explain_smoke idiom
    env_overrides = {
        # the memory profile must not shortcut admission; the cardinality
        # profile is the thing under test, isolated in a temp dir
        "QK_MEMPROFILE_DIR": "",
        "QK_CARDPROFILE_DIR": tempfile.mkdtemp(prefix="qk-health-card-"),
        # sidecar on an ephemeral port; fast sampler so /history fills
        "QK_METRICS_PORT": "0",
        "QK_HISTORY_INTERVAL_S": "0.2",
    }
    saved = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    def fail(msg: str) -> int:
        sys.stderr.write(f"health-smoke: FAIL — {msg}\n")
        return 1

    try:
        from quokka_tpu import QuokkaContext, obs

        from quokka_tpu.service import QueryService

        with tempfile.TemporaryDirectory(prefix="qk-health-smoke-") as tmp:
            fp, dp = _make_tables(tmp)
            syncs0 = obs.REGISTRY.snapshot().get("shuffle.host_syncs", 0)
            with QueryService(pool_size=2) as svc:
                if svc.metrics_server is None:
                    return fail("metrics sidecar did not start under "
                                "QK_METRICS_PORT=0")
                url = svc.metrics_server.url

                # -- proof 1: monotone 0→1 progress, cold then warm -------
                results = []
                for label in ("cold", "warm"):
                    ctx = QuokkaContext(io_channels=2, exec_channels=2)
                    h = svc.submit(_query(ctx, fp, dp))
                    fracs, bases, etas, final = _poll_to_done(h)
                    if h.error is not None:
                        return fail(f"{label} query failed: {h.error!r}")
                    if len(fracs) < 3:
                        return fail(f"{label} query finished with only "
                                    f"{len(fracs)} progress sample(s) — "
                                    "nothing was observable live")
                    if any(a > b for a, b in zip(fracs, fracs[1:])):
                        return fail(f"{label} fraction series is not "
                                    f"monotone: {fracs}")
                    if fracs[-1] != 1.0:
                        return fail(f"{label} final fraction "
                                    f"{fracs[-1]} != 1.0")
                    results.append((label, fracs, bases, etas, final))
                    print(f"health-smoke: {label} run {len(fracs)} "
                          f"sample(s), basis={sorted(bases)}, "
                          f"max_live={max(fracs[:-1]):.3f}, "
                          f"etas_seen={len(etas)}")
                if "size_hint" not in results[0][2]:
                    return fail("cold run never used the size_hint basis "
                                f"(saw {sorted(results[0][2])})")
                if "cardprofile" not in results[1][2]:
                    return fail("warm run never used the cardprofile basis "
                                "— measured cardinalities did not persist "
                                f"(saw {sorted(results[1][2])})")
                if not any(e >= 0 for e in results[1][3]):
                    return fail("warm run produced no finite ETA")

                # -- proof 2: endpoints -----------------------------------
                st = _fetch(url("/status?format=json"))
                svc_stats = st.get("service") or {}
                rows = svc_stats.get("sessions")
                if rows is None:
                    return fail("/status?format=json carries no service "
                                "sessions block")
                hist = _fetch(url("/history"))
                if len(hist.get("samples") or []) < 2:
                    return fail(f"/history holds "
                                f"{len(hist.get('samples') or [])} "
                                "sample(s); sampler never ran")
                if not hist.get("rates"):
                    return fail("/history derived no counter rates over a "
                                "two-query run")
                print(f"health-smoke: /history {len(hist['samples'])} "
                      f"sample(s), {len(hist['rates'])} rated counter(s)")

                # -- proof 3: degrade + recover ---------------------------
                if _fetch(url("/health"))["status"] != "ok":
                    return fail("baseline /health is not ok: "
                                f"{_fetch(url('/health'))}")
                fired0 = obs.REGISTRY.snapshot().get(
                    "alert.channel_skew", 0)
                fake = "shuffle.skew.qfake.a0-a1"
                obs.REGISTRY.gauge(fake).set(99.0)
                obs.alerts.ENGINE.evaluate_now()
                health = _fetch(url("/health"))
                firing = [f["rule"] for f in health["firing"]]
                if health["status"] != "degraded" \
                        or "channel_skew" not in firing:
                    return fail("injected skew did not degrade /health: "
                                f"{health}")
                fired = obs.REGISTRY.snapshot().get(
                    "alert.channel_skew", 0) - fired0
                if fired != 1:
                    return fail(f"alert.channel_skew counter moved by "
                                f"{fired}, want exactly 1 (edge-triggered)")
                obs.REGISTRY.remove(fake)
                obs.alerts.ENGINE.evaluate_now()
                health = _fetch(url("/health"))
                if health["status"] != "ok":
                    return fail(f"/health did not recover after the fault "
                                f"cleared: {health}")
                print("health-smoke: /health ok -> degraded(channel_skew) "
                      "-> ok, alert counter +1")

                # -- proof 4: zero added host syncs -----------------------
                syncs = obs.REGISTRY.snapshot().get(
                    "shuffle.host_syncs", 0) - syncs0
                print(f"health-smoke: host_syncs delta {syncs}")
                if syncs:
                    return fail(f"the health plane cost {syncs} host "
                                "sync(s) — progress must consume only "
                                "host-side ledger figures")
        print("health-smoke: OK")
        return 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    sys.exit(main())
