"""Device-profiling smoke: the roofline plane closes end-to-end.

    python -m quokka_tpu.obs.devprof_smoke      (or: make devprof-smoke)

One process, five proofs over a seeded Q3-shaped join+aggregate submitted
through the QueryService:

1. **calibrated peaks per fingerprint** — ``devprof.calibrate()``
   persists ``{peak_flops_s, peak_bw_bytes_s}`` under this backend's
   fingerprint and reloads it after a process-state reset; a profile
   carrying a FOREIGN fingerprint is rejected wholesale;
2. **every program costed** — every AOT program the query compiled (the
   whole-stage-fused ones included) carries static flops/bytes figures
   from ``compiled.cost_analysis()``;
3. **finite roofline efficiency per hot operator** — the explain
   snapshot's ``efficiency`` section reports a finite roofline fraction
   for every attributed operator, and the rendered EXPLAIN ANALYZE
   shows the device-efficiency section;
4. **zero added host syncs** — costing + attribution ride the dispatch
   path without a single new ``shuffle.host_syncs``;
5. **seconds-basis planning on the warm re-plan** — a warm variant of
   the query (same dim build side, fresh fact predicate) plans against
   the measured build cardinality AND the calibrated bandwidth: its
   broadcast decision record quotes predicted device seconds, with the
   fresh probe side converting as a literal ``seconds(roofline)`` basis.

Exit nonzero on any violation, with the observed figures printed.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Optional


def _make_tables(tmp: str, seed: int = 20260807):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    n_fact, n_dim = 200_000, 20_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "grp": r.integers(0, 64, n_dim).astype(np.int64),
    })
    fp = os.path.join(tmp, "fact.parquet")
    dp = os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fp, row_group_size=1 << 16)
    pq.write_table(dim, dp)
    return fp, dp


def _query(ctx, fp, dp, flag_lt=3):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fp)
    dim = ctx.read_parquet(dp)
    return (
        fact.filter(col("flag") < flag_lt)
        .join(dim, left_on="fk", right_on="pk")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def _efficiency_violation(snap, rendered: str) -> Optional[str]:
    """Proof 3: attributed operators carry finite roofline figures and the
    rendering surfaces them."""
    import math

    eff = snap.get("efficiency") or {}
    rows = eff.get("operators") or []
    if not rows:
        return ("no operators were attributed any program cost — the "
                "dispatch funnel recorded nothing")
    if not eff.get("peaks"):
        return "efficiency section carries no calibrated peaks"
    for r in rows:
        e = r.get("efficiency")
        if e is None or not math.isfinite(e) or e <= 0:
            return (f"operator a{r['actor']} ({r['op']}) has non-finite "
                    f"roofline efficiency {e!r} despite calibrated peaks")
    if "device efficiency" not in rendered:
        return "rendered EXPLAIN ANALYZE carries no device-efficiency section"
    return None


def main() -> int:  # noqa: C901 — linear proof script, explain_smoke idiom
    devprof_dir = tempfile.mkdtemp(prefix="qk-devprof-")
    env_overrides = {
        # isolate every profile this smoke writes or reads
        "QK_DEVPROF_DIR": devprof_dir,
        "QK_CARDPROFILE_DIR": tempfile.mkdtemp(prefix="qk-cardprofile-"),
        "QK_MEMPROFILE_DIR": "",
        # fresh AOT store: every program compiles (and is costed) this run
        "QUOKKA_AOT_CACHE_DIR": tempfile.mkdtemp(prefix="qk-aot-"),
    }
    saved = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    def fail(msg: str) -> int:
        sys.stderr.write(f"devprof-smoke: FAIL — {msg}\n")
        return 1

    try:
        from quokka_tpu import QuokkaContext, obs
        from quokka_tpu.obs import devprof
        from quokka_tpu.runtime import compileplane
        from quokka_tpu.service import QueryService

        devprof.reset()

        # -- proof 1: calibration persists per fingerprint ----------------
        prof = devprof.ensure_calibrated()
        if not prof:
            return fail("ensure_calibrated produced no profile")
        fpr = prof["fingerprint"]
        path = os.path.join(devprof_dir, f"{fpr}.json")
        if not os.path.exists(path):
            return fail(f"no profile persisted at {path}")
        devprof.reset()
        reloaded = devprof.peaks()
        if not reloaded or reloaded["peak_flops_s"] != prof["peak_flops_s"]:
            return fail("persisted profile did not survive a state reset")
        print(f"devprof-smoke: calibrated {fpr}: "
              f"peak_flops={prof['peak_flops_s']:.3g}/s "
              f"peak_bw={prof['peak_bw_bytes_s']:.3g}B/s")

        # foreign fingerprint rejected wholesale
        foreign = dict(reloaded, fingerprint="tpu-8x-deadbeef")
        with open(path, "w") as f:
            json.dump(foreign, f)
        devprof.reset()
        if devprof.peaks() is not None:
            return fail("a foreign-fingerprint profile was accepted")
        print("devprof-smoke: foreign-fingerprint profile rejected")
        with open(path, "w") as f:
            json.dump(reloaded, f)
        devprof.reset()
        if devprof.peaks() is None:
            return fail("restored profile failed to reload")

        with tempfile.TemporaryDirectory(prefix="qk-devprof-smoke-") as tmp:
            fp, dp = _make_tables(tmp)
            syncs0 = obs.REGISTRY.snapshot().get("shuffle.host_syncs", 0)
            with QueryService(pool_size=2) as svc:
                ctx = QuokkaContext(io_channels=2, exec_channels=2)
                h1 = svc.submit(_query(ctx, fp, dp))
                rows = h1.to_arrow(timeout=600)
                if rows.num_rows <= 0:
                    return fail("smoke query returned no rows")
                snap = h1.explain(as_dict=True)
                if not snap:
                    return fail("no opstats snapshot survived the query GC")
                rendered = h1.explain()
                print(rendered)

                # -- proof 2: every compiled program is costed ------------
                uncosted = [k for k in compileplane.PROGRAMS
                            if devprof.program_cost(k) is None]
                ncost = len(compileplane.PROGRAMS) - len(uncosted)
                if not compileplane.PROGRAMS:
                    return fail("the query compiled no AOT programs")
                if uncosted:
                    return fail(
                        f"{len(uncosted)}/{len(compileplane.PROGRAMS)} "
                        "compiled program(s) carry no static cost figures: "
                        + ", ".join(compileplane.key_hash(k)
                                    for k in uncosted[:5]))
                top = devprof.costs_snapshot()[0]
                print(f"devprof-smoke: {ncost} program(s) costed; "
                      f"heaviest {top['sig']}: flops={top['flops']:.3g} "
                      f"bytes={top['bytes']:.3g} "
                      f"dispatches={top['dispatches']}")

                # -- proof 3: finite roofline efficiency ------------------
                err = _efficiency_violation(snap, rendered)
                if err:
                    return fail(err)
                effs = snap["efficiency"]["operators"]
                print(f"devprof-smoke: roofline efficiency finite for "
                      f"{len(effs)} attributed operator(s), worst "
                      f"{min(r['efficiency'] for r in effs):.2%}")

                # -- proof 4: zero added host syncs -----------------------
                syncs = obs.REGISTRY.snapshot().get("shuffle.host_syncs",
                                                    0) - syncs0
                print(f"devprof-smoke: host_syncs delta {syncs}")
                if syncs:
                    return fail(f"costing + attribution cost {syncs} host "
                                "sync(s) — the plane must never read a "
                                "device value")

                # -- proof 5: warm re-plan decides in seconds -------------
                # a warm VARIANT (different fact predicate): the dim build
                # side keeps its measured cardinality + scan seconds, the
                # probe side's fresh signature has no measured seconds and
                # must convert through the calibrated bandwidth — the
                # decision record quotes a seconds(roofline)-basis figure
                h2 = svc.submit(_query(QuokkaContext(io_channels=2,
                                                     exec_channels=2),
                                       fp, dp, flag_lt=2))
                h2.result(timeout=600)
                snap2 = h2.explain(as_dict=True)
                rendered2 = h2.explain()
                decisions = snap2.get("planner") or []
                seconds_based = [
                    d for d in decisions
                    if "seconds(" in str(d.get("est_s_basis", ""))
                    or "seconds(" in str(d.get("probe_s_basis", ""))]
                if not seconds_based:
                    return fail(
                        "warm re-plan recorded no seconds-basis decision "
                        f"(decisions: {decisions!r})")
                d = seconds_based[0]
                print("devprof-smoke: warm decision "
                      f"{d.get('kind')}: broadcast_s={d.get('broadcast_s')} "
                      f"partition_s={d.get('partition_s')} "
                      f"[{d.get('est_s_basis')}, "
                      f"probe {d.get('probe_s_basis')}]")
                if "seconds(roofline)" not in rendered2:
                    return fail("rendered warm EXPLAIN quotes no "
                                "seconds(roofline)-basis figure")
        print("devprof-smoke: OK — peaks calibrated+persisted (foreign "
              "rejected), every program costed, roofline finite per "
              "operator, zero added host syncs, warm re-plan decided in "
              "predicted seconds")
        return 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    sys.exit(main())
