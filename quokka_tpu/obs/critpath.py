"""Critical-path profiler: turn a merged flight timeline into an answer.

The flight recorder (obs/recorder.py) captures WHAT happened — task
dispatches, batch push/pull edges, compiles, recovery events.  This module
reconstructs WHY a query took as long as it did: it rebuilds the causal
task DAG from the merged timeline, walks the critical path from the last
task back to the query's start, and attributes every second of wall time
to one of the latency buckets the accelerator-query-engine literature
separates (arxiv 2203.01877, 2512.02862):

    compile     XLA backend compiles overlapping the path
    scan_read   parquet decode / reader execution / prefetch waits
    transfer    host<->device bridging, partition pushes, result d2h
    compute     executor kernels (exec./done./source. spans)
    queue_wait  inputs were ready but the task waited for a dispatch slot
    stall       the pipeline itself was starved (task.wait backpressure)
    recovery    replay/exectape tasks + recover.*/chaos overlap
    other       planning, store bookkeeping, unattributed task interior

Buckets PARTITION the analysis window: their sum equals the window's wall
time by construction, so a report whose buckets do not reconcile with the
measured wall clock (within recorder granularity) indicates dropped events
— which the report states explicitly via the recorder's drop counter.

Edges come from the producer/consumer notes the engine attaches to task
events (runtime/engine.py dispatch_task): each task event carries its
``(a, c)`` identity, the output seqs it pushed (``outs``) and, for exec
tasks, the ``(src, [[ch, seq], ...])`` batches it consumed.  A consumer's
data predecessor is whoever produced ``(src, ch, seq)``; tasks on one
channel additionally chain sequentially (executor state is serial per
channel).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

BUCKETS = ("compile", "scan_read", "transfer", "compute",
           "queue_wait", "stall", "recovery", "other")

# span-name prefix -> bucket, for spans nested inside a task's interval.
# "spill." (HBQ spill: d2h copy + checksummed write) is TRANSFER, not
# compute — it moves bytes off-device; since the async spill pool it runs
# on its own thread, so what remains inside task intervals is genuine
# barrier time (flush at checkpoint/recovery boundaries).
_SPAN_BUCKETS = (
    (("reader.", "prefetch"), "scan_read"),
    (("bridge.", "emit.", "push.", "spill.", "count_valid"), "transfer"),
    (("exec.", "done.", "source."), "compute"),
)

# task kinds that ARE recovery work, whole-interval
_RECOVERY_KINDS = ("exectape", "replay")


def _span_bucket(name: str) -> Optional[str]:
    for prefixes, bucket in _SPAN_BUCKETS:
        if name.startswith(prefixes):
            return bucket
    return None


@dataclass
class _Task:
    """One dispatched task reconstructed from a ``task`` event."""

    pid: str
    tid: str
    label: str
    kind: str           # input | exec | exectape | replay
    actor: int
    channel: int
    start: float
    end: float
    q: Optional[str]
    src: Optional[int] = None                 # exec: planned source actor
    ins: List[Tuple[int, int]] = field(default_factory=list)   # (ch, seq)
    outs: List[int] = field(default_factory=list)              # pushed seqs
    critpred: Optional["_Task"] = None
    arrival: Optional[float] = None

    @property
    def dur(self) -> float:
        return self.end - self.start


@dataclass
class CritPath:
    """The analysis result: bucketed wall-time attribution + the path."""

    query: Optional[str]
    wall_s: float
    buckets: Dict[str, float]
    path: List[dict]          # [{label, start_s, dur_s, gap_s, gap_bucket}]
    n_tasks: int
    n_path: int
    dropped: int = 0

    def to_json(self) -> dict:
        return {
            "query": self.query,
            "wall_s": round(self.wall_s, 6),
            "buckets": {k: round(v, 6) for k, v in self.buckets.items()},
            "bucket_sum_s": round(sum(self.buckets.values()), 6),
            "n_tasks": self.n_tasks,
            "n_path": self.n_path,
            "dropped_events": self.dropped,
            "path": self.path,
        }

    def render(self, max_segments: int = 12) -> str:
        head = f"query {self.query}" if self.query else "run"
        lines = [f"==== critical path: {head} ====",
                 f"wall {self.wall_s * 1e3:.1f}ms over {self.n_tasks} "
                 f"task(s), {self.n_path} on the critical path"]
        if self.dropped:
            lines.append(f"WARNING: flight recorder dropped {self.dropped} "
                         "event(s) — attribution is missing the earliest "
                         "tail (raise QK_TRACE_BUFFER)")
        wall = max(self.wall_s, 1e-12)
        for k in BUCKETS:
            v = self.buckets.get(k, 0.0)
            if v <= 0:
                continue
            bar = "#" * max(1, int(30 * v / wall))
            lines.append(f"  {k:<10} {v * 1e3:>9.1f}ms {100 * v / wall:>5.1f}%  {bar}")
        segs = sorted(self.path, key=lambda s: -(s["dur_s"] + s["gap_s"]))
        segs = segs[:max_segments]
        keep = {id(s) for s in segs}
        if segs:
            lines.append(f"top path segments (of {len(self.path)}):")
        for s in self.path:
            if id(s) not in keep:
                continue
            gap = (f"  [+{s['gap_s'] * 1e3:.1f}ms {s['gap_bucket']}]"
                   if s["gap_s"] > 0 else "")
            lines.append(f"  {s['label']:<36} {s['dur_s'] * 1e3:>8.1f}ms{gap}")
        lines.append("=" * 33)
        return "\n".join(lines)


def _clip_total(intervals: List[Tuple[float, float]],
                lo: float, hi: float) -> float:
    """Total coverage of [lo, hi] by the (possibly overlapping) intervals."""
    clipped = sorted((max(lo, s), min(hi, e)) for s, e in intervals
                     if e > lo and s < hi)
    total = 0.0
    cur = lo
    for s, e in clipped:
        s = max(s, cur)
        if e > s:
            total += e - s
            cur = e
    return total


def _parse_tasks(merged: Sequence[dict],
                 query: Optional[str]) -> List[_Task]:
    tasks: List[_Task] = []
    for d in merged:
        if d["kind"] != "task":
            continue
        args = d.get("args") or {}
        q = args.get("q")
        if query is not None and q != query:
            continue
        a, c = args.get("a"), args.get("c")
        if a is None or c is None:
            continue  # pre-enrichment event stream: no DAG identity
        tasks.append(_Task(
            pid=d["pid"], tid=d["tid"], label=d["name"],
            kind=args.get("k", d["name"].split(":")[0] or "exec"),
            actor=int(a), channel=int(c),
            start=d["ts"] - d["dur"], end=d["ts"], q=q,
            src=args.get("src"),
            ins=[(int(ch), int(s)) for ch, s in (args.get("in") or [])],
            outs=[int(s) for s in (args.get("outs") or [])],
        ))
    tasks.sort(key=lambda t: t.end)
    return tasks


def _link(tasks: List[_Task]) -> None:
    """Fill ``critpred``/``arrival`` on every task: the latest-finishing
    predecessor among (a) the previous task on the same channel and (b) the
    producers of every batch this task consumed."""
    producers: Dict[Tuple[int, int, int], _Task] = {}
    last_on_channel: Dict[Tuple[str, int, int], _Task] = {}
    for t in tasks:  # already end-ordered
        preds: List[_Task] = []
        chain = last_on_channel.get((t.pid, t.actor, t.channel))
        if chain is not None:
            preds.append(chain)
        if t.src is not None:
            for ch, seq in t.ins:
                p = producers.get((int(t.src), ch, seq))
                if p is not None and p is not t:
                    preds.append(p)
        if preds:
            t.critpred = max(preds, key=lambda p: p.end)
            t.arrival = t.critpred.end
        last_on_channel[(t.pid, t.actor, t.channel)] = t
        for seq in t.outs:
            producers.setdefault((t.actor, t.channel, seq), t)


def _task_interior(t: _Task, spans: List[Tuple[float, float, str]],
                   compiles: List[Tuple[float, float]],
                   buckets: Dict[str, float],
                   lo: float, hi: float) -> None:
    """Attribute one on-path task's interior, CLIPPED to [lo, hi] — the
    portion of the task not already covered by earlier path segments
    (cross-process chains can overlap in time; attributing overlap twice
    would break the buckets-partition-the-window invariant).  Recovery
    tasks count whole; others split by their nested spans with a
    covered-until watermark (a nested span's time goes to whichever span
    started first), compile events claim what the spans left, and the
    remainder is ``other``."""
    if hi <= lo:
        return  # fully shadowed by an already-attributed segment
    dur = hi - lo
    if t.kind in _RECOVERY_KINDS:
        buckets["recovery"] += dur
        return
    covered = lo
    accounted = 0.0
    marks: List[Tuple[float, float, str]] = [
        (s, e, _span_bucket(name) or "other")
        for (s, e, name) in spans
        if e > lo - 1e-9 and s < hi + 1e-9
    ]
    marks.sort()
    for s, e, bucket in marks:
        s = max(s, covered, lo)
        e = min(e, hi)
        if e > s:
            buckets[bucket] += e - s
            accounted += e - s
            covered = max(covered, e)
    comp = min(_clip_total(compiles, lo, hi),
               max(0.0, dur - accounted))
    buckets["compile"] += comp
    buckets["other"] += max(0.0, dur - accounted - comp)


def analyze(merged: Sequence[dict],
            query: Optional[str] = None,
            window: Optional[Tuple[float, float]] = None,
            dropped: int = 0) -> Optional[CritPath]:
    """Merged-timeline dicts (obs.merge_streams output) -> CritPath, or
    None when the stream holds no DAG-enriched task events (recorder off,
    or an old stream).  ``window`` widens/narrows the analysis to an
    externally measured [t0, t1]; buckets partition exactly that window."""
    tasks = _parse_tasks(merged, query)
    if not tasks:
        return None
    if query is None:
        # majority query: profile the dominant stream, ignore neighbors
        by_q: Dict[Optional[str], int] = {}
        for t in tasks:
            by_q[t.q] = by_q.get(t.q, 0) + 1
        query = max(by_q, key=lambda k: by_q[k])
        if query is not None:
            tasks = [t for t in tasks if t.q == query]
    _link(tasks)

    terminal = max(tasks, key=lambda t: t.end)
    chain: List[_Task] = []
    cur: Optional[_Task] = terminal
    seen = set()
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        chain.append(cur)
        cur = cur.critpred
    chain.reverse()

    t0 = window[0] if window else min(t.start for t in tasks)
    t1 = window[1] if window else terminal.end
    t0 = min(t0, chain[0].start)
    t1 = max(t1, terminal.end)

    # supporting events, indexed once
    spans_by_thread: Dict[Tuple[str, str], List[Tuple[float, float, str]]] = {}
    compiles_by_pid: Dict[str, List[Tuple[float, float]]] = {}
    recov_by_pid: Dict[str, List[Tuple[float, float]]] = {}
    waits: List[Tuple[float, int, int]] = []   # (ts, actor, channel)
    admits: List[Tuple[float, float]] = []     # [submit_ts, admit_ts]
    pending_submit: Dict[str, float] = {}
    for d in merged:
        kind = d["kind"]
        if kind == "span":
            spans_by_thread.setdefault((d["pid"], d["tid"]), []).append(
                (d["ts"] - d["dur"], d["ts"], d["name"]))
        elif kind == "compile":
            compiles_by_pid.setdefault(d["pid"], []).append(
                (d["ts"] - d["dur"], d["ts"]))
        elif kind.startswith(("recover", "chaos")):
            recov_by_pid.setdefault(d["pid"], []).append(
                (d["ts"] - max(d["dur"], 0.001), d["ts"]))
        elif kind == "task.wait":
            args = d.get("args") or {}
            if query is None or args.get("q") in (None, query):
                waits.append((d["ts"], args.get("a"), args.get("c")))
        elif kind == "service.submit" and d["name"] == query:
            pending_submit[d["name"]] = d["ts"]
        elif kind == "service.admit" and d["name"] == query:
            sub = pending_submit.pop(d["name"], None)
            if sub is not None:
                admits.append((sub, d["ts"]))

    buckets: Dict[str, float] = {k: 0.0 for k in BUCKETS}
    path_out: List[dict] = []
    prev_end = t0
    for t in chain:
        gap_bucket = ""
        gap = t.start - prev_end
        if gap > 0:
            pid_comp = compiles_by_pid.get(t.pid, [])
            comp = _clip_total(pid_comp, prev_end, t.start)
            buckets["compile"] += comp
            rec = min(_clip_total(recov_by_pid.get(t.pid, []),
                                  prev_end, t.start), gap - comp)
            buckets["recovery"] += rec
            adm = min(_clip_total(admits, prev_end, t.start),
                      gap - comp - rec)
            buckets["queue_wait"] += adm
            rest = gap - comp - rec - adm
            stalled = any(prev_end <= ts <= t.start
                          and (a is None or a == t.actor)
                          for ts, a, c in waits)
            if t.critpred is None and not admits:
                # leading edge: planning/lowering before the first task
                gap_bucket = "startup(other)"
                buckets["other"] += rest
            elif stalled:
                gap_bucket = "stall"
                buckets["stall"] += rest
            else:
                gap_bucket = "queue_wait"
                buckets["queue_wait"] += rest
        _task_interior(t, spans_by_thread.get((t.pid, t.tid), []),
                       compiles_by_pid.get(t.pid, []), buckets,
                       max(t.start, prev_end), t.end)
        path_out.append({
            "label": t.label,
            "start_s": round(t.start - t0, 6),
            "dur_s": round(t.dur, 6),
            "gap_s": round(max(0.0, gap), 6),
            "gap_bucket": gap_bucket,
        })
        prev_end = max(prev_end, t.end)
    buckets["other"] += max(0.0, t1 - prev_end)  # trailing drain/teardown

    return CritPath(query=query, wall_s=t1 - t0, buckets=buckets,
                    path=path_out, n_tasks=len(tasks), n_path=len(chain),
                    dropped=dropped)


def summarize_queries(merged: Sequence[dict],
                      max_queries: int = 4) -> List[CritPath]:
    """Per-query critical paths for a merged timeline (stall dumps append
    these): the busiest ``max_queries`` queries, busiest first."""
    counts: Dict[str, int] = {}
    for d in merged:
        if d["kind"] == "task":
            q = (d.get("args") or {}).get("q")
            if q is not None:
                counts[q] = counts.get(q, 0) + 1
    out: List[CritPath] = []
    for q in sorted(counts, key=lambda k: -counts[k])[:max_queries]:
        cp = analyze(merged, query=q)
        if cp is not None:
            out.append(cp)
    return out


class profile:
    """``with critpath.profile() as p: run()`` — profile exactly this
    window of the process-local flight recorder and analyze it on exit
    (``p.result`` is the CritPath, None when the recorder was off)."""

    def __init__(self, query: Optional[str] = None):
        self.query = query
        self.result: Optional[CritPath] = None

    def __enter__(self) -> "profile":
        from quokka_tpu.obs import recorder

        self._rec = recorder.RECORDER
        self._since = self._rec.record("critpath.begin", "")
        self._drop0 = self._rec.dropped_total
        self._t0 = time.time()
        return self

    def __exit__(self, *exc) -> bool:
        self._t1 = time.time()
        if exc and exc[0] is not None:
            return False
        from quokka_tpu.obs import merge

        evs = self._rec.snapshot(since=self._since)
        merged = merge.merge_streams({"local": evs})
        self.result = analyze(
            merged, query=self.query, window=(self._t0, self._t1),
            dropped=max(0, self._rec.dropped_total - self._drop0))
        return False
