"""Per-operator runtime statistics: the EXPLAIN ANALYZE plane's ledger.

The obs stack attributes a query's seconds to buckets (``obs/critpath.py``)
and its bytes to allocation sites (``obs/memplane.py``) but was blind at the
operator level: nothing recorded rows in/out, selectivity, padded-vs-live
waste, or per-channel skew, so a slow join could be *timed* but not
*explained*.  This module closes that gap with a per-(query, actor, channel)
statistics ledger fed from the engine's existing choke points:

- ``Engine.handle_input_task`` reports each scan batch (raw reader rows,
  post-predicate rows, bytes, padded length);
- ``Engine.handle_exec_task`` reports consumed batches and emitted rows per
  dispatch, and exposes a thread-local *current operator* so executors can
  annotate domain figures (join build/probe sizes) without knowing their
  (query, actor, channel) identity;
- ``Engine.push`` reports delivered rows per (source, target, channel) on
  every exchange edge — the per-channel histograms the skew report reads;
- ``Engine.dispatch_task`` reports wall seconds per completed dispatch, so
  operators carry a critical-path time share.

ZERO new device syncs: a host-known ``batch.nrows`` lands as an int; a
device-resolved count rides the batch's ``nrows_dev`` scalar (whose async
d2h copy ``note_count`` already started) onto a pending list, resolved with
``int(dev)`` at the engine's metric-flush cadence — the exact
``EngineMetrics`` discipline.  Shuffle-smoke's ``host_syncs==0`` gate stays
green.

Closing the loop (the memplane pattern): ``on_query_gc`` — called from
``TaskGraph.cleanup`` — persists measured cardinalities per plan fingerprint
under ``<cache>/cardprofile/`` (atomic tmp+replace, max-merged, a corrupt or
foreign-fingerprint profile ignored wholesale).  ``service/admission.py``
charges the measured source bytes instead of reader ``size_hint()`` guesses
on the next submit of the same plan shape, ``ops/strategy.calibrate()``
sizes its probes from measured rows, and the size_hint-vs-actual gap lands
on the ``opstats.size_hint_drift_bytes`` counter.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_PROFILE_VERSION = 1
_TOP_N = 5

# per-operator integer fields every record carries
_FIELDS = ("rows_in", "rows_out", "bytes_in", "bytes_out", "batches_in",
           "batches_out", "dispatches", "padded_in", "rows_unknown")


def skew_ratio_threshold() -> float:
    """``QK_SKEW_RATIO``: max/mean channel-row ratio above which an exchange
    edge is flagged skewed (default 2.0; must exceed 1.0)."""
    try:
        return max(1.0, float(os.environ.get("QK_SKEW_RATIO", 2.0)))
    except ValueError:
        return 2.0


# thread-local current-operator marker: the engine sets it around
# ``executor.execute`` so an executor can report domain figures (join
# build/probe rows) without threading its (query, actor, channel) identity
# through every call signature
_CUR = threading.local()


def note(**figures) -> None:
    """Executor-side annotation onto the current operator's record (no-op
    outside a dispatch, or for an unregistered query).  Values accumulate:
    ``note(join_build_rows=n)`` twice records the sum."""
    key = getattr(_CUR, "key", None)
    if key is not None:
        OPSTATS._note(key, figures)


class OpStats:
    """Process-wide operator-statistics ledger.  All mutation is under one
    lock (the per-call work is a few dict increments); device-count scalars
    go to a pending list and resolve to ints at flush/snapshot time."""

    def __init__(self):
        self._lock = threading.Lock()
        # query_id -> {"actors": {aid: {...}}, "plan_fp", "size_hint_bytes",
        #              "t0"} — a query records ONLY while registered here, so
        # a straggler report after on_query_gc can never resurrect state
        self._plans: Dict[str, dict] = {}
        # (query_id, actor, channel) -> {field: int}
        self._ops: Dict[Tuple[str, int, int], Dict[str, int]] = {}
        # (query_id, actor, channel) -> wall seconds across dispatches
        self._time: Dict[Tuple[str, int, int], float] = {}
        # (query_id, src_actor, tgt_actor) -> {tgt_channel: rows}
        self._edges: Dict[Tuple[str, int, int], Dict[int, int]] = {}
        # (query_id, actor, channel) -> executor-noted domain figures
        self._notes: Dict[Tuple[str, int, int], Dict[str, int]] = {}
        # deferred device counts: ("op", key, field, dev) / ("edge", key, dev)
        self._pending: List[tuple] = []
        # query_id -> per-query gauge names created (GC'd in on_query_gc)
        self._gauges: Dict[str, List[str]] = {}
        # query_id -> worst edge skew ratio seen; the global shuffle.skew
        # gauge is the max over LIVE queries (recomputed at GC so a
        # /health skew alert clears without a process restart)
        self._skew_worst: Dict[str, float] = {}
        # most recently finished query's snapshot (what bench reads after a
        # one-shot run's cleanup)
        self._last: Optional[dict] = None

    # -- plan registration ---------------------------------------------------
    def register_plan(self, graph, op_names: Optional[Dict[int, str]] = None
                      ) -> None:
        """Capture a query's topology host-side (actor kinds, channel
        counts, targets, reader size hints).  Idempotent; a graph without a
        query_id (distributed worker shard of a foreign query) records
        under its shipped id like any other."""
        qid = getattr(graph, "query_id", None)
        if qid is None:
            return
        with self._lock:
            plan = self._plans.get(qid)
            if plan is not None:
                if op_names:
                    for aid, name in op_names.items():
                        if aid in plan["actors"]:
                            plan["actors"][aid]["op"] = name
                return
            actors: Dict[int, dict] = {}
            hint_total = 0
            for aid, info in graph.actors.items():
                ent = {
                    "kind": info.kind,
                    "op": (op_names or {}).get(aid) or _actor_op_name(info),
                    "channels": int(getattr(info, "channels", 1) or 1),
                    "targets": sorted(getattr(info, "targets", {}) or {}),
                    "stage": int(getattr(info, "stage", 0) or 0),
                }
                if info.kind == "input":
                    with contextlib.suppress(Exception):
                        h = int(info.reader.size_hint() or 0)
                        if h > 0:
                            ent["size_hint_bytes"] = h
                            hint_total += h
                    sig = getattr(info, "src_sig", None)
                    if sig:
                        # plan-independent scan identity: cardprofile
                        # persistence keys this scan's measured figures
                        # under it (planner/cost.py reads them back)
                        ent["src_sig"] = sig
                actors[aid] = ent
            self._plans[qid] = {
                "actors": actors,
                "plan_fp": getattr(graph, "plan_fp", None),
                "size_hint_bytes": hint_total,
                "t0": time.time(),
                # plan-time decisions (planner/decide.py), attached to the
                # graph at lowering; runtime adaptations append here
                "planner": list(getattr(graph, "planner_decisions", None)
                                or []),
            }

    def note_adaptation(self, qid: Optional[str], rec: dict) -> None:
        """Engine-side: append a runtime re-optimization record (skew
        trigger fired, exchange re-routed) to the query's planner-decision
        log so explain() shows plan-time choices and runtime adaptations in
        one section.  No-op for an unregistered query."""
        if qid is None:
            return
        with self._lock:
            plan = self._plans.get(qid)
            if plan is None:
                return
            plan.setdefault("planner", []).append(dict(rec))

    # -- hot-path recording (engine choke points) ----------------------------
    def _rec(self, key: Tuple[str, int, int]) -> Dict[str, int]:
        r = self._ops.get(key)
        if r is None:
            r = self._ops[key] = dict.fromkeys(_FIELDS, 0)
        return r

    def _add_rows(self, key, field: str, rows) -> None:
        """caller holds the lock.  rows: int (host-known), device scalar
        (deferred), or None (unknown without a sync: counted, never synced)."""
        if rows is None:
            self._rec(key)["rows_unknown"] += 1
        elif isinstance(rows, int):
            self._rec(key)[field] += rows
        else:
            self._pending.append(("op", key, field, rows))

    def scan(self, qid: Optional[str], actor: int, channel: int,
             rows_raw, rows_out, nbytes: int, padded: int) -> None:
        """One source batch: ``rows_raw`` pre-predicate (what the reader
        produced — reconciles against the source's own row count),
        ``rows_out`` post-predicate (what entered the pipeline)."""
        if qid is None:
            return
        with self._lock:
            if qid not in self._plans:
                return
            key = (qid, actor, channel)
            r = self._rec(key)
            r["dispatches"] += 1
            r["batches_in"] += 1
            r["batches_out"] += 1
            r["bytes_in"] += int(nbytes)
            r["bytes_out"] += int(nbytes)
            r["padded_in"] += int(padded)
            self._add_rows(key, "rows_in", rows_raw)
            self._add_rows(key, "rows_out", rows_out)

    def exec_in(self, qid: Optional[str], actor: int, channel: int,
                batches) -> None:
        """Batches a dispatch is about to consume (host-side metadata only)."""
        if qid is None:
            return
        rows_int = 0
        devs = []
        nbytes = 0
        padded = 0
        unknown = 0
        from quokka_tpu.runtime.cache import _batch_nbytes

        for b in batches:
            if b.nrows is not None:
                rows_int += b.nrows
            elif b.nrows_dev is not None:
                devs.append(b.nrows_dev)
            else:
                unknown += 1
            nbytes += _batch_nbytes(b)
            padded += b.padded_len
        with self._lock:
            if qid not in self._plans:
                return
            key = (qid, actor, channel)
            r = self._rec(key)
            r["dispatches"] += 1
            r["batches_in"] += len(batches)
            r["bytes_in"] += nbytes
            r["padded_in"] += padded
            r["rows_in"] += rows_int
            r["rows_unknown"] += unknown
            for dev in devs:
                self._pending.append(("op", key, "rows_in", dev))

    def exec_out(self, qid: Optional[str], actor: int, channel: int,
                 rows_out) -> None:
        """Rows a dispatch emitted (int, device scalar, or 0 for no-emit)."""
        if qid is None:
            return
        with self._lock:
            if qid not in self._plans:
                return
            key = (qid, actor, channel)
            if not (isinstance(rows_out, int) and rows_out == 0):
                self._rec(key)["batches_out"] += 1
            self._add_rows(key, "rows_out", rows_out)

    def edge(self, qid: Optional[str], src: int, tgt: int, tgt_ch: int,
             rows) -> None:
        """Rows delivered on an exchange edge's target channel — the
        per-channel histogram the skew report is computed from."""
        if qid is None or rows is None:
            return
        with self._lock:
            if qid not in self._plans:
                return
            if isinstance(rows, int):
                d = self._edges.setdefault((qid, src, tgt), {})
                d[tgt_ch] = d.get(tgt_ch, 0) + rows
            else:
                self._pending.append(("edge", (qid, src, tgt, tgt_ch), rows))

    def dispatch_time(self, qid: Optional[str], actor: int, channel: int,
                      dur_s: float) -> None:
        if qid is None:
            return
        with self._lock:
            if qid not in self._plans:
                return
            key = (qid, actor, channel)
            self._time[key] = self._time.get(key, 0.0) + float(dur_s)

    def _note(self, key: Tuple[str, int, int], figures: Dict[str, int]
              ) -> None:
        with self._lock:
            if key[0] not in self._plans:
                return
            d = self._notes.setdefault(key, {})
            for name, v in figures.items():
                with contextlib.suppress(TypeError, ValueError):
                    d[name] = d.get(name, 0) + int(v)

    @contextlib.contextmanager
    def current_op(self, qid: Optional[str], actor: int, channel: int):
        """Engine-side: marks the operator executing on this thread so
        ``note()`` calls from inside the executor attribute correctly."""
        if qid is None:
            yield
            return
        prev = getattr(_CUR, "key", None)
        _CUR.key = (qid, actor, channel)
        try:
            yield
        finally:
            _CUR.key = prev

    # -- deferred device-count resolution ------------------------------------
    def resolve_pending(self) -> None:
        """Turn queued device scalars into ints (their async host copies
        have long landed by the flush cadence) and fold them in.  A scalar
        that fails to resolve is dropped — diagnostics never raise."""
        with self._lock:
            pend, self._pending = self._pending, []
        if not pend:
            return
        resolved = []
        for ent in pend:
            with contextlib.suppress(Exception):
                if ent[0] == "op":
                    resolved.append(("op", ent[1], ent[2], int(ent[3])))
                else:
                    resolved.append(("edge", ent[1], int(ent[2])))
        with self._lock:
            for ent in resolved:
                if ent[0] == "op":
                    _, key, field, n = ent
                    if key[0] in self._plans:
                        self._rec(key)[field] += n
                else:
                    _, (qid, src, tgt, ch), n = ent
                    if qid in self._plans:
                        d = self._edges.setdefault((qid, src, tgt), {})
                        d[ch] = d.get(ch, 0) + n

    # -- snapshots / rendering ----------------------------------------------
    def snapshot(self, qid: str, top_n: int = _TOP_N) -> Optional[dict]:
        """The query's full operator report (operators, exchange edges with
        skew figures, top-N hot operators).  None for an unregistered id.
        Also refreshes the per-query ``opstats.*``/``shuffle.skew.*`` gauges
        (created here, GC'd in ``on_query_gc``)."""
        self.resolve_pending()
        thresh = skew_ratio_threshold()
        with self._lock:
            plan = self._plans.get(qid)
            if plan is None:
                last = self._last
                return last if last and last.get("query_id") == qid else None
            snap = self._render_locked(qid, plan, thresh, top_n)
        self._export_gauges(qid, snap)
        # device-efficiency join (obs/devprof.py): static program costs vs
        # the measured per-operator seconds above — outside the lock, no
        # device reads
        from quokka_tpu.obs import devprof

        devprof.attach(qid, snap)
        return snap

    def _render_locked(self, qid: str, plan: dict, thresh: float,
                       top_n: int) -> dict:
        total_time = 0.0
        per_actor: Dict[int, dict] = {}
        for (q, aid, ch), r in self._ops.items():
            if q != qid:
                continue
            agg = per_actor.setdefault(aid, dict.fromkeys(_FIELDS, 0))
            for f in _FIELDS:
                agg[f] += r[f]
        times: Dict[int, float] = {}
        for (q, aid, ch), t in self._time.items():
            if q == qid:
                times[aid] = times.get(aid, 0.0) + t
                total_time += t
        notes: Dict[int, Dict[str, int]] = {}
        for (q, aid, ch), d in self._notes.items():
            if q == qid:
                agg = notes.setdefault(aid, {})
                for name, v in d.items():
                    agg[name] = agg.get(name, 0) + v
        operators = []
        for aid in sorted(plan["actors"]):
            ent = plan["actors"][aid]
            agg = per_actor.get(aid, dict.fromkeys(_FIELDS, 0))
            t = times.get(aid, 0.0)
            op = {
                "actor": aid,
                "op": ent["op"],
                "kind": ent["kind"],
                "channels": ent["channels"],
                "targets": ent["targets"],
                "stage": ent["stage"],
                **agg,
                "time_s": round(t, 6),
                "time_share": round(t / total_time, 4) if total_time else 0.0,
            }
            if agg["rows_in"]:
                op["selectivity"] = round(agg["rows_out"] / agg["rows_in"], 6)
            if agg["padded_in"]:
                # bucket-ladder waste: padded slots carried vs live rows
                op["pad_waste"] = round(
                    max(0.0, 1.0 - agg["rows_in"] / agg["padded_in"]), 4)
            if ent.get("size_hint_bytes"):
                op["size_hint_bytes"] = ent["size_hint_bytes"]
            if ent.get("src_sig"):
                op["src_sig"] = ent["src_sig"]
            if aid in notes:
                op.update(notes[aid])
            operators.append(op)
        edges = []
        for (q, src, tgt), chd in sorted(self._edges.items()):
            if q != qid or not chd:
                continue
            rows = [chd.get(c, 0)
                    for c in range(plan["actors"][tgt]["channels"])] \
                if tgt in plan["actors"] else list(chd.values())
            total = sum(rows)
            mean = total / len(rows) if rows else 0.0
            mx = max(rows) if rows else 0
            ratio = (mx / mean) if mean > 0 else 1.0
            edges.append({
                "edge": f"a{src}->a{tgt}",
                "src": src,
                "tgt": tgt,
                "channels": len(rows),
                "rows_total": total,
                "rows_max": mx,
                "rows_mean": round(mean, 2),
                "skew_ratio": round(ratio, 4),
                "skewed": bool(len(rows) > 1 and mean > 0
                               and ratio >= thresh),
                "channel_rows": rows,
            })
        hot = sorted(operators,
                     key=lambda o: (o["time_s"], o["rows_out"]),
                     reverse=True)[:top_n]
        rows_unknown = sum(o["rows_unknown"] for o in operators)
        return {
            "query_id": qid,
            "plan_fp": plan.get("plan_fp"),
            "wall_s": round(time.time() - plan["t0"], 6),
            "time_s": round(total_time, 6),
            "size_hint_bytes": plan.get("size_hint_bytes", 0),
            "skew_threshold": thresh,
            "operators": operators,
            "edges": edges,
            "top_operators": [
                {"actor": o["actor"], "op": o["op"], "time_s": o["time_s"],
                 "time_share": o["time_share"], "rows_out": o["rows_out"]}
                for o in hot],
            "rows_unknown": rows_unknown,
            # plan-time choices + runtime adaptations, with the figures
            # that drove them (explain's "planner decisions" section)
            "planner": [dict(d) for d in plan.get("planner") or []],
        }

    def _export_gauges(self, qid: str, snap: dict) -> None:
        """Per-query gauge twins (rows totals + per-edge skew ratios),
        created on first snapshot, names remembered for on_query_gc."""
        from quokka_tpu import obs

        pairs = [
            (f"opstats.rows_in.{qid}",
             sum(o["rows_in"] for o in snap["operators"])),
            (f"opstats.rows_out.{qid}",
             sum(o["rows_out"] for o in snap["operators"])),
        ]
        worst = 0.0
        for e in snap["edges"]:
            pairs.append(
                (f"shuffle.skew.{qid}.{e['src']}-{e['tgt']}",
                 e["skew_ratio"]))
            worst = max(worst, e["skew_ratio"])
        with self._lock:
            if qid not in self._plans:
                return  # GC'd between render and export: do not resurrect
            self._gauges[qid] = [name for name, _ in pairs]
            self._skew_worst[qid] = max(self._skew_worst.get(qid, 0.0),
                                        worst)
            live_worst = max(self._skew_worst.values(), default=0.0)
        for name, value in pairs:
            obs.REGISTRY.gauge(name).set(value)
        # max over LIVE queries, not a process-lifetime ratchet: the gauge
        # falls back to 0 once the skewed query GCs (on_query_gc recomputes)
        obs.REGISTRY.gauge("shuffle.skew").set(live_worst)

    def top_operator(self, qid: str) -> Optional[str]:
        """One-line hottest-operator label for /status (non-creating; falls
        back to the stashed snapshot for the just-finished query)."""
        with self._lock:
            plan = self._plans.get(qid)
            if plan is None:
                last = self._last
                if not (last and last.get("query_id") == qid):
                    return None
                hot = last.get("top_operators") or []
                top = hot[0] if hot else None
            else:
                top = None
                best = (-1.0, -1)
                for (q, aid, ch), r in self._ops.items():
                    if q != qid:
                        continue
                    score = (self._time.get((q, aid, ch), 0.0), r["rows_out"])
                    if score > best:
                        best = score
                        ent = plan["actors"].get(aid, {})
                        top = {"actor": aid, "op": ent.get("op", "?"),
                               "time_s": score[0], "rows_out": r["rows_out"]}
        if top is None:
            return None
        return (f"{top['op']}(a{top['actor']}) "
                f"{top['time_s']:.3f}s rows={top['rows_out']}")

    def last_finished(self) -> Optional[dict]:
        """The most recently GC'd query's snapshot (what bench.py reads
        after a one-shot run's cleanup)."""
        with self._lock:
            return self._last

    def progress_view(self, qid: str) -> Optional[dict]:
        """The HOST-SIDE figures the progress estimator consumes — plan
        fingerprint, start time, reader size-hint total, scanned source
        bytes/rows so far, and per-exec-operator ``rows_out`` keyed the way
        the cardinality profile keys them (``a<aid>:<op>``).  Deliberately
        skips the pending device scalars: a progress poll must never force
        a device sync, so a not-yet-flushed device count simply isn't
        visible until the engine's next metric-flush cadence.  None for an
        unregistered query id."""
        with self._lock:
            plan = self._plans.get(qid)
            if plan is None:
                return None
            scanned_bytes = 0
            scanned_rows = 0
            rows_out: Dict[int, int] = {}
            for (q, aid, ch), r in self._ops.items():
                if q != qid:
                    continue
                ent = plan["actors"].get(aid)
                if ent is not None and ent["kind"] == "input":
                    scanned_bytes += r["bytes_out"]
                    scanned_rows += r["rows_out"]
                else:
                    rows_out[aid] = rows_out.get(aid, 0) + r["rows_out"]
            return {
                "query_id": qid,
                "plan_fp": plan.get("plan_fp"),
                "t0": plan["t0"],
                "size_hint_bytes": plan.get("size_hint_bytes", 0),
                "scanned_bytes": scanned_bytes,
                "scanned_rows": scanned_rows,
                "op_rows_out": {
                    f"a{aid}:{plan['actors'][aid]['op']}": n
                    for aid, n in rows_out.items()
                    if aid in plan["actors"]
                },
            }

    def live_queries(self) -> list:
        """Query ids with a registered plan (stall dumps snapshot each of
        these to say where the rows had gotten to when the run wedged)."""
        with self._lock:
            return list(self._plans)

    # -- query GC + persistence ---------------------------------------------
    def on_query_gc(self, qid: Optional[str],
                    plan_fp: Optional[str] = None) -> Optional[dict]:
        """``TaskGraph.cleanup`` hook: final snapshot, persist measured
        cardinalities under the plan fingerprint, record size_hint drift,
        drop per-query state and gauge twins."""
        if qid is None:
            return None
        snap = self.snapshot(qid)
        with self._lock:
            plan = self._plans.pop(qid, None)
            if plan is None:
                return None
            for key in [k for k in self._ops if k[0] == qid]:
                del self._ops[key]
            for key in [k for k in self._time if k[0] == qid]:
                del self._time[key]
            for key in [k for k in self._edges if k[0] == qid]:
                del self._edges[key]
            for key in [k for k in self._notes if k[0] == qid]:
                del self._notes[key]
            self._pending = [p for p in self._pending if p[1][0] != qid]
            gauges = self._gauges.pop(qid, [])
            self._skew_worst.pop(qid, None)
            live_worst = max(self._skew_worst.values(), default=0.0)
            self._last = snap
        from quokka_tpu import obs

        if gauges:
            obs.REGISTRY.remove(*gauges)
        # per-query epoch reset: with the skewed query gone the global max
        # drops to the worst LIVE query (0 when idle), so /health alerts
        # clear without a restart
        obs.REGISTRY.gauge("shuffle.skew").set(live_worst)
        from quokka_tpu.obs import devprof

        devprof.on_query_finished(qid, plan_fp or (plan or {}).get("plan_fp"),
                                  snap or {})
        fp = plan_fp or (plan or {}).get("plan_fp")
        if snap is not None:
            record_cardinalities(fp, snap)
            hint = int(snap.get("size_hint_bytes", 0) or 0)
            actual = _source_bytes(snap)
            if hint > 0 and actual > 0:
                drift = abs(hint - actual)
                obs.REGISTRY.counter("opstats.size_hint_drift_bytes").inc(
                    drift)
                obs.RECORDER.record("opstats.drift", qid, hint=hint,
                                    actual=actual, drift=drift)
        return snap

    def reset(self) -> None:
        """Tests only."""
        with self._lock:
            self._plans.clear()
            self._ops.clear()
            self._time.clear()
            self._edges.clear()
            self._notes.clear()
            self._pending.clear()
            self._gauges.clear()
            self._skew_worst.clear()
            self._last = None


def _actor_op_name(info) -> str:
    """Best-effort operator label straight from the ActorInfo (the engine
    upgrades exec labels to the bound executor's class name)."""
    if info.kind == "input":
        return type(info.reader).__name__
    factory = getattr(info, "executor_factory", None)
    f = getattr(factory, "func", factory)
    name = getattr(f, "__name__", None)
    if name and name != "<lambda>":
        return name
    return info.kind


def _source_bytes(snap: dict) -> int:
    return sum(o["bytes_out"] for o in snap.get("operators", ())
               if o.get("kind") == "input")


def _source_rows(snap: dict) -> int:
    return sum(o["rows_out"] for o in snap.get("operators", ())
               if o.get("kind") == "input")


OPSTATS = OpStats()


# ---------------------------------------------------------------------------
# Measured cardinalities: per-plan-fingerprint persistence (memplane's
# strategy-profile pattern) feeding admission + strategy calibration
# ---------------------------------------------------------------------------


def _profile_dir() -> Optional[str]:
    """``QK_CARDPROFILE_DIR`` overrides (empty disables, the QK_STRATEGY_DIR
    idiom); default lives beside the memory profiles under the cache root."""
    env = os.environ.get("QK_CARDPROFILE_DIR")
    if env is not None:
        return env or None
    from quokka_tpu import config

    if not config.CACHE_ROOT:
        return None
    return os.path.join(config.CACHE_ROOT, "cardprofile")


def _profile_path() -> Optional[str]:
    d = _profile_dir()
    if d is None:
        return None
    from quokka_tpu.runtime import compileplane

    return os.path.join(d, compileplane.backend_fingerprint() + ".json")


def _load_profile(path: str) -> Optional[dict]:
    """The profile dict, or None when absent/corrupt/foreign.  A profile
    measured on a different backend topology is rejected wholesale."""
    try:
        with open(path, encoding="utf-8") as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(prof, dict) or prof.get("version") != _PROFILE_VERSION:
        return None
    from quokka_tpu.runtime import compileplane

    if prof.get("fingerprint") != compileplane.backend_fingerprint():
        return None
    return prof if isinstance(prof.get("plans"), dict) else None


def record_cardinalities(plan_fp: Optional[str], snap: dict) -> None:
    """Persist a finished query's measured figures under its plan
    fingerprint (atomic tmp+replace, max-merged across runs so a partial
    run never shrinks a measured cardinality).  Best effort: never raises."""
    if not plan_fp or not snap:
        return
    src_rows = _source_rows(snap)
    src_bytes = _source_bytes(snap)
    if src_rows <= 0 and src_bytes <= 0:
        return
    path = _profile_path()
    if path is None:
        return
    try:
        from quokka_tpu.runtime import compileplane

        prof = _load_profile(path) or {
            "version": _PROFILE_VERSION,
            "fingerprint": compileplane.backend_fingerprint(),
            "plans": {},
        }
        ent = prof["plans"].get(plan_fp)
        ent = ent if isinstance(ent, dict) else {}
        rows = ent.get("rows") if isinstance(ent.get("rows"), dict) else {}
        for o in snap.get("operators", ()):
            k = f"a{o['actor']}:{o['op']}"
            rows[k] = max(int(o["rows_out"]), int(rows.get(k, 0) or 0))
        # plan-INDEPENDENT scan figures keyed by source signature: any plan
        # scanning the same (reader, predicate, projection) reuses them
        # (planner/cost.py's MEASURED basis)
        sources = prof.get("sources")
        sources = sources if isinstance(sources, dict) else {}
        for o in snap.get("operators", ()):
            sig = o.get("src_sig")
            if not sig or o.get("kind") != "input" or not o.get("rows_out"):
                continue
            cur = sources.get(sig)
            cur = cur if isinstance(cur, dict) else {}
            sources[sig] = {
                "rows_raw": max(int(o["rows_in"]),
                                int(cur.get("rows_raw", 0) or 0)),
                "rows": max(int(o["rows_out"]), int(cur.get("rows", 0) or 0)),
                "bytes": max(int(o["bytes_out"]),
                             int(cur.get("bytes", 0) or 0)),
                "runs": int(cur.get("runs", 0) or 0) + 1,
            }
        prof["sources"] = sources
        prof["plans"][plan_fp] = {
            "source_rows": max(src_rows, int(ent.get("source_rows", 0) or 0)),
            "source_bytes": max(src_bytes,
                                int(ent.get("source_bytes", 0) or 0)),
            "max_rows": max([int(o["rows_out"])
                             for o in snap.get("operators", ())] + [0]
                            + [int(ent.get("max_rows", 0) or 0)]),
            "rows": rows,
            "runs": int(ent.get("runs", 0) or 0) + 1,
        }
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(prof, f)
        os.replace(tmp, path)
    except (OSError, TypeError, ValueError) as e:
        from quokka_tpu import obs

        obs.diag(f"[opstats] cardinality persist for {plan_fp} failed: {e!r}")


def _plan_entry(plan_fp: Optional[str]) -> Optional[dict]:
    if not plan_fp:
        return None
    path = _profile_path()
    if path is None:
        return None
    prof = _load_profile(path)
    if prof is None:
        return None
    ent = prof["plans"].get(plan_fp)
    return ent if isinstance(ent, dict) else None


def measured_source_bytes(plan_fp: Optional[str]) -> Optional[int]:
    """Measured bytes the plan's sources actually produced, or None —
    admission falls back to ``size_hint()`` estimation then."""
    ent = _plan_entry(plan_fp)
    if ent is None:
        return None
    try:
        b = int(ent.get("source_bytes", 0))
    except (TypeError, ValueError):
        return None
    return b if b > 0 else None


def measured_sources() -> Dict[str, dict]:
    """Plan-independent measured scan figures keyed by source signature:
    ``{sig: {"rows_raw", "rows", "bytes", "runs"}}`` where ``rows_raw`` is
    pre-predicate reader output, ``rows``/``bytes`` post-predicate.  The
    planner's cost model (``planner/cost.py``) treats an exact signature
    match as MEASURED basis; a bare-scan signature match supplies the
    measured selectivity of a predicate.  Empty dict when no profile."""
    path = _profile_path()
    if path is None:
        return {}
    prof = _load_profile(path)
    if prof is None:
        return {}
    src = prof.get("sources")
    if not isinstance(src, dict):
        return {}
    return {sig: ent for sig, ent in src.items() if isinstance(ent, dict)}


def measured_calib_rows() -> Optional[int]:
    """A representative measured batch cardinality for strategy
    calibration: the largest per-operator row count any profiled plan
    produced on this backend, or None (calibration keeps its default)."""
    path = _profile_path()
    if path is None:
        return None
    prof = _load_profile(path)
    if prof is None:
        return None
    best = 0
    for ent in prof["plans"].values():
        if isinstance(ent, dict):
            with contextlib.suppress(TypeError, ValueError):
                best = max(best, int(ent.get("max_rows", 0) or 0))
    return best if best > 0 else None
