"""Typed counters/gauges + the engine's per-channel task accounting.

Replaces the ad-hoc ``_metrics`` dict that used to live inline in
runtime/engine.py with two layers:

- a process-wide ``Registry`` of named ``Counter``/``Gauge`` instruments
  (cache hits, rpc calls, bytes pushed, ...) that bench.py snapshots into
  its per-query breakdown JSON;
- ``EngineMetrics``: the per-(actor, channel) {tasks, rows, bytes}
  accounting every engine/worker flushes through the control store —
  byte-identical snapshot shape to the old ``_metrics``/``_flush_metrics``
  (``graph.metrics()`` consumers are oblivious), including the deferred
  device-row counters (a device count scalar resolves at flush time, when
  its async host copy has long landed — emit paths must not block on a
  device round trip for a counter).
"""

from __future__ import annotations

import bisect
import contextlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple


class Counter:
    """Monotone counter.  ``inc`` takes the registry lock: increments are
    read-modify-write and these sit on per-task (not per-row) paths."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins instrument (queue depths, buffer sizes)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)  # single store: atomic under the GIL

    @property
    def value(self) -> float:
        return self._value


# latency bucket ladder (seconds): sub-ms dispatch quanta up through the
# stall-timeout regime.  Fixed across the process so histograms merge and
# the Prometheus exposition stays a stable family.
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Fixed-bucket latency histogram (task latency, rpc latency, admission
    queue wait).  ``observe`` takes the registry lock: it is a
    read-modify-write on the bucket counts and sits on per-task / per-rpc
    (not per-row) paths, same cost class as ``Counter.inc``."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, lock: threading.Lock,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None
                   else DEFAULT_LATENCY_BUCKETS))
        # one slot per finite bound + the +Inf overflow slot
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = lock

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.bounds, v)] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> Tuple[List[Tuple[float, int]], float, int]:
        """One ATOMIC read: ([(upper_bound, cumulative_count)] ending with
        (inf, total), sum, count).  Buckets, sum and count come from the
        same locked instant, so the Prometheus exposition invariant
        ``bucket{le="+Inf"} == _count`` holds on every scrape even while
        dispatch threads keep observing."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        out: List[Tuple[float, int]] = []
        acc = 0
        for bound, n in zip(self.bounds, counts):
            acc += n
            out.append((bound, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out, total_sum, total_count

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)] ending with (inf, total) — the
        Prometheus ``_bucket{le=...}`` series."""
        return self.snapshot()[0]

    def _quantile_from(self, cum: List[Tuple[float, int]],
                       q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate over one snapshot (None
        when empty).  Values past the last finite bound report that bound —
        the estimate is for dashboards/stats, not for billing."""
        total = cum[-1][1]
        if total == 0:
            return None
        rank = q * total
        lo = 0.0
        prev = 0
        for bound, acc in cum:
            if acc >= rank and acc > prev:
                if bound == float("inf"):
                    return self.bounds[-1] if self.bounds else lo
                frac = (rank - prev) / (acc - prev)
                return lo + (bound - lo) * min(1.0, max(0.0, frac))
            lo, prev = (bound, acc) if bound != float("inf") else (lo, acc)
        return self.bounds[-1] if self.bounds else None

    def quantile(self, q: float) -> Optional[float]:
        return self._quantile_from(self.cumulative(), q)

    def stats(self) -> Dict[str, Optional[float]]:
        """{count, sum, p50, p95, p99} from ONE atomic snapshot — what
        service stats() embeds."""
        cum, total, count = self.snapshot()
        return {
            "count": count,
            "sum": round(total, 6),
            "p50": self._quantile_from(cum, 0.5),
            "p95": self._quantile_from(cum, 0.95),
            "p99": self._quantile_from(cum, 0.99),
        }

    @staticmethod
    def empty_stats() -> Dict[str, Optional[float]]:
        """The stats() shape for a histogram that does not (or no longer)
        exists — non-creating readers (service stats, /status) use this
        instead of resurrecting a GC'd per-query instrument."""
        return {"count": 0, "sum": 0.0, "p50": None, "p95": None,
                "p99": None}


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name, self._lock))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(
                    name, Histogram(name, self._lock, buckets))
        if buckets is not None and tuple(sorted(buckets)) != h.bounds:
            # silently handing back different bounds would scatter the
            # caller's observations across an unexpected ladder
            raise ValueError(
                f"histogram {name!r} already exists with bounds "
                f"{h.bounds}; requested {tuple(sorted(buckets))}")
        return h

    def histograms(self) -> Dict[str, Histogram]:
        """Live histogram instruments (the Prometheus exporter iterates)."""
        with self._lock:
            return dict(self._histograms)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {n: c.value
                                     for n, c in self._counters.items()}
            out.update({n: g.value for n, g in self._gauges.items()})
            # histograms flatten to their scalar moments; the full bucket
            # vector stays behind histograms()/cumulative()
            for n, h in self._histograms.items():
                out[f"{n}.count"] = h._count
                out[f"{n}.sum"] = round(h._sum, 6)
        return out

    def typed_snapshot(self) -> Dict[str, Dict]:
        """One atomic read of the whole registry, KEPT BY KIND — what the
        history ring records.  ``snapshot()`` flattens histograms into
        ``.count``/``.sum`` keys, which loses the kind distinction rate
        derivation needs (counters are rateable, gauges are not)."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {n: g.value for n, g in self._gauges.items()},
                "histograms": {n: (h._count, round(h._sum, 6))
                               for n, h in self._histograms.items()},
            }

    def remove(self, *names: str) -> None:
        """Drop named instruments (per-query counters GC with their query —
        a long-lived service would otherwise grow one pair per query id)."""
        with self._lock:
            for n in names:
                self._counters.pop(n, None)
                self._gauges.pop(n, None)
                self._histograms.pop(n, None)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


REGISTRY = Registry()


class _ChannelCounters:
    __slots__ = ("tasks", "rows", "bytes")

    def __init__(self):
        self.tasks = 0
        self.rows = 0
        self.bytes = 0

    def as_dict(self) -> Dict[str, int]:
        return {"tasks": self.tasks, "rows": self.rows, "bytes": self.bytes}


class EngineMetrics:
    """Per-(actor, channel) progress counters an engine/worker flushes to
    the store under ``("metrics", worker_id)`` — the exact contract
    TaskGraph.metrics() aggregates."""

    def __init__(self):
        self._chan: Dict[Tuple[int, int], _ChannelCounters] = {}
        # (key, device-scalar) pairs resolved lazily at flush time
        self._pending: List[Tuple[Tuple[int, int], object]] = []
        self.dirty = 0

    def __bool__(self) -> bool:
        return bool(self._chan)

    def task(self, actor: int, channel: int, rows, nbytes: int) -> None:
        """rows: an int, or a device count scalar (resolved at flush)."""
        key = (actor, channel)
        e = self._chan.get(key)
        if e is None:
            e = self._chan[key] = _ChannelCounters()
        e.tasks += 1
        if isinstance(rows, int):
            e.rows += rows
        elif rows is not None:
            self._pending.append((key, rows))
        e.bytes += nbytes
        self.dirty += 1

    def snapshot(self) -> Dict:
        """Resolve deferred device rows and render the store payload:
        {(actor, ch): {tasks, rows, bytes}, "__compile__": compile stats}."""
        for key, dev in self._pending:
            # a dead device buffer must not sink the flush
            with contextlib.suppress(Exception):
                self._chan[key].rows += int(dev)
        self._pending = []
        snap: Dict = {k: c.as_dict() for k, c in self._chan.items()}
        from quokka_tpu.utils import compilestats

        # each worker process has its own counters; ship them with the
        # flush so metrics() can see worker-side compile churn
        snap["__compile__"] = compilestats.snapshot()
        self.dirty = 0
        return snap
