"""Typed counters/gauges + the engine's per-channel task accounting.

Replaces the ad-hoc ``_metrics`` dict that used to live inline in
runtime/engine.py with two layers:

- a process-wide ``Registry`` of named ``Counter``/``Gauge`` instruments
  (cache hits, rpc calls, bytes pushed, ...) that bench.py snapshots into
  its per-query breakdown JSON;
- ``EngineMetrics``: the per-(actor, channel) {tasks, rows, bytes}
  accounting every engine/worker flushes through the control store —
  byte-identical snapshot shape to the old ``_metrics``/``_flush_metrics``
  (``graph.metrics()`` consumers are oblivious), including the deferred
  device-row counters (a device count scalar resolves at flush time, when
  its async host copy has long landed — emit paths must not block on a
  device round trip for a counter).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Tuple


class Counter:
    """Monotone counter.  ``inc`` takes the registry lock: increments are
    read-modify-write and these sit on per-task (not per-row) paths."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._value = 0
        self._lock = lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins instrument (queue depths, buffer sizes)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)  # single store: atomic under the GIL

    @property
    def value(self) -> float:
        return self._value


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name, self._lock))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {n: c.value
                                     for n, c in self._counters.items()}
            out.update({n: g.value for n, g in self._gauges.items()})
        return out

    def remove(self, *names: str) -> None:
        """Drop named instruments (per-query counters GC with their query —
        a long-lived service would otherwise grow one pair per query id)."""
        with self._lock:
            for n in names:
                self._counters.pop(n, None)
                self._gauges.pop(n, None)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


REGISTRY = Registry()


class _ChannelCounters:
    __slots__ = ("tasks", "rows", "bytes")

    def __init__(self):
        self.tasks = 0
        self.rows = 0
        self.bytes = 0

    def as_dict(self) -> Dict[str, int]:
        return {"tasks": self.tasks, "rows": self.rows, "bytes": self.bytes}


class EngineMetrics:
    """Per-(actor, channel) progress counters an engine/worker flushes to
    the store under ``("metrics", worker_id)`` — the exact contract
    TaskGraph.metrics() aggregates."""

    def __init__(self):
        self._chan: Dict[Tuple[int, int], _ChannelCounters] = {}
        # (key, device-scalar) pairs resolved lazily at flush time
        self._pending: List[Tuple[Tuple[int, int], object]] = []
        self.dirty = 0

    def __bool__(self) -> bool:
        return bool(self._chan)

    def task(self, actor: int, channel: int, rows, nbytes: int) -> None:
        """rows: an int, or a device count scalar (resolved at flush)."""
        key = (actor, channel)
        e = self._chan.get(key)
        if e is None:
            e = self._chan[key] = _ChannelCounters()
        e.tasks += 1
        if isinstance(rows, int):
            e.rows += rows
        elif rows is not None:
            self._pending.append((key, rows))
        e.bytes += nbytes
        self.dirty += 1

    def snapshot(self) -> Dict:
        """Resolve deferred device rows and render the store payload:
        {(actor, ch): {tasks, rows, bytes}, "__compile__": compile stats}."""
        for key, dev in self._pending:
            # a dead device buffer must not sink the flush
            with contextlib.suppress(Exception):
                self._chan[key].rows += int(dev)
        self._pending = []
        snap: Dict = {k: c.as_dict() for k, c in self._chan.items()}
        from quokka_tpu.utils import compilestats

        # each worker process has its own counters; ship them with the
        # flush so metrics() can see worker-side compile churn
        snap["__compile__"] = compilestats.snapshot()
        self.dirty = 0
        return snap
