"""Metrics history: a bounded ring of periodic full-registry snapshots.

Every instrument on /metrics is an instant — skew ratios, watermark lag,
mem.live_bytes, admission queue depth all answer "now?" but never
"trending which way?".  This module adds the time dimension: a sampler
thread records ``Registry.typed_snapshot()`` every ``QK_HISTORY_INTERVAL_S``
seconds into a ring of ``QK_HISTORY_DEPTH`` samples, derives per-counter
rates from adjacent samples, and serves the whole thing as JSON at
``/history`` on the metrics sidecar.

Each recorded sample is also handed to the alert engine
(:mod:`quokka_tpu.obs.alerts`) — history IS the alert cadence, so every
rule sees the same timeline the operator sees.

The sampler is refcounted process-wide: each ``QueryService`` acquires it
on start and releases on shutdown, so N in-process services share ONE
thread and the last shutdown stops it.  ``interval_s <= 0`` disables
periodic sampling entirely (tests and smokes then drive ``RING.record()``
by hand for determinism).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


def _interval_s() -> float:
    """``QK_HISTORY_INTERVAL_S`` (seconds between samples; default 5.0;
    ``0``/empty disables the sampler)."""
    raw = os.environ.get("QK_HISTORY_INTERVAL_S")
    if raw is None:
        return 5.0
    if not raw.strip():
        return 0.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 5.0


def _depth() -> int:
    """``QK_HISTORY_DEPTH`` (ring capacity; default 120 samples — 10 min
    at the default 5 s interval; floor 2 so rates stay derivable)."""
    try:
        return max(2, int(os.environ.get("QK_HISTORY_DEPTH", 120)))
    except ValueError:
        return 120


class HistoryRing:
    """The bounded sample ring.  ``record()`` takes one registry snapshot
    (outside this ring's lock — the registry has its own), appends it, and
    evicts past depth.  Rate derivation happens at read time so the hot
    record path stays a list append."""

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: List[dict] = []

    def record(self, now: Optional[float] = None) -> dict:
        """Take and store one sample; returns it (the alert engine and the
        smokes evaluate the sample they just forced)."""
        from quokka_tpu import obs

        snap = obs.REGISTRY.typed_snapshot()
        sample = {
            "t": time.time() if now is None else now,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap["histograms"],
        }
        depth = _depth()
        with self._lock:
            self._samples.append(sample)
            if len(self._samples) > depth:
                del self._samples[:len(self._samples) - depth]
        obs.REGISTRY.counter("history.samples").inc()
        return sample

    def samples(self) -> List[dict]:
        with self._lock:
            return list(self._samples)

    def last(self, n: int = 2) -> List[dict]:
        """The newest n samples, oldest first (what the alert engine's
        delta rules compare)."""
        with self._lock:
            return self._samples[-n:]

    def rates(self) -> Dict[str, List[dict]]:
        """Per-counter rate series derived from adjacent sample pairs:
        ``{counter: [{t, rate}]}`` where rate = (v1-v0)/dt at t1.  Only
        counters that moved at least once appear — a full cross-product of
        every counter times every interval would dwarf the samples
        themselves.  Histogram counts rate the same way under a
        ``<name>.count`` key (observations/second)."""
        samples = self.samples()
        out: Dict[str, List[dict]] = {}
        for prev, cur in zip(samples, samples[1:]):
            dt = cur["t"] - prev["t"]
            if dt <= 0:
                continue
            for name, v1 in cur["counters"].items():
                v0 = prev["counters"].get(name, 0)
                if v1 != v0:
                    out.setdefault(name, []).append(
                        {"t": cur["t"], "rate": round((v1 - v0) / dt, 6)})
            for name, (c1, _) in cur["histograms"].items():
                c0 = prev["histograms"].get(name, (0, 0.0))[0]
                if c1 != c0:
                    out.setdefault(f"{name}.count", []).append(
                        {"t": cur["t"], "rate": round((c1 - c0) / dt, 6)})
        return out

    def payload(self) -> dict:
        """What /history serves."""
        return {
            "interval_s": _interval_s(),
            "depth": _depth(),
            "samples": self.samples(),
            "rates": self.rates(),
        }

    def reset(self) -> None:
        """Tests only."""
        with self._lock:
            self._samples.clear()


RING = HistoryRing()


# ---------------------------------------------------------------------------
# Refcounted global sampler thread
# ---------------------------------------------------------------------------

_sampler_lock = threading.Lock()
_sampler_refs = 0
_sampler_stop: Optional[threading.Event] = None
_sampler_thread: Optional[threading.Thread] = None


def _sampler_loop(stop: threading.Event, interval: float) -> None:
    from quokka_tpu.obs import alerts, progress

    while not stop.wait(interval):
        # refresh progress gauges first so the stall rule sees fractions
        # even when no client polls /status between samples
        progress.refresh_live()
        sample = RING.record()
        alerts.ENGINE.evaluate(sample)


def acquire_sampler() -> None:
    """Refcount up; the first acquirer starts the sampler thread (no-op
    when QK_HISTORY_INTERVAL_S disables sampling)."""
    global _sampler_refs, _sampler_stop, _sampler_thread
    interval = _interval_s()
    with _sampler_lock:
        _sampler_refs += 1
        if _sampler_thread is not None or interval <= 0:
            return
        stop = threading.Event()
        t = threading.Thread(
            target=_sampler_loop, args=(stop, interval),
            name="qk-history-sampler", daemon=True)
        _sampler_stop, _sampler_thread = stop, t
        t.start()


def release_sampler() -> None:
    """Refcount down; the last release stops and joins the thread."""
    global _sampler_refs, _sampler_stop, _sampler_thread
    with _sampler_lock:
        _sampler_refs = max(0, _sampler_refs - 1)
        if _sampler_refs > 0 or _sampler_thread is None:
            return
        stop, t = _sampler_stop, _sampler_thread
        _sampler_stop = _sampler_thread = None
    stop.set()
    t.join(timeout=5.0)
