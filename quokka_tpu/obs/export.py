"""Prometheus text exposition + the /metrics //status HTTP sidecar.

Renders the typed Registry (obs/metrics.py) in Prometheus text format
(version 0.0.4) and serves it from a stdlib-only background HTTP server so
an external scraper can watch a soak or a long-lived QueryService run from
outside the process:

    GET /metrics   Prometheus text: counters, gauges, histograms
    GET /status    JSON: live QueryService.stats() (when a service is
                   attached), process info, recorder drop counter.
                   ``?format=json`` is an explicit alias (the machine
                   contract a router scrapes); ``?format=text`` renders a
                   human-readable summary instead
    GET /history   JSON: the bounded metrics-history ring (obs/history.py)
                   with derived per-counter rates
    GET /health    JSON: the alert engine's ok/degraded/critical verdict
                   plus the firing rules (obs/alerts.py)

``QK_METRICS_PORT`` opts in: QueryService starts a sidecar on that port at
construction and stops it at shutdown (port ``0`` binds an ephemeral port,
readable from ``server.port`` — what tests use).  No third-party
dependency: the container has no prometheus_client, and the text format is
ten lines of escaping rules.

Naming: dotted instrument names sanitize to ``quokka_<name>`` metric
families.  Per-query/per-site instrument families (``task.latency_s.<qid>``,
``cache.plan_hit.<qid>``, ``rpc.<method>``, ``chaos.<site>``) render as ONE
family with a label instead of one family per query — the cardinality lives
in label values, where Prometheus expects it.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from quokka_tpu.obs import recorder as _recorder
from quokka_tpu.obs.metrics import REGISTRY, Histogram, Registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# (kind, dotted-prefix, family, label_key).  A name matches when it is the
# right instrument kind and extends the prefix with a NON-EMPTY suffix; the
# suffix becomes the label value, so per-query/per-site instruments render
# as ONE family with a label instead of unbounded family names.
# INVARIANT: when the runtime also keeps an unlabeled AGGREGATE instrument
# of a labeled family (observing every event into both), the aggregate
# needs its own _EXACT_FAMILIES name below — sharing the labeled family
# would double-count under sum()-style PromQL.
_LABEL_FAMILIES: Tuple[Tuple[str, str, str, str], ...] = (
    ("histogram", "task.latency_s.", "quokka_task_latency_seconds", "query"),
    ("counter", "cache.plan_hit.", "quokka_cache_plan_hit", "query"),
    ("counter", "cache.plan_miss.", "quokka_cache_plan_miss", "query"),
    ("counter", "chaos.", "quokka_chaos_injected", "site"),
    ("counter", "rpc.", "quokka_rpc_calls", "method"),
    # compile plane (runtime/compileplane.py): per-query twins of the
    # cache-hit/miss/prewarm-hit event counters
    ("counter", "compile.cache_hit.", "quokka_compile_cache_hit", "query"),
    ("counter", "compile.miss.", "quokka_compile_miss", "query"),
    ("counter", "compile.prewarm_hit.", "quokka_compile_prewarm_hit",
     "query"),
    # streaming plane (quokka_tpu/streaming/): standing-query pane/late
    # counters + watermark-staleness gauge, per-query twins GC'd with the
    # namespace exactly like the shuffle/compile families
    ("counter", "stream.panes.", "quokka_stream_panes", "query"),
    ("counter", "stream.late_dropped.", "quokka_stream_late_dropped",
     "query"),
    ("gauge", "stream.watermark_lag_s.", "quokka_stream_watermark_lag_seconds",
     "query"),
    # memory plane (obs/memplane.py): per-query footprint gauges GC'd with
    # the namespace, plus per-site-class residency
    ("gauge", "mem.live_bytes.", "quokka_mem_live_bytes", "query"),
    ("gauge", "mem.peak_bytes.", "quokka_mem_peak_bytes", "query"),
    ("gauge", "mem.spill_resident_bytes.", "quokka_mem_spill_resident_bytes",
     "query"),
    ("gauge", "mem.site_bytes.", "quokka_mem_site_bytes", "site"),
    # EXPLAIN ANALYZE plane (obs/opstats.py): per-query operator-row
    # gauges and per-exchange-edge skew ratios ("<qid>.a<src>-a<tgt>"),
    # created at snapshot time and GC'd in opstats.on_query_gc
    ("gauge", "opstats.rows_in.", "quokka_opstats_rows_in", "query"),
    ("gauge", "opstats.rows_out.", "quokka_opstats_rows_out", "query"),
    ("gauge", "shuffle.skew.", "quokka_shuffle_skew_ratio", "edge"),
    # per-query twins of the shuffle byte/sync counters (engine.py GCs the
    # instruments with the namespace; the label keeps the family bounded)
    ("counter", "shuffle.bytes.", "quokka_shuffle_bytes_by_query", "query"),
    ("counter", "shuffle.host_syncs.", "quokka_shuffle_host_syncs_by_query",
     "query"),
    # health plane (obs/progress.py + obs/alerts.py): per-query progress
    # gauges GC'd with the query, per-rule alert-fired counters
    ("gauge", "progress.fraction.", "quokka_progress_fraction", "query"),
    ("gauge", "progress.eta_s.", "quokka_progress_eta_seconds", "query"),
    ("counter", "alert.", "quokka_alerts_fired", "rule"),
    # device-efficiency plane (obs/devprof.py): per-(query, operator)
    # roofline-efficiency gauges ("<qid>.a<actor>"), created at snapshot
    # time and GC'd with the query like the opstats twins
    ("gauge", "devprof.eff.", "quokka_devprof_roofline_efficiency", "op"),
)

# Aggregate instruments that ALSO exist as a labeled per-query family: the
# engine observes every dispatch into both 'task.latency_s' and
# 'task.latency_s.<qid>' (same for cache.plan_hit/miss).  The aggregate
# must NOT share the labeled family's name, or sum()-style PromQL over the
# family double-counts every observation.
_EXACT_FAMILIES: Dict[Tuple[str, str], str] = {
    ("histogram", "task.latency_s"): "quokka_task_latency_all_seconds",
    ("counter", "cache.plan_hit"): "quokka_cache_plan_hit_all",
    ("counter", "cache.plan_miss"): "quokka_cache_plan_miss_all",
    ("counter", "compile.cache_hit"): "quokka_compile_cache_hit_all",
    ("counter", "compile.miss"): "quokka_compile_miss_all",
    ("counter", "compile.prewarm_hit"): "quokka_compile_prewarm_hit_all",
    ("counter", "stream.panes"): "quokka_stream_panes_all",
    ("counter", "stream.late_dropped"): "quokka_stream_late_dropped_all",
    ("gauge", "stream.watermark_lag_s"):
        "quokka_stream_watermark_lag_all_seconds",
    ("gauge", "mem.live_bytes"): "quokka_mem_live_bytes_all",
    ("gauge", "mem.peak_bytes"): "quokka_mem_peak_bytes_all",
    ("gauge", "mem.spill_resident_bytes"):
        "quokka_mem_spill_resident_bytes_all",
    # worst skew ratio observed process-wide (per-edge twins carry the
    # labeled family above)
    ("gauge", "shuffle.skew"): "quokka_shuffle_skew_ratio_max",
    ("counter", "opstats.size_hint_drift_bytes"):
        "quokka_opstats_size_hint_drift_bytes",
    # calibrated device peaks (obs/devprof.py calibrate): process-wide,
    # not per-query, so they must not share the labeled devprof family
    ("gauge", "devprof.peak_flops"): "quokka_devprof_peak_flops",
    ("gauge", "devprof.peak_bw_bytes"): "quokka_devprof_peak_bw_bytes",
}


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch in "_:" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _family(name: str, kind: str) -> Tuple[str, Optional[str]]:
    """(family_name, label_or_None) for one instrument name."""
    exact = _EXACT_FAMILIES.get((kind, name))
    if exact is not None:
        return exact, None
    # strategy.<op>.<choice> (ops/strategy.note_used): two label dimensions,
    # so the kernel-strategy matrix reads as ONE family —
    # quokka_kernel_strategy_used_total{op="asof",choice="searchsorted"}
    if kind == "counter" and name.startswith("strategy."):
        rest = name[len("strategy."):]
        op, _, choice_ = rest.partition(".")
        if op and choice_:
            return ("quokka_kernel_strategy_used",
                    f'op="{escape_label_value(op)}",'
                    f'choice="{escape_label_value(choice_)}"')
    for want_kind, prefix, fam, key in _LABEL_FAMILIES:
        if (kind == want_kind and name.startswith(prefix)
                and len(name) > len(prefix)):
            val = name[len(prefix):]
            return fam, f'{key}="{escape_label_value(val)}"'
    if kind == "histogram" and name.endswith("_s"):
        # seconds-suffix convention: task.latency_s -> ..._latency_seconds
        return "quokka_" + _sanitize(name[:-2]) + "_seconds", None
    return "quokka_" + _sanitize(name), None


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def render(registry: Registry = None,
           extra_gauges: Optional[Dict[str, float]] = None) -> str:
    """The /metrics payload.  ``extra_gauges`` lets callers append
    process-level facts (recorder drops, uptime) without registering
    instruments."""
    registry = REGISTRY if registry is None else registry
    lines: List[str] = []
    typed: Dict[str, str] = {}   # family -> TYPE already emitted

    def emit(family: str, kind: str, label: Optional[str], value,
             suffix: str = "", extra_label: str = "") -> None:
        if typed.get(family) != kind:
            lines.append(f"# TYPE {family} {kind}")
            typed[family] = kind
        labels = ",".join(x for x in (label, extra_label) if x)
        body = "{" + labels + "}" if labels else ""
        lines.append(f"{family}{suffix}{body} {_fmt(value)}")

    with registry._lock:
        counters = {n: c.value for n, c in registry._counters.items()}
        gauges = {n: g.value for n, g in registry._gauges.items()}
        histograms = dict(registry._histograms)
    for name in sorted(counters):
        fam, label = _family(name, "counter")
        emit(fam + "_total", "counter", label, counters[name])
    for name in sorted(gauges):
        fam, label = _family(name, "gauge")
        emit(fam, "gauge", label, gauges[name])
    for name in sorted(histograms):
        h: Histogram = histograms[name]
        fam, label = _family(name, "histogram")
        # one atomic snapshot: bucket{+Inf} == _count must hold per scrape
        cum, h_sum, h_count = h.snapshot()
        for bound, acc in cum:
            emit(fam, "histogram", label, acc, suffix="_bucket",
                 extra_label=f'le="{_fmt(bound)}"')
        emit(fam, "histogram", label, h_sum, suffix="_sum")
        emit(fam, "histogram", label, h_count, suffix="_count")
    for name in sorted(extra_gauges or {}):
        emit("quokka_" + _sanitize(name), "gauge", None, extra_gauges[name])
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Background stdlib HTTP sidecar serving /metrics and /status.

    ``service`` (a QueryService) is optional; without one, /status reports
    process-level info only.  ``port=0`` binds an ephemeral port (read it
    back from ``self.port``)."""

    def __init__(self, port: Optional[int] = None, host: str = "127.0.0.1",
                 service=None, registry: Registry = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        if port is None:
            port = int(os.environ.get("QK_METRICS_PORT", "0"))
        self.service = service
        self.registry = REGISTRY if registry is None else registry
        self._started = time.time()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # scrapes are not diagnostics
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                params = dict(
                    kv.partition("=")[::2] for kv in query.split("&") if kv)
                try:
                    if path == "/metrics":
                        self._send(200, outer.metrics_text().encode(),
                                   CONTENT_TYPE)
                    elif path == "/status":
                        # JSON is (and stays) the default; ?format=json is
                        # the explicit machine-contract spelling, text the
                        # human one
                        if params.get("format") == "text":
                            self._send(200, outer.status_text().encode(),
                                       "text/plain; charset=utf-8")
                        else:
                            self._send(200,
                                       json.dumps(outer.status(),
                                                  default=repr).encode(),
                                       "application/json")
                    elif path == "/history":
                        from quokka_tpu.obs import history

                        self._send(200,
                                   json.dumps(history.RING.payload(),
                                              default=repr).encode(),
                                   "application/json")
                    elif path == "/health":
                        from quokka_tpu.obs import alerts

                        self._send(200,
                                   json.dumps(alerts.ENGINE.health(),
                                              default=repr).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found: try /metrics, "
                                        b"/status, /history or /health\n",
                                   "text/plain")
                except Exception as e:  # noqa: BLE001 — a scrape must not
                    # take the serving thread down with it; if even the
                    # 500 cannot be sent the scraper already hung up
                    with contextlib.suppress(OSError):
                        self._send(500, repr(e).encode(), "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"qk-metrics-{self.port}")
        self._thread.start()

    # -- payloads -----------------------------------------------------------
    def metrics_text(self) -> str:
        return render(self.registry, extra_gauges={
            "obs_dropped_events": _recorder.RECORDER.dropped_total,
            "uptime_seconds": round(time.time() - self._started, 3),
        })

    def status(self) -> Dict:
        snap = self.registry.snapshot()
        out = {
            "pid": os.getpid(),
            "time": time.time(),
            "uptime_s": round(time.time() - self._started, 3),
            "obs": {
                "recorder_enabled": _recorder.RECORDER.enabled,
                "dropped_events": _recorder.RECORDER.dropped_total,
                "dropped_by_type": _recorder.RECORDER.dropped,
                "sampled_by_type": _recorder.RECORDER.sampled,
                "ring_capacity": _recorder.RECORDER.capacity,
            },
            # the counters an operator triages incidents from
            "integrity_corrupt": snap.get("integrity.corrupt", 0),
            "chaos": {k.split(".", 1)[1]: v for k, v in snap.items()
                      if k.startswith("chaos.")},
        }
        try:
            from quokka_tpu.obs import devprof

            out["devprof"] = devprof.summary()
        except Exception as e:  # noqa: BLE001 — profiling must not 500
            out["devprof"] = {"error": repr(e)}  # /status
        svc = self.service
        if svc is not None:
            try:
                out["service"] = svc.stats()
            except Exception as e:  # noqa: BLE001 — a torn-down service
                out["service"] = {"error": repr(e)}  # must not 500 /status
        return out

    def status_text(self) -> str:
        """Human-readable /status?format=text render of the same dict the
        JSON twin serves — a terminal-width summary, not a new contract."""
        st = self.status()
        from quokka_tpu.obs import alerts

        health = alerts.ENGINE.health()
        lines = [
            f"quokka pid={st['pid']} uptime={st['uptime_s']:.1f}s "
            f"health={health['status']}",
        ]
        for f in health["firing"]:
            lines.append(f"  ALERT [{f['severity']}] {f['rule']}: "
                         f"{f['message']}")
        svc = st.get("service")
        if isinstance(svc, dict) and "error" not in svc:
            lines.append(
                f"service: pool={svc.get('pool_size')} "
                f"alive={svc.get('workers_alive')} "
                f"finished={svc.get('finished')}")
            for qid, row in sorted(svc.get("sessions", {}).items()):
                frac = row.get("progress")
                eta = row.get("eta_s")
                prog = (f" {frac:.0%}" if isinstance(frac, float) else "")
                prog += (f" eta={eta:.1f}s" if isinstance(eta, float)
                         else "")
                lines.append(f"  {qid} [{row.get('status')}]{prog}")
        if st.get("integrity_corrupt"):
            lines.append(f"integrity.corrupt={st['integrity_corrupt']}")
        return "\n".join(lines) + "\n"

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        # double-close / already-dead socket is a no-op, not an error
        with contextlib.suppress(OSError):
            self._httpd.shutdown()
            self._httpd.server_close()

    stop = close


def start_from_env(service=None) -> Optional[MetricsServer]:
    """Start a sidecar when ``QK_METRICS_PORT`` is set (any value,
    including ``0`` for an ephemeral port); None when unset."""
    port = os.environ.get("QK_METRICS_PORT")
    if port is None or port.strip() == "":
        return None
    try:
        return MetricsServer(port=int(port), service=service)
    except (OSError, ValueError) as e:
        from quokka_tpu import obs

        obs.diag(f"[metrics] sidecar on QK_METRICS_PORT={port!r} failed "
                 f"to start: {e!r}")
        return None
