"""Structured tracing: named spans with aggregate timings + flight events.

The span API that used to live in utils/tracing.py (which now re-exports
this module).  Two consumers share one ``span(...)`` call site:

- the aggregate summary (``QUOKKA_TRACE=1`` or ``set_enabled(True)``):
  name -> (count, total seconds), printed by bench.py at run end — the
  replacement for the reference's print_if_profile timestamp prints
  (pyquokka/core.py:20-30);
- the flight recorder: every span lands as a duration event in the ring
  (obs/recorder.py) so merged timelines show where time went per worker.

When neither consumer is live the span body pays nothing but the two
``perf_counter`` calls it skipped before this refactor, restored by the
early-out below.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict

from quokka_tpu.obs import recorder as _recorder

_enabled = os.environ.get("QUOKKA_TRACE", "0") not in ("0", "", "false")

_lock = threading.Lock()
_stats = defaultdict(lambda: [0, 0.0])  # name -> [count, total_seconds]


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Turn aggregate collection on programmatically (bench.py does this so
    its breakdown JSON is populated even without QUOKKA_TRACE=1)."""
    global _enabled
    _enabled = bool(on)


@contextmanager
def span(name: str):
    rec = _recorder.RECORDER
    if not (_enabled or rec.enabled):
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if _enabled:
            with _lock:
                s = _stats[name]
                s[0] += 1
                s[1] += dt
        rec.record("span", name, dur=dt)


def add(name: str, seconds: float, count: int = 1):
    rec = _recorder.RECORDER
    if not (_enabled or rec.enabled):
        return
    if _enabled:
        with _lock:
            s = _stats[name]
            s[0] += count
            s[1] += seconds
    rec.record("span", name, dur=seconds, count=count)


def stats() -> Dict[str, Dict[str, float]]:
    """Structured snapshot: name -> {count, total_s} (bench breakdown)."""
    with _lock:
        return {name: {"count": n, "total_s": round(total, 6)}
                for name, (n, total) in _stats.items()}


def summary() -> str:
    with _lock:
        rows = sorted(_stats.items(), key=lambda kv: -kv[1][1])
    lines = [f"{'span':<28}{'count':>8}{'total_s':>10}{'avg_ms':>10}"]
    for name, (n, total) in rows:
        lines.append(f"{name:<28}{n:>8}{total:>10.3f}{total / max(n,1) * 1e3:>10.2f}")
    return "\n".join(lines)


def reset():
    with _lock:
        _stats.clear()
