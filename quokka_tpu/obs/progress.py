"""Per-query progress/ETA estimation: the health plane's forward-looking eye.

Every instrument to date looks backward (what the query did); nothing says
how far along a RUNNING query is.  This module blends two host-side signals
already in the opstats ledger into a monotone completion fraction plus an
EWMA-throughput ETA:

- **scanned-source progress** — bytes (and rows) the scan operators have
  produced so far, against the plan-fingerprint cardinality profile's
  persisted ``source_bytes`` total (PR 14).  A warm plan therefore knows its
  denominator from MEASUREMENT; a cold plan (no profile) falls back to the
  readers' ``size_hint()`` bytes, the same degraded prior admission uses.
- **per-operator completion** — each exec operator's observed ``rows_out``
  against the profile's persisted per-operator max rows, averaged across
  profiled operators (warm plans only: a cold plan has no per-op prior).

The blend is clamped monotone per query (an out-of-order opstats report or
a profile denominator that proves too small can never move the bar
backward) and capped below 1.0 until the query actually finishes — the
estimator never claims completion it cannot know.

ZERO device syncs: the estimator consumes only the ledger's host-side
integer figures (``OpStats.progress_view``); deferred device-count scalars
stay on the pending list untouched.  explain-smoke's ``host_syncs == 0``
gate covers the whole collection path.

Surfaces: ``QueryHandle.progress()``, the per-session ``progress``/``eta_s``
columns in ``QueryService.stats()`` (hence ``/status``), the
``progress.fraction.<qid>`` / ``progress.eta_s.<qid>`` gauges on
``/metrics`` (GC'd with the query), ``bench.py --measure`` detail, and —
pane-frontier based — ``StreamingHandle.progress()``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

# EWMA smoothing for the fraction-per-second throughput estimate: heavy
# enough that one slow poll doesn't whipsaw the ETA, light enough to track
# a genuine rate change within a few samples.
_EWMA_ALPHA = 0.3
# a live query never reports complete: the last percent belongs to the
# finish transition (sink flush, teardown), which only finish() observes
_LIVE_CAP = 0.99
# rates below this (fraction/s) produce no ETA: the query is effectively
# stalled and an ETA in the thousands of hours is noise, not information
_MIN_RATE = 1e-6


class ProgressTracker:
    """Process-wide per-query progress state.  All figures flow one way:
    ``snapshot(qid)`` reads the opstats ledger, folds in the cached
    cardinality-profile prior, and updates the monotone fraction + EWMA
    rate under this tracker's own lock (never the registry lock)."""

    def __init__(self):
        self._lock = threading.Lock()
        # qid -> {fraction, rate, last_t, profile, profile_loaded, gauges}
        self._q: Dict[str, dict] = {}
        # most recently finished query's final snapshot (what bench.py
        # reads after a one-shot run's cleanup — the opstats _last idiom)
        self._last: Optional[dict] = None

    # -- estimation ----------------------------------------------------------
    def snapshot(self, qid: Optional[str],
                 now: Optional[float] = None) -> Optional[dict]:
        """The query's current progress estimate:

        ``{fraction, eta_s, basis, elapsed_s, rate_per_s, source_bytes_done,
        source_bytes_total, op_completion, profiled_ops}``

        ``fraction`` is monotone per query and < 1.0 while live.  ``basis``
        is ``"cardprofile"`` (measured denominators), ``"size_hint"`` (cold
        plan), or ``"none"`` (no denominator at all — fraction stays 0).
        None for an id the ledger does not know (after GC: the stashed
        final snapshot if it matches)."""
        if qid is None:
            return None
        from quokka_tpu.obs import opstats

        view = opstats.OPSTATS.progress_view(qid)
        if view is None:
            with self._lock:
                last = self._last
            return last if last and last.get("query_id") == qid else None
        now = time.time() if now is None else now
        profile = self._profile_for(qid, view.get("plan_fp"))
        raw, basis, detail = _estimate(view, profile)
        with self._lock:
            st = self._q.setdefault(qid, {
                "fraction": 0.0, "rate": None, "last_t": None,
            })
            frac = min(max(st["fraction"], raw), _LIVE_CAP)
            if st["last_t"] is not None:
                dt = now - st["last_t"]
                if dt > 0:
                    inst = max(0.0, (frac - st["fraction"]) / dt)
                    st["rate"] = (inst if st["rate"] is None else
                                  _EWMA_ALPHA * inst
                                  + (1.0 - _EWMA_ALPHA) * st["rate"])
            st["fraction"] = frac
            st["last_t"] = now
            rate = st["rate"]
        eta = ((1.0 - frac) / rate
               if rate is not None and rate > _MIN_RATE else None)
        snap = {
            "query_id": qid,
            "fraction": round(frac, 6),
            "eta_s": round(eta, 3) if eta is not None else None,
            "basis": basis,
            "elapsed_s": round(max(0.0, now - view["t0"]), 6),
            "rate_per_s": round(rate, 9) if rate is not None else None,
            **detail,
        }
        self._export_gauges(qid, snap)
        return snap

    def _profile_for(self, qid: str, plan_fp: Optional[str]
                     ) -> Optional[dict]:
        """The plan's persisted cardinality entry, loaded from disk ONCE per
        query and cached (a per-poll profile read would put file I/O on
        every /status scrape)."""
        with self._lock:
            st = self._q.get(qid)
            if st is not None and st.get("profile_loaded"):
                return st.get("profile")
        from quokka_tpu.obs import opstats

        profile = None
        with contextlib.suppress(Exception):
            profile = opstats._plan_entry(plan_fp)
        with self._lock:
            st = self._q.setdefault(qid, {
                "fraction": 0.0, "rate": None, "last_t": None,
            })
            st["profile"] = profile
            st["profile_loaded"] = True
        return profile

    def _export_gauges(self, qid: str, snap: dict) -> None:
        from quokka_tpu import obs

        names = (f"progress.fraction.{qid}", f"progress.eta_s.{qid}")
        with self._lock:
            st = self._q.get(qid)
            if st is None:
                return  # GC'd between estimate and export: do not resurrect
            st["gauges"] = names
        obs.REGISTRY.gauge(names[0]).set(snap["fraction"])
        obs.REGISTRY.gauge(names[1]).set(
            snap["eta_s"] if snap["eta_s"] is not None else -1.0)

    # -- lifecycle -----------------------------------------------------------
    def on_query_gc(self, qid: Optional[str],
                    finished: bool = True) -> Optional[dict]:
        """``TaskGraph.cleanup`` hook (the opstats/memplane discipline):
        stamp the final snapshot — fraction 1.0 for a finished query — stash
        it for post-GC readers, drop per-query state + gauge twins."""
        if qid is None:
            return None
        snap = self.snapshot(qid)
        with self._lock:
            st = self._q.pop(qid, None)
            # idempotent: a second GC (session.finish already ran; the
            # engine's cleanup hook fires later) must not restamp the
            # stashed final snapshot — a failed query keeps its honest
            # fraction even though this call defaults finished=True
            already_final = (st is None and self._last is not None
                             and self._last.get("query_id") == qid)
            if already_final:
                return dict(self._last)
            if snap is not None and snap.get("query_id") == qid:
                snap = dict(snap)
                if finished:
                    snap["fraction"] = 1.0
                    snap["eta_s"] = 0.0
                self._last = snap
            gauges = (st or {}).get("gauges") or ()
        if gauges:
            from quokka_tpu import obs

            obs.REGISTRY.remove(*gauges)
        return snap

    def last_finished(self) -> Optional[dict]:
        """The most recently GC'd query's final progress snapshot (what
        ``bench.py --measure`` embeds in detail.progress)."""
        with self._lock:
            return self._last

    def reset(self) -> None:
        """Tests only."""
        with self._lock:
            self._q.clear()
            self._last = None


def _estimate(view: dict, profile: Optional[dict]):
    """(raw_fraction, basis, detail) from one ledger view + optional
    cardinality-profile prior.  Pure function of host-side ints — the
    known-answer tests drive it directly."""
    scanned = int(view.get("scanned_bytes", 0) or 0)
    detail: Dict[str, object] = {
        "source_bytes_done": scanned,
        "source_bytes_total": 0,
        "op_completion": None,
        "profiled_ops": 0,
    }
    prof_bytes = 0
    if isinstance(profile, dict):
        with contextlib.suppress(TypeError, ValueError):
            prof_bytes = int(profile.get("source_bytes", 0) or 0)
    if prof_bytes > 0:
        detail["source_bytes_total"] = prof_bytes
        scan_frac = min(1.0, scanned / prof_bytes)
        # per-operator completion against the profiled per-op max rows
        rows_prior = profile.get("rows")
        fracs = []
        if isinstance(rows_prior, dict):
            for key, rows_out in (view.get("op_rows_out") or {}).items():
                with contextlib.suppress(TypeError, ValueError):
                    want = int(rows_prior.get(key, 0) or 0)
                    if want > 0:
                        fracs.append(min(1.0, int(rows_out) / want))
        if fracs:
            op_frac = sum(fracs) / len(fracs)
            detail["op_completion"] = round(op_frac, 6)
            detail["profiled_ops"] = len(fracs)
            return 0.5 * scan_frac + 0.5 * op_frac, "cardprofile", detail
        return scan_frac, "cardprofile", detail
    hint = int(view.get("size_hint_bytes", 0) or 0)
    if hint > 0:
        detail["source_bytes_total"] = hint
        return min(1.0, scanned / hint), "size_hint", detail
    return 0.0, "none", detail


def refresh_live() -> None:
    """Snapshot every query the opstats ledger knows, refreshing the
    ``progress.fraction.*`` gauges — the history sampler calls this each
    tick so the no-progress alert rule sees fractions even when no client
    is polling /status or a handle."""
    from quokka_tpu.obs import opstats

    for qid in opstats.OPSTATS.live_queries():
        with contextlib.suppress(Exception):
            TRACKER.snapshot(qid)


TRACKER = ProgressTracker()
