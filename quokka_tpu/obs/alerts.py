"""Rule-driven alert engine: the health verdict over the metrics timeline.

Every rule is a pure predicate over (current sample, previous sample,
per-rule streak state) — samples being the history ring's typed registry
snapshots — returning a human-readable message when the condition holds.
The engine evaluates the rule table per sample (the history sampler's
cadence, or forced via ``evaluate_now()``), EDGE-TRIGGERED: entering the
firing state emits one flight-recorder ``alert.<rule>`` event and bumps the
``alert.<rule>`` counter; staying in it does neither; leaving it clears the
rule from the active set.  ``health()`` folds the active set into the
ok/degraded/critical verdict ``/health`` serves — the placement signal a
multi-replica router reads per replica.

Rule table (thresholds are env knobs, one per rule):

============== ======== ======================================================
rule           severity fires when
============== ======== ======================================================
channel_skew   warn     any per-edge ``shuffle.skew.<qid>.*`` gauge >=
                        QK_SKEW_RATIO (the opstats threshold)
watermark_lag  warn     any ``stream.watermark_lag_s*`` gauge >=
                        QK_ALERT_WM_LAG_S (default 30)
mem_budget     critical max ``mem.live_bytes*`` gauge >= QK_ALERT_MEM_PCT
                        (default 0.9) of the QK_SERVICE_MEM_BUDGET
queue_wait     warn     ``admission.queue_wait_s`` p95 >=
                        QK_ALERT_QUEUE_P95_S (default 10) while new waits
                        keep arriving (count moved since last sample)
no_progress    warn     some ``progress.fraction.<qid>`` gauge unchanged and
                        < 0.99 for QK_ALERT_STALL_EVALS (default 3)
                        consecutive samples — the stall-dump precursor
mem_leak       warn     ``mem.leaked`` counter moved since last sample
integrity      warn     ``integrity.corrupt`` counter moved since last
                        sample (chaos-detected checksum rejections)
============== ======== ======================================================
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

_WARN, _CRITICAL = "warn", "critical"


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _gauges(sample: dict, prefix: str, exact_too: bool = False
            ) -> Dict[str, float]:
    """Gauges under a dotted prefix (optionally the bare name too)."""
    g = sample.get("gauges") or {}
    out = {n: v for n, v in g.items() if n.startswith(prefix)}
    bare = prefix.rstrip(".")
    if exact_too and bare in g:
        out[bare] = g[bare]
    return out


def _counter_delta(cur: dict, prev: Optional[dict], name: str) -> float:
    v1 = (cur.get("counters") or {}).get(name, 0)
    v0 = ((prev or {}).get("counters") or {}).get(name, 0)
    return v1 - v0


# -- rule predicates: (cur, prev, state) -> Optional[message] ----------------
# state is a per-rule dict the engine persists between evaluations (streak
# counters live there); rules never touch the registry directly — they see
# only the sampled timeline, same as the operator.


def _rule_channel_skew(cur, prev, state):
    from quokka_tpu.obs import opstats

    thresh = opstats.skew_ratio_threshold()
    hot = {n: v for n, v in _gauges(cur, "shuffle.skew.").items()
           if v >= thresh}
    if not hot:
        return None
    worst = max(hot, key=hot.get)
    return (f"{len(hot)} exchange edge(s) at skew >= {thresh:g}; "
            f"worst {worst} = {hot[worst]:.2f}")


def _rule_watermark_lag(cur, prev, state):
    thresh = _envf("QK_ALERT_WM_LAG_S", 30.0)
    hot = {n: v for n, v in
           _gauges(cur, "stream.watermark_lag_s.", exact_too=True).items()
           if v >= thresh}
    if not hot:
        return None
    worst = max(hot, key=hot.get)
    return (f"watermark lag >= {thresh:g}s on {len(hot)} stream(s); "
            f"worst {worst} = {hot[worst]:.1f}s")


def _rule_mem_budget(cur, prev, state):
    from quokka_tpu.service import admission

    budget = admission.mem_budget_bytes()
    if budget <= 0:
        return None
    pct = _envf("QK_ALERT_MEM_PCT", 0.9)
    live = max(_gauges(cur, "mem.live_bytes.", exact_too=True).values(),
               default=0.0)
    if live < pct * budget:
        return None
    return (f"live tracked memory {int(live)} B is "
            f"{live / budget:.0%} of the {budget} B service budget")


def _rule_queue_wait(cur, prev, state):
    thresh = _envf("QK_ALERT_QUEUE_P95_S", 10.0)
    h = (cur.get("histograms") or {}).get("admission.queue_wait_s")
    if not h or h[0] == 0:
        return None
    # only while waits keep ARRIVING: the histogram is cumulative, so a
    # long-past pileup would otherwise pin the alert forever
    h0 = ((prev or {}).get("histograms") or {}).get(
        "admission.queue_wait_s", (0, 0.0))
    if h[0] <= h0[0]:
        return None
    from quokka_tpu import obs

    p95 = obs.REGISTRY.histogram("admission.queue_wait_s").quantile(0.95)
    if p95 is None or p95 < thresh:
        return None
    return f"admission queue wait p95 {p95:.1f}s >= {thresh:g}s"


def _rule_no_progress(cur, prev, state):
    need = max(1, int(_envf("QK_ALERT_STALL_EVALS", 3)))
    streaks: Dict[str, int] = state.setdefault("streaks", {})
    fracs = _gauges(cur, "progress.fraction.")
    prev_fracs = _gauges(prev, "progress.fraction.") if prev else {}
    stalled = []
    for name, v in fracs.items():
        if name in prev_fracs and v == prev_fracs[name] and v < 0.99:
            streaks[name] = streaks.get(name, 0) + 1
            if streaks[name] >= need:
                stalled.append((name, v))
        else:
            streaks.pop(name, None)
    for name in list(streaks):
        if name not in fracs:
            del streaks[name]  # query finished/GC'd: forget its streak
    if not stalled:
        return None
    name, v = stalled[0]
    qid = name.rsplit(".", 1)[-1]
    return (f"{len(stalled)} query(ies) made no progress for {need} "
            f"samples; e.g. {qid} stuck at {v:.0%}")


def _rule_mem_leak(cur, prev, state):
    d = _counter_delta(cur, prev, "mem.leaked")
    if d <= 0:
        return None
    return f"{int(d)} allocation(s) leaked past query GC since last sample"


def _rule_integrity(cur, prev, state):
    d = _counter_delta(cur, prev, "integrity.corrupt")
    if d <= 0:
        return None
    return f"{int(d)} checksum rejection(s) since last sample"


RULES = (
    ("channel_skew", _WARN, _rule_channel_skew),
    ("watermark_lag", _WARN, _rule_watermark_lag),
    ("mem_budget", _CRITICAL, _rule_mem_budget),
    ("queue_wait", _WARN, _rule_queue_wait),
    ("no_progress", _WARN, _rule_no_progress),
    ("mem_leak", _WARN, _rule_mem_leak),
    ("integrity", _WARN, _rule_integrity),
)


class AlertEngine:
    """Evaluates the rule table per sample and keeps the active set.  All
    state is under the engine's own lock; rule predicates run OUTSIDE it
    (they only read the passed samples + their private state dict)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev: Optional[dict] = None
        # rule name -> {"severity", "message", "since"}
        self._active: Dict[str, dict] = {}
        self._state: Dict[str, dict] = {}
        self._evaluated_at: Optional[float] = None

    def evaluate(self, sample: dict) -> List[dict]:
        """Run every rule against (sample, previous sample); returns the
        alerts that NEWLY fired this evaluation."""
        with self._lock:
            prev = self._prev
            states = {name: self._state.setdefault(name, {})
                      for name, _, _ in RULES}
        results = {}
        for name, severity, fn in RULES:
            msg = None
            try:
                msg = fn(sample, prev, states[name])
            except Exception as e:  # a broken rule must not sink the sampler
                from quokka_tpu import obs

                obs.diag(f"[alerts] rule {name} raised: {e!r}")
            results[name] = (severity, msg)
        fired = []
        now = sample.get("t", time.time())
        with self._lock:
            self._prev = sample
            self._evaluated_at = now
            for name, (severity, msg) in results.items():
                if msg is None:
                    self._active.pop(name, None)
                    continue
                ent = self._active.get(name)
                if ent is None:
                    ent = {"rule": name, "severity": severity,
                           "message": msg, "since": now}
                    self._active[name] = ent
                    fired.append(dict(ent))
                else:
                    ent["message"] = msg  # refresh text, keep the edge time
        from quokka_tpu import obs

        for ent in fired:
            obs.REGISTRY.counter(f"alert.{ent['rule']}").inc()
            obs.RECORDER.record(f"alert.{ent['rule']}", ent["message"],
                                severity=ent["severity"])
        self._export_health_gauge()
        return fired

    def evaluate_now(self) -> List[dict]:
        """Force one sample + evaluation (smokes/tests; also useful when
        the periodic sampler is disabled)."""
        from quokka_tpu.obs import history, progress

        progress.refresh_live()
        return self.evaluate(history.RING.record())

    def health(self) -> dict:
        """The /health verdict: critical if any active critical rule,
        degraded if anything at all is firing, ok otherwise."""
        with self._lock:
            firing = [dict(ent) for ent in self._active.values()]
            evaluated_at = self._evaluated_at
        if any(f["severity"] == _CRITICAL for f in firing):
            status = "critical"
        elif firing:
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "firing": sorted(firing, key=lambda f: f["rule"]),
            "evaluated_at": evaluated_at,
        }

    def _export_health_gauge(self) -> None:
        from quokka_tpu import obs

        status = self.health()["status"]
        obs.REGISTRY.gauge("health.status").set(
            {"ok": 0, "degraded": 1, "critical": 2}[status])

    def reset(self) -> None:
        """Tests only."""
        with self._lock:
            self._prev = None
            self._active.clear()
            self._state.clear()
            self._evaluated_at = None


ENGINE = AlertEngine()
