"""Coordinator-side timeline merger, Chrome-trace export, stall reports.

Per-worker flight-recorder streams (shipped through the control store as
incremental snapshots) merge into one wall-clock-ordered timeline.  Two
renderings:

- Chrome trace-event JSON — ``{"traceEvents": [...]}`` — loadable in
  Perfetto (ui.perfetto.dev -> "Open trace file") or chrome://tracing.
  Spans become complete ("X") events with start = end - duration; instants
  become "i" events.  One Perfetto "process" track per worker plus the
  coordinator, one thread track per recorded thread.
- a human-readable stall report: per-worker liveness (heartbeat age, last
  progress, in-flight task from the coordinator's pop records), pending
  task-queue depths, each worker's last events, and a one-line verdict
  naming the stuck worker and its in-flight task.

``dump_flight`` ties them together: on heartbeat silence or coordinator
timeout the distributed runtime writes both files into ``QK_DUMP_DIR``
(default ``<tmp>/quokka_tpu_dumps``) instead of dying with a bare timeout.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Tuple

# a worker whose heartbeat is older than this while peers stay fresh is
# presumed wedged (heartbeats flow at 0.2 s between dispatches; only a
# dispatch that never returns silences them for seconds)
STUCK_AFTER_S = 2.0


def merge_streams(streams: Dict[str, Sequence[tuple]]) -> List[dict]:
    """{stream_name: [recorder event tuples]} -> one ordered timeline of
    dicts.  Ordering is (wall-clock ts, stream, seq): recorder timestamps
    are ``time.time()`` precisely so cross-process streams share an axis;
    same-process ties break on the ring sequence number, which preserves
    each stream's own order (monotone by construction)."""
    merged: List[dict] = []
    for pid, evs in streams.items():
        for e in evs:
            seq, ts, kind, name, dur, thread, args = e
            merged.append({
                "pid": str(pid), "seq": int(seq), "ts": float(ts),
                "kind": kind, "name": name, "dur": float(dur),
                "tid": thread, "args": dict(args) if args else {},
            })
    merged.sort(key=lambda d: (d["ts"], d["pid"], d["seq"]))
    return merged


def to_chrome_trace(merged: Sequence[dict]) -> dict:
    """Chrome trace-event JSON (https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU)
    from a merged timeline.  Timestamps are microseconds relative to the
    earliest event start so Perfetto's viewport lands on the data."""
    if merged:
        t0 = min(d["ts"] - d["dur"] for d in merged)
    else:
        t0 = 0.0
    events = []
    for d in merged:
        name = d["name"] or d["kind"]
        base = {
            "name": name,
            "cat": d["kind"],
            "pid": d["pid"],
            "tid": d["tid"],
            "args": d["args"],
        }
        if d["dur"] > 0:
            base.update(ph="X", ts=round((d["ts"] - d["dur"] - t0) * 1e6, 1),
                        dur=round(d["dur"] * 1e6, 1))
        else:
            base.update(ph="i", s="t", ts=round((d["ts"] - t0) * 1e6, 1))
        events.append(base)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"t0_unix_s": t0}}


def write_chrome_trace(path: str, merged: Sequence[dict]) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_chrome_trace(merged), f)
    return path


# ---------------------------------------------------------------------------
# Stall analysis
# ---------------------------------------------------------------------------


def find_stuck(heartbeats: Dict[int, float],
               inflight: Dict[int, tuple],
               now: Optional[float] = None) -> List[Tuple[int, float, tuple]]:
    """[(worker_id, heartbeat_age_s, inflight_record_or_None)] for every
    worker whose heartbeat has been silent past STUCK_AFTER_S, oldest
    first.  ``inflight`` is the coordinator-side pop record
    {worker: (actor, channel, task_kind, popped_at)}."""
    now = time.time() if now is None else now
    out = []
    for w, hb in heartbeats.items():
        age = now - hb
        if age > STUCK_AFTER_S:
            out.append((w, age, inflight.get(w)))
    out.sort(key=lambda x: -x[1])
    return out


def stuck_headline(stuck: List[Tuple[int, float, tuple]],
                   have_heartbeats: bool = True) -> str:
    if not stuck:
        if not have_heartbeats:
            # embedded run, or workers never got as far as a heartbeat —
            # claiming "all heartbeats fresh" here would be a false verdict
            return ("no per-worker heartbeat data (embedded engine, or "
                    "workers never heartbeated) — see the event tail below")
        return "no worker looks wedged (all heartbeats fresh)"
    w, age, rec = stuck[0]
    if rec is not None:
        actor, ch, kind = rec[0], rec[1], rec[2]
        args = rec[4] if len(rec) > 4 and rec[4] else None
        return (f"stuck worker {w}: in-flight {kind} task "
                f"(actor {actor}, channel {ch}"
                + (f", {args}" if args else "")
                + f") — heartbeat silent {age:.1f}s")
    return f"stuck worker {w}: heartbeat silent {age:.1f}s (no task popped)"


def _drop_total(v) -> int:
    """Drop counts arrive as a plain int (legacy worker states) or as the
    recorder's per-event-type dict — normalize to a total."""
    if isinstance(v, dict):
        return sum(int(n) for n in v.values())
    return int(v or 0)


def _fmt_drops(v) -> str:
    if isinstance(v, dict):
        by = ",".join(f"{k}:{n}" for k, n in sorted(v.items()) if n)
        return f"{_drop_total(v)}({by})"
    return str(int(v))


def stall_report(reason: str,
                 merged: Sequence[dict],
                 heartbeats: Dict[int, float],
                 states: Dict[int, object],
                 inflight: Dict[int, tuple],
                 ntt_depth: Optional[Dict] = None,
                 now: Optional[float] = None,
                 last_n: int = 15,
                 dropped: Optional[Dict[str, int]] = None) -> str:
    now = time.time() if now is None else now
    lines = ["==== quokka-tpu stall report ====", f"reason: {reason}",
             f"wall clock: {now:.3f}"]
    stuck = find_stuck(heartbeats, inflight, now)
    lines.append(
        f"verdict: {stuck_headline(stuck, have_heartbeats=bool(heartbeats))}")
    drops = {p: n for p, n in (dropped or {}).items() if _drop_total(n)}
    if drops:
        # a wrapped ring means the analysis below is missing its earliest
        # tail — say so before anyone trusts the timeline
        lines.append("WARNING: flight-recorder ring(s) dropped events "
                     "(oldest overwritten; raise QK_TRACE_BUFFER): "
                     + ", ".join(f"{p}={_fmt_drops(n)}"
                                 for p, n in sorted(drops.items())))
    workers = sorted(set(heartbeats) | set(states) | set(inflight))
    lines.append(f"workers ({len(workers)}):")
    for w in workers:
        hb = heartbeats.get(w)
        hb_s = f"heartbeat {now - hb:.1f}s ago" if hb else "no heartbeat yet"
        flight = inflight.get(w)
        if flight is not None:
            actor, ch, kind, t = flight[0], flight[1], flight[2], flight[3]
            args = flight[4] if len(flight) > 4 and flight[4] else None
            fl_s = (f"last pop: {kind} task (actor {actor}, channel {ch}) "
                    f"{now - t:.1f}s ago"
                    + (f" [{args}]" if args else ""))
        else:
            fl_s = "last pop: none"
        wedged = any(sw == w for sw, _, _ in stuck)
        lines.append(f"  worker {w}: {hb_s}; {fl_s}"
                     + ("  <-- WEDGED" if wedged else ""))
        st = states.get(w)
        if st is not None:
            lines.append(f"    state: {_render_state(st, now)}")
    if ntt_depth:
        pending = {str(k): v for k, v in sorted(ntt_depth.items()) if v}
        lines.append(f"pending task queues (actor -> depth): {pending}")
    by_pid: Dict[str, List[dict]] = {}
    for d in merged:
        by_pid.setdefault(d["pid"], []).append(d)
    for pid in sorted(by_pid):
        evs = by_pid[pid][-last_n:]
        lines.append(f"last {len(evs)} event(s) of {pid}:")
        for d in evs:
            dur = f" dur={d['dur'] * 1e3:.2f}ms" if d["dur"] else ""
            args = f" {d['args']}" if d["args"] else ""
            lines.append(f"  {d['ts']:.6f} [{d['tid']}] "
                         f"{d['kind']}:{d['name']}{dur}{args}")
    lines.append("=" * 33)
    return "\n".join(lines) + "\n"


def _render_state(st, now: float) -> str:
    """WorkerState (runtime/state.py) or any mapping shipped in a heartbeat."""
    d = getattr(st, "__dict__", None) or (st if isinstance(st, dict) else {})
    parts = []
    for k, v in d.items():
        if k in ("last_progress", "ts") and isinstance(v, (int, float)) and v:
            parts.append(f"{k}={now - v:.1f}s ago")
        else:
            parts.append(f"{k}={v}")
    return ", ".join(parts) if parts else repr(st)


# ---------------------------------------------------------------------------
# Dump orchestration
# ---------------------------------------------------------------------------


def dump_dir() -> str:
    return os.environ.get("QK_DUMP_DIR") or os.path.join(
        tempfile.gettempdir(), "quokka_tpu_dumps")


def dump_flight(reason: str,
                streams: Dict[str, Sequence[tuple]],
                heartbeats: Optional[Dict[int, float]] = None,
                states: Optional[Dict[int, object]] = None,
                inflight: Optional[Dict[int, tuple]] = None,
                ntt_depth: Optional[Dict] = None,
                directory: Optional[str] = None,
                echo: bool = True,
                dropped: Optional[Dict[str, int]] = None) -> Tuple[str, str, str]:
    """Write the merged Chrome trace + stall report (with per-query
    critical-path attribution appended); returns (trace_path, report_path,
    one-line headline).  Never raises: a failed dump must not mask the
    stall it is describing."""
    heartbeats = heartbeats or {}
    try:
        merged = merge_streams(streams)
        d = directory or dump_dir()
        os.makedirs(d, exist_ok=True)
        stamp = f"{os.getpid()}-{int(time.time())}"
        trace_path = os.path.join(d, f"flight-{stamp}.trace.json")
        report_path = os.path.join(d, f"flight-{stamp}.report.txt")
        write_chrome_trace(trace_path, merged)
        if dropped is None:
            from quokka_tpu.obs.recorder import RECORDER

            dropped = {"local": RECORDER.dropped}
        report = stall_report(reason, merged, heartbeats, states or {},
                              inflight or {}, ntt_depth, dropped=dropped)
        headline = stuck_headline(find_stuck(heartbeats, inflight or {}),
                                  have_heartbeats=bool(heartbeats))
        with open(report_path, "w", encoding="utf-8") as f:
            f.write(report)
            # where the wall time of each in-flight query went, so a stall
            # triage starts from attribution, not from raw events
            with contextlib.suppress(Exception):
                from quokka_tpu.obs import critpath as _critpath

                for cp in _critpath.summarize_queries(merged):
                    f.write(cp.render() + "\n")
            # the operator-statistics ledger for every in-flight query:
            # where each operator's rows had gotten to (and which exchange
            # edges were skewed) at the moment the run wedged
            with contextlib.suppress(Exception):
                from quokka_tpu.obs import explain as _explain
                from quokka_tpu.obs import opstats as _opstats

                for qid in _opstats.OPSTATS.live_queries():
                    snap = _opstats.OPSTATS.snapshot(qid)
                    if snap:
                        f.write("---- opstats at stall ----\n")
                        f.write(_explain.render(snap) + "\n")
            f.write(f"chrome trace: {trace_path} "
                    f"(load at ui.perfetto.dev)\n")
        if echo:
            sys.stderr.write(report)
            sys.stderr.write(f"[flight-recorder] merged trace: {trace_path}; "
                             f"report: {report_path}\n")
            sys.stderr.flush()
        return trace_path, report_path, headline
    except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
        with contextlib.suppress(OSError, ValueError):
            sys.stderr.write(f"[flight-recorder] dump failed: {e!r}\n")
        return "", "", f"(flight dump failed: {e!r})"
