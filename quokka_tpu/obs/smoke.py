"""Observability smoke: critical-path attribution reconciles with the wall
clock and the /metrics //status sidecar serves a live service.

    python -m quokka_tpu.obs.smoke      (or: make obs-smoke)

Three assertions, seconds of wall time, exit nonzero on any failure:

1. a real query profiled with ``critpath.profile()`` attributes its wall
   time into buckets whose sum reconciles with the measured wall clock
   within 10% (the ISSUE 5 acceptance bound);
2. ``/metrics`` during a live 2-query QueryService run returns Prometheus
   text exposition containing the task-latency histogram families;
3. ``/status`` returns JSON naming both running/finished queries and the
   admission budget.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request


def _table(n=120_000, seed=0):
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(seed)
    return pa.table({"k": r.integers(0, 32, n).astype(np.int64),
                     "v": r.integers(0, 1000, n).astype(np.int64)})


def _query(ctx, table):
    return ctx.from_arrow(table).groupby("k").agg_sql(
        "sum(v) as sv, count(*) as n")


def main() -> int:
    from quokka_tpu import QuokkaContext
    from quokka_tpu.obs import critpath
    from quokka_tpu.obs.export import MetricsServer
    from quokka_tpu.service import QueryService

    table = _table()
    ctx = QuokkaContext()
    _query(ctx, table).collect()  # warm: compiles are not the subject here

    # -- 1. critical-path buckets reconcile with the wall clock -------------
    t0 = time.time()
    with critpath.profile() as prof:
        df = _query(ctx, table).collect()
    wall = time.time() - t0
    assert len(df) > 0
    cp = prof.result
    if cp is None:
        print("obs-smoke: FAIL — no critical path (recorder disabled? "
              "unset QK_TRACE_EVENTS)", file=sys.stderr)
        return 1
    total = sum(cp.buckets.values())
    print(cp.render())
    ratio = total / wall if wall > 0 else 0.0
    print(f"obs-smoke: buckets sum {total * 1e3:.1f}ms vs measured wall "
          f"{wall * 1e3:.1f}ms (ratio {ratio:.3f})")
    if not 0.9 <= ratio <= 1.1:
        print("obs-smoke: FAIL — critical-path buckets do not reconcile "
              "with the measured wall time within 10%", file=sys.stderr)
        return 1

    # -- 2./3. live scrape of a 2-query service run -------------------------
    with QueryService(pool_size=2) as svc:
        server = MetricsServer(port=0, service=svc)
        try:
            handles = [svc.submit(_query(QuokkaContext(), _table(seed=i)))
                       for i in (1, 2)]
            # scrape MID-RUN (best effort: tiny queries may finish first),
            # then after completion, when the histograms must be populated
            mid = urllib.request.urlopen(server.url("/metrics"),
                                         timeout=10).read().decode()
            for h in handles:
                h.result(timeout=300)
            text = urllib.request.urlopen(server.url("/metrics"),
                                          timeout=10).read().decode()
            status = json.loads(urllib.request.urlopen(
                server.url("/status"), timeout=10).read().decode())
        finally:
            server.close()
    for needle in ("quokka_task_latency_all_seconds_bucket",
                   "quokka_task_latency_all_seconds_count",
                   'le="+Inf"'):
        if needle not in text:
            print(f"obs-smoke: FAIL — /metrics missing {needle!r}",
                  file=sys.stderr)
            return 1
    svc_stats = status.get("service") or {}
    done = (svc_stats.get("finished", 0)
            + len(svc_stats.get("sessions", {})))
    if done < 2:
        print(f"obs-smoke: FAIL — /status saw {done} of 2 queries: "
              f"{json.dumps(svc_stats)[:400]}", file=sys.stderr)
        return 1
    if "admission" not in svc_stats:
        print("obs-smoke: FAIL — /status missing admission stats",
              file=sys.stderr)
        return 1
    print(f"obs-smoke: scraped {len(mid)}B mid-run and {len(text)}B "
          f"post-run of Prometheus text; /status reported "
          f"{done} queries, admission budget "
          f"{svc_stats['admission'].get('budget_bytes', '?')}")
    print("obs-smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
