"""SQL expression parser.

The reference leans on sqlglot for all SQL-text surfaces (filter_sql,
with_columns_sql, agg_sql, transform_sql — pyquokka/datastream.py) and on
DuckDB to execute what Polars can't.  Neither exists in this environment, so
quokka-tpu ships its own tokenizer + Pratt parser that lowers SQL scalar and
aggregate expressions directly into the quokka_tpu.expression AST (which then
compiles to JAX kernels).  Coverage target: the expression surface TPC-H and
the reference's apps/ actually use — arithmetic, comparisons, AND/OR/NOT,
LIKE/IN/BETWEEN/IS NULL, CASE, CAST, date/interval literals and arithmetic,
EXTRACT, string functions, aggregate calls incl. COUNT(DISTINCT x).
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

from quokka_tpu.expression import (
    Agg,
    Alias,
    BinOp,
    Case,
    Cast,
    ColRef,
    DateLit,
    DtField,
    Expr,
    Func,
    InList,
    IntervalLit,
    IsNull,
    Literal,
    StrOp,
    UnaryOp,
)

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*(?:\.[A-Za-z_][A-Za-z_0-9]*)*)
  | (?P<op><=|>=|<>|!=|\|\||==|[(),*+\-/%=<>])
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "and", "or", "not", "in", "like", "between", "is", "null", "case", "when",
    "then", "else", "end", "cast", "as", "date", "timestamp", "interval", "true",
    "false", "distinct", "extract", "from", "asc", "desc", "by",
}
# statement-level words stay ordinary identifiers inside expressions (columns
# named `left`, `order`, `on`, ... must keep parsing in filter_sql/agg_sql);
# parse_select matches them contextually via Parser.accept_word
STATEMENT_WORDS = {
    "select", "where", "group", "having", "order", "limit", "join", "inner",
    "left", "semi", "anti", "on",
}


class Token:
    __slots__ = ("kind", "text")

    def __init__(self, kind, text):
        self.kind = kind
        self.text = text

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def tokenize(s: str) -> List[Token]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise ValueError(f"cannot tokenize SQL at: {s[pos:pos+30]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        if m.lastgroup == "ident" and text.lower() in KEYWORDS:
            out.append(Token("kw", text.lower()))
        else:
            out.append(Token(m.lastgroup, text))
    out.append(Token("eof", ""))
    return out


class Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    # -- token helpers -------------------------------------------------------
    def peek(self, ahead=0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i = min(self.i + 1, len(self.toks) - 1)
        return t

    def accept(self, kind, text=None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (text is None or t.text == text):
            return self.next()
        return None

    def expect(self, kind, text=None) -> Token:
        t = self.accept(kind, text)
        if t is None:
            raise ValueError(f"expected {text or kind}, got {self.peek()}")
        return t

    # statement-level words are ordinary identifiers in expression context;
    # match them contextually (kind may be ident or kw)
    def peek_word(self, word: str) -> bool:
        t = self.peek()
        return t.kind in ("ident", "kw") and t.text.lower() == word

    def accept_word(self, word: str) -> Optional[Token]:
        if self.peek_word(word):
            return self.next()
        return None

    def expect_word(self, word: str) -> Token:
        t = self.accept_word(word)
        if t is None:
            raise ValueError(f"expected {word}, got {self.peek()}")
        return t

    # -- grammar -------------------------------------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        e = self.parse_and()
        while self.accept("kw", "or"):
            e = BinOp("or", e, self.parse_and())
        return e

    def parse_and(self) -> Expr:
        e = self.parse_not()
        while self.accept("kw", "and"):
            e = BinOp("and", e, self.parse_not())
        return e

    def parse_not(self) -> Expr:
        if self.accept("kw", "not"):
            return UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        e = self.parse_additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("=", "==", "!=", "<>", "<", "<=", ">", ">="):
                self.next()
                op = {"==": "=", "<>": "!="}.get(t.text, t.text)
                e = BinOp(op, e, self.parse_additive())
            elif t.kind == "kw" and t.text == "like":
                self.next()
                pat = self.expect("str").text
                e = StrOp("like", e, [_unquote(pat)])
            elif t.kind == "kw" and t.text == "in":
                self.next()
                e = self._parse_in(e, negated=False)
            elif t.kind == "kw" and t.text == "between":
                self.next()
                lo = self.parse_additive()
                self.expect("kw", "and")
                hi = self.parse_additive()
                e = BinOp("and", BinOp(">=", e, lo), BinOp("<=", e, hi))
            elif t.kind == "kw" and t.text == "is":
                self.next()
                negated = bool(self.accept("kw", "not"))
                self.expect("kw", "null")
                e = IsNull(e, negated)
            elif t.kind == "kw" and t.text == "not":
                self.next()
                if self.accept("kw", "like"):
                    pat = self.expect("str").text
                    e = UnaryOp("not", StrOp("like", e, [_unquote(pat)]))
                elif self.accept("kw", "in"):
                    e = self._parse_in(e, negated=True)
                elif self.accept("kw", "between"):
                    lo = self.parse_additive()
                    self.expect("kw", "and")
                    hi = self.parse_additive()
                    e = UnaryOp("not", BinOp("and", BinOp(">=", e, lo), BinOp("<=", e, hi)))
                else:
                    raise ValueError(f"unexpected NOT at {self.peek()}")
            else:
                return e

    def _parse_in(self, e: Expr, negated: bool) -> Expr:
        self.expect("op", "(")
        values = []
        while True:
            t = self.next()
            if t.kind == "str":
                values.append(_unquote(t.text))
            elif t.kind == "num":
                values.append(_num(t.text))
            else:
                raise ValueError(f"IN list supports literals only, got {t}")
            if not self.accept("op", ","):
                break
        self.expect("op", ")")
        return InList(e, values, negated)

    def parse_additive(self) -> Expr:
        e = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("+", "-"):
                self.next()
                e = BinOp(t.text, e, self.parse_multiplicative())
            elif t.kind == "op" and t.text == "||":
                self.next()
                e = Func("concat", [e, self.parse_multiplicative()])
            else:
                return e

    def parse_multiplicative(self) -> Expr:
        e = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("*", "/", "%"):
                self.next()
                e = BinOp(t.text, e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> Expr:
        if self.accept("op", "-"):
            return UnaryOp("-", self.parse_unary())
        if self.accept("op", "+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "num":
            self.next()
            return Literal(_num(t.text))
        if t.kind == "str":
            self.next()
            return Literal(_unquote(t.text))
        if t.kind == "op" and t.text == "(":
            self.next()
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if t.kind == "op" and t.text == "*":
            self.next()
            return Literal("*")  # only meaningful inside count(*)
        if t.kind == "kw":
            if t.text in ("date", "timestamp"):
                self.next()
                s = self.expect("str").text
                return DateLit(_unquote(s))
            if t.text == "interval":
                self.next()
                s = self.peek()
                if s.kind == "str":
                    self.next()
                    parts = _unquote(s.text).split()
                    if len(parts) == 2:
                        return IntervalLit(float(parts[0]), parts[1])
                    n = float(parts[0])
                else:
                    n = _num(self.expect("num").text)
                unit = self.expect("ident").text
                return IntervalLit(float(n), unit)
            if t.text == "case":
                self.next()
                return self._parse_case()
            if t.text == "cast":
                self.next()
                self.expect("op", "(")
                e = self.parse_expr()
                self.expect("kw", "as")
                ty = []
                while not self.accept("op", ")"):
                    ty.append(self.next().text)
                return Cast(e, " ".join(ty))
            if t.text == "extract":
                self.next()
                self.expect("op", "(")
                field = self.next().text.lower()
                self.expect("kw", "from")
                e = self.parse_expr()
                self.expect("op", ")")
                return DtField(field, e)
            if t.text == "true":
                self.next()
                return Literal(True)
            if t.text == "false":
                self.next()
                return Literal(False)
            if t.text == "null":
                self.next()
                return Literal(None)
        if t.kind == "ident":
            self.next()
            if self.peek().kind == "op" and self.peek().text == "(":
                return self._parse_call(t.text)
            name = t.text.split(".")[-1]  # strip table qualifier
            return ColRef(name)
        raise ValueError(f"unexpected token {t}")

    def _parse_case(self) -> Expr:
        whens: List[Tuple[Expr, Expr]] = []
        # support both searched CASE and simple CASE <operand>
        operand = None
        if not (self.peek().kind == "kw" and self.peek().text == "when"):
            operand = self.parse_expr()
        while self.accept("kw", "when"):
            cond = self.parse_expr()
            if operand is not None:
                cond = BinOp("=", operand, cond)
            self.expect("kw", "then")
            val = self.parse_expr()
            whens.append((cond, val))
        default = None
        if self.accept("kw", "else"):
            default = self.parse_expr()
        self.expect("kw", "end")
        return Case(whens, default)

    def _parse_call(self, name: str) -> Expr:
        name = name.lower()
        self.expect("op", "(")
        distinct = bool(self.accept("kw", "distinct"))
        args: List[Expr] = []
        if not self.accept("op", ")"):
            while True:
                args.append(self.parse_expr())
                if not self.accept("op", ","):
                    break
            self.expect("op", ")")
        return build_call(name, args, distinct)


AGG_FUNCS = {"sum", "avg", "mean", "min", "max", "count", "stddev", "var"}
STR_FUNCS = {"upper", "lower", "length", "trim", "ltrim", "rtrim", "contains",
             "starts_with", "ends_with"}
MATH_FUNCS = {"abs", "round", "sqrt", "exp", "ln", "log", "floor", "ceil",
              "ceiling", "power", "pow", "sin", "cos", "coalesce", "greatest",
              "least", "sign"}
DT_FUNCS = {"year", "month", "day", "hour", "minute", "second", "weekday"}


def build_call(name: str, args: List[Expr], distinct: bool = False) -> Expr:
    if name in AGG_FUNCS:
        arg = args[0] if args else None
        if isinstance(arg, Literal) and arg.value == "*":
            arg = None
        if name == "mean":
            name = "avg"
        return Agg(name, arg, distinct)
    if name in ("substring", "substr"):
        off = args[1].value if isinstance(args[1], Literal) else 1
        length = args[2].value if len(args) > 2 and isinstance(args[2], Literal) else None
        return StrOp("slice", args[0], [int(off) - 1, length])  # SQL is 1-based
    if name in STR_FUNCS:
        base = args[0]
        extra = [a.value if isinstance(a, Literal) else a for a in args[1:]]
        op = {"trim": "strip", "ltrim": "strip", "rtrim": "strip"}.get(name, name)
        return StrOp(op, base, extra)
    if name in DT_FUNCS:
        return DtField(name, args[0])
    if name == "date_trunc":
        return Func("date_trunc", args)
    if name in MATH_FUNCS:
        if name == "ceiling":
            name = "ceil"
        if name == "pow":
            name = "power"
        return Func(name, args)
    if name == "list_contains":
        return Func("list_contains", args)
    return Func(name, args)


def _unquote(s: str) -> str:
    return s[1:-1].replace("''", "'")


def _num(s: str):
    return float(s) if ("." in s or "e" in s or "E" in s) else int(s)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def parse_expression(sql: str) -> Expr:
    """Parse one SQL scalar/boolean expression."""
    p = Parser(tokenize(sql))
    e = p.parse_expr()
    if p.peek().kind != "eof":
        raise ValueError(f"trailing tokens in SQL expression: {p.peek()}")
    return e


def parse_select_list(sql: str) -> List[Expr]:
    """Parse 'expr [as name], expr [as name], ...' (the agg_sql /
    with_columns_sql surface).  Returns Alias-wrapped expressions."""
    p = Parser(tokenize(sql))
    out = []
    while True:
        e = p.parse_expr()
        if p.accept("kw", "as"):
            name = p.expect("ident").text
            e = Alias(e, name)
        elif p.peek().kind == "ident":
            # implicit alias: "sum(x) total"
            name = p.next().text
            e = Alias(e, name)
        out.append(e)
        if not p.accept("op", ","):
            break
    if p.peek().kind != "eof":
        raise ValueError(f"trailing tokens in select list: {p.peek()}")
    return out


class SelectStatement:
    """Parsed SELECT: tables + join specs + clauses (the frontend surface of
    the reference's experimental SQL tier, pyquokka/sql.py:74)."""

    def __init__(self):
        self.select: List[Expr] = []
        self.distinct = False
        self.table: str = ""
        self.joins: List[Tuple[str, str, Expr]] = []  # (how, table, on-expr)
        self.where: Optional[Expr] = None
        self.group_by: List[str] = []
        self.having: Optional[Expr] = None
        self.order_by: List[Tuple[str, bool]] = []
        self.limit: Optional[int] = None


def parse_select(sql: str) -> SelectStatement:
    p = Parser(tokenize(sql))
    st = SelectStatement()
    p.expect_word("select")
    st.distinct = bool(p.accept("kw", "distinct"))
    while True:
        e = p.parse_expr()
        if p.accept("kw", "as"):
            e = Alias(e, p.expect("ident").text)
        elif p.peek().kind == "ident" and p.peek().text.lower() not in STATEMENT_WORDS:
            e = Alias(e, p.next().text)
        st.select.append(e)
        if not p.accept("op", ","):
            break
    p.expect("kw", "from")
    st.table = p.expect("ident").text
    while True:
        how = None
        if p.accept_word("join") or (p.accept_word("inner") and p.expect_word("join")):
            how = "inner"
        elif any(p.peek_word(w) for w in ("left", "semi", "anti")):
            how = p.next().text.lower()
            p.expect_word("join")
        else:
            break
        tname = p.expect("ident").text
        p.expect_word("on")
        cond = p.parse_expr()
        st.joins.append((how, tname, cond))
    if p.accept_word("where"):
        st.where = p.parse_expr()
    if p.accept_word("group"):
        p.expect("kw", "by")
        while True:
            st.group_by.append(p.expect("ident").text.split(".")[-1])
            if not p.accept("op", ","):
                break
    if p.accept_word("having"):
        st.having = p.parse_expr()
    if p.accept_word("order"):
        p.expect("kw", "by")
        while True:
            name = p.expect("ident").text.split(".")[-1]
            desc = bool(p.accept("kw", "desc"))
            if not desc:
                p.accept("kw", "asc")
            st.order_by.append((name, desc))
            if not p.accept("op", ","):
                break
    if p.accept_word("limit"):
        st.limit = int(_num(p.expect("num").text))
    if p.peek().kind != "eof":
        raise ValueError(f"trailing tokens in SELECT: {p.peek()}")
    return st


def parse_order_by(sql: str) -> List[Tuple[str, bool]]:
    """Parse 'col [asc|desc], ...' -> [(col, descending)]."""
    p = Parser(tokenize(sql))
    out = []
    while True:
        name = p.expect("ident").text
        desc = False
        if p.accept("kw", "desc"):
            desc = True
        elif p.accept("kw", "asc"):
            pass
        out.append((name, desc))
        if not p.accept("op", ","):
            break
    return out
