"""DataStream: the lazy relational API.

Mirrors the reference's surface (pyquokka/datastream.py:15-2192): every method
appends a logical Node to the context's plan and returns a new stream; nothing
executes until collect()/compute()/count().  SQL-string variants (filter_sql,
with_columns_sql, agg_sql, transform_sql) go through quokka_tpu.sqlparse
instead of sqlglot.
"""

from __future__ import annotations

import functools

from typing import Callable, Dict, List, Optional, Sequence, Union

from quokka_tpu import logical, sqlparse
from quokka_tpu.expression import (
    Agg,
    Alias,
    ColRef,
    Expr,
    IsNull,
    col,
    conjoin,
    lit_wrap,
)
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops.batch import DeviceBatch
from quokka_tpu.ops.expr_compile import evaluate_to_column, plan_aggregation


class DataStream:
    def __init__(self, ctx, node_id: int):
        self.ctx = ctx
        self.node_id = node_id

    # -- plan plumbing -------------------------------------------------------
    @property
    def _node(self) -> logical.Node:
        return self.ctx.nodes[self.node_id]

    @property
    def schema(self) -> List[str]:
        return list(self._node.schema)

    def _child(self, node: logical.Node) -> "DataStream":
        nid = self.ctx.add_node(node)
        return DataStream(self.ctx, nid)

    def _ordered_child(self, node: logical.Node) -> "OrderedStream":
        nid = self.ctx.add_node(node)
        return OrderedStream(self.ctx, nid)

    def explain(self) -> str:
        return self.ctx.explain(self.node_id)

    # -- execution -----------------------------------------------------------
    def collect(self):
        """Execute and return a pandas DataFrame (the reference returns a
        Polars DF, datastream.py:75)."""
        ds = self.compute()
        df = ds.to_df()
        if df is None:
            import pandas as pd

            return pd.DataFrame(columns=self.schema)
        return df

    def to_arrow(self):
        return self.compute().to_arrow()

    def compute(self):
        """Execute and return the materialized ResultDataset."""
        return self.ctx.execute_node(self.node_id)

    def count(self) -> int:
        df = self.aggregate_sql("count(*) as count").collect()
        return int(df["count"][0])

    # -- row ops ---------------------------------------------------------------
    def filter(self, predicate: Union[Expr, str]) -> "DataStream":
        if isinstance(predicate, str):
            return self.filter_sql(predicate)
        assert isinstance(predicate, Expr)
        missing = predicate.required_columns() - set(self.schema)
        if missing:
            raise ValueError(f"filter references unknown columns {missing}")
        return self._child(logical.FilterNode([self.node_id], self.schema, predicate))

    def filter_sql(self, sql: str) -> "DataStream":
        return self.filter(sqlparse.parse_expression(sql))

    def select(self, columns: Sequence[str]) -> "DataStream":
        columns = [columns] if isinstance(columns, str) else list(columns)
        missing = set(columns) - set(self.schema)
        if missing:
            raise ValueError(f"select references unknown columns {missing}")
        return self._child(logical.ProjectionNode([self.node_id], columns))

    def drop(self, columns: Sequence[str]) -> "DataStream":
        columns = [columns] if isinstance(columns, str) else list(columns)
        return self.select([c for c in self.schema if c not in set(columns)])

    def rename(self, mapping: Dict[str, str]) -> "DataStream":
        new_schema = [mapping.get(c, c) for c in self.schema]
        return self._child(
            logical.MapNode([self.node_id], new_schema, logical.RenameFn(mapping),
                            rename=dict(mapping))
        )

    def with_columns(self, exprs: Dict[str, Union[Expr, str]]) -> "DataStream":
        compiled = {
            k: (sqlparse.parse_expression(v) if isinstance(v, str) else v)
            for k, v in exprs.items()
        }
        new_schema = self.schema + [k for k in compiled if k not in self.schema]
        return self._child(
            logical.MapNode(
                [self.node_id], new_schema, logical.WithColumnsFn(compiled),
                exprs=compiled,
            )
        )

    def with_columns_sql(self, sql: str) -> "DataStream":
        exprs = sqlparse.parse_select_list(sql)
        named = {}
        for e in exprs:
            if not isinstance(e, Alias):
                raise ValueError(f"with_columns_sql needs 'expr as name': {e.sql()}")
            named[e.name] = e.expr
        return self.with_columns(named)

    def transform(self, fn: Callable, new_schema: List[str]) -> "DataStream":
        """Arbitrary per-batch UDF over a pandas DataFrame (host round-trip,
        like the reference's polars UDFs, datastream.py:652)."""

        def wrapped(b: DeviceBatch) -> Optional[DeviceBatch]:
            import pyarrow as pa

            df = bridge.to_pandas(b)
            out = fn(df)
            if out is None or len(out) == 0:
                return None
            return bridge.arrow_to_device(pa.Table.from_pandas(out, preserve_index=False))

        return self._child(
            logical.MapNode([self.node_id], new_schema, wrapped, declared=True)
        )

    def stateful_transform(self, executor, new_schema: List[str],
                           required_columns=None, by=None,
                           placement=None) -> "DataStream":
        """Run a user Executor over the stream, optionally key-partitioned
        (datastream.py:1312).  placement: a runtime/placement.py strategy
        (e.g. SingleChannelStrategy for unsharded state, or
        TaggedCustomChannelsStrategy to pin channels to tagged workers) —
        reference placement_strategy kwarg, datastream.py:1312."""
        from quokka_tpu.target_info import HashPartitioner, PassThroughPartitioner

        part = HashPartitioner(list(by)) if by else PassThroughPartitioner()
        import copy as _copy

        node = logical.StatefulNode(
            [self.node_id],
            new_schema,
            functools.partial(_copy.deepcopy, executor),
            partitioners={0: part},
        )
        if placement is not None:
            node.placement = placement
            node.channels = placement.num_channels(
                getattr(self.ctx, "cluster_workers", 1),
                self.ctx.exec_channels,
                getattr(self.ctx, "worker_tags", None),
            )
        return self._child(node)

    def cogroup(self, right: "DataStream", fn, new_schema, on=None,
                left_on=None, right_on=None) -> "DataStream":
        """Group BOTH streams by key and run fn(key, left_df, right_df) per
        distinct key (host DataFrames; either side may be empty, with the
        stream's columns) — the reference's cogroup (datastream.py:2073).
        Keys are colocated by hash-partitioned edges; fn is a host UDF, so
        this path is embedded-engine only (not picklable)."""
        from quokka_tpu.executors.sql_execs import CogroupExecutor
        from quokka_tpu.target_info import HashPartitioner

        if on is not None:
            left_on = right_on = on
        if left_on not in self.schema:
            raise ValueError(f"cogroup key {left_on} not in {self.schema}")
        if right_on not in right.schema:
            raise ValueError(f"cogroup key {right_on} not in {right.schema}")
        node = logical.StatefulNode(
            [self.node_id, right.node_id],
            list(new_schema),
            functools.partial(
                CogroupExecutor, left_on, right_on, fn, list(new_schema),
                list(self.schema), list(right.schema),
            ),
            partitioners={
                0: HashPartitioner([left_on]),
                1: HashPartitioner([right_on]),
            },
        )
        return self._child(node)

    def clip(self, limit: int) -> "DataStream":
        return self.head(limit)

    def head(self, limit: int) -> "DataStream":
        return self._child(_HeadNode([self.node_id], self.schema, limit))

    def union(self, other: "DataStream") -> "DataStream":
        if set(other.schema) != set(self.schema):
            raise ValueError("union requires identical schemas")
        return self._child(_UnionNode([self.node_id, other.node_id], self.schema))

    def distinct(self, keys: Optional[Sequence[str]] = None) -> "DataStream":
        keys = list(keys) if keys else self.schema
        return self._child(logical.DistinctNode([self.node_id], keys, keys))

    # -- joins ----------------------------------------------------------------
    def join(
        self,
        right: "DataStream",
        on: Optional[Union[str, Sequence[str]]] = None,
        left_on=None,
        right_on=None,
        how: str = "inner",
        suffix: str = "_2",
        maintain_sort_order=None,
    ) -> "DataStream":
        if on is not None:
            left_on = right_on = [on] if isinstance(on, str) else list(on)
        else:
            left_on = [left_on] if isinstance(left_on, str) else list(left_on)
            right_on = [right_on] if isinstance(right_on, str) else list(right_on)
        for c in left_on:
            if c not in self.schema:
                raise ValueError(f"left join key {c} not in {self.schema}")
        for c in right_on:
            if c not in right.schema:
                raise ValueError(f"right join key {c} not in {right.schema}")
        rename = None
        if how in ("semi", "anti"):
            out_schema = self.schema
        else:
            rpayload = [c for c in right.schema if c not in set(right_on)]
            rename = {c: c + suffix for c in rpayload if c in set(self.schema)}
            out_schema = self.schema + [rename.get(c, c) for c in rpayload]
        return self._child(
            logical.JoinNode(
                [self.node_id, right.node_id], out_schema, left_on, right_on, how,
                suffix, rename=rename,
            )
        )

    def broadcast_join(self, right: "DataStream", on=None, left_on=None,
                       right_on=None, how: str = "inner", suffix: str = "_2"):
        ds = self.join(right, on, left_on, right_on, how, suffix)
        ds._node.broadcast = True
        return ds

    # -- aggregation -----------------------------------------------------------
    def groupby(self, keys: Union[str, Sequence[str]], orderby=None) -> "GroupedDataStream":
        keys = [keys] if isinstance(keys, str) else list(keys)
        for k in keys:
            if k not in self.schema:
                raise ValueError(f"groupby key {k} not in {self.schema}")
        return GroupedDataStream(self, keys, orderby)

    def agg(self, aggregations: Dict) -> "DataStream":
        return GroupedDataStream(self, [], None).agg(aggregations)

    def agg_sql(self, sql: str) -> "DataStream":
        return GroupedDataStream(self, [], None).agg_sql(sql)

    aggregate = agg
    aggregate_sql = agg_sql

    def count_distinct(self, col_name: str) -> "DataStream":
        # same lowering (and null exclusion) as SQL count(distinct col)
        return GroupedDataStream(self, [], None)._agg_exprs(
            [Alias(Agg("count", ColRef(col_name), distinct=True), "count")]
        )

    def sum(self, columns) -> "DataStream":
        columns = [columns] if isinstance(columns, str) else list(columns)
        return self.agg_sql(", ".join(f"sum({c}) as {c}_sum" for c in columns))

    def max(self, columns) -> "DataStream":
        columns = [columns] if isinstance(columns, str) else list(columns)
        return self.agg_sql(", ".join(f"max({c}) as {c}_max" for c in columns))

    def min(self, columns) -> "DataStream":
        columns = [columns] if isinstance(columns, str) else list(columns)
        return self.agg_sql(", ".join(f"min({c}) as {c}_min" for c in columns))

    def mean(self, columns) -> "DataStream":
        columns = [columns] if isinstance(columns, str) else list(columns)
        return self.agg_sql(", ".join(f"avg({c}) as {c}_mean" for c in columns))

    # -- writers (datastream.py:129/205 write_csv / write_parquet) ------------
    def write_parquet(self, path: str, rows_per_file: int = 1 << 20):
        """Execute and write Parquet files under `path`; returns the written
        filenames as a DataFrame."""
        return self._write(path, "parquet", rows_per_file)

    def write_csv(self, path: str, rows_per_file: int = 1 << 20):
        return self._write(path, "csv", rows_per_file)

    def _write(self, path: str, fmt: str, rows_per_file: int):
        from quokka_tpu.executors.output import OutputExecutor

        node = logical.StatefulNode(
            [self.node_id],
            ["filename"],
            functools.partial(OutputExecutor, path, fmt, rows_per_file),
        )
        return self._child(node).collect()

    # -- vectors (datastream.py:396 vector_nn_join) ---------------------------
    def nearest_neighbors(self, queries, vec_col: str, k: int,
                          payload=None, approximate: bool = False,
                          nprobe: int = 4) -> "DataStream":
        """Top-k cosine matches of each query vector against this stream's
        `vec_col` vectors (brute force on the MXU).  approximate=True lets the
        optimizer push the search into an IVF sidecar index when the source
        has one (dataset/vector.build_vector_index): only row groups owning
        the queries' nprobe closest cells are scanned — Lance-style ANN
        pushdown (reference df.py:1264-1352)."""
        import numpy as _np

        from quokka_tpu.executors.vector import (
            GlobalTopKReduceExecutor,
            NearestNeighborExecutor,
        )

        queries = _np.asarray(queries)
        payload_cols = list(payload) if payload else [
            c for c in self.schema if c != vec_col
        ]
        out_schema = ["query_idx", "score"] + payload_cols
        local = logical.StatefulNode(
            [self.node_id],
            out_schema,
            functools.partial(NearestNeighborExecutor, queries, vec_col, k, payload_cols),
        )
        if approximate:
            local.ann_info = {"queries": queries, "nprobe": int(nprobe)}
        local_id = self.ctx.add_node(local)
        reduce_node = logical.StatefulNode(
            [local_id], out_schema, functools.partial(GlobalTopKReduceExecutor, k)
        )
        reduce_node.channels = 1
        return DataStream(self.ctx, self.ctx.add_node(reduce_node))

    vector_nn_join = nearest_neighbors

    # -- numeric extras (datastream.py:1033/1100/921) -------------------------
    def gramian(self, columns) -> "DataStream":
        return self._gramian(columns, covariance=False)

    def covariance(self, columns) -> "DataStream":
        return self._gramian(columns, covariance=True)

    def _gramian(self, columns, covariance: bool):
        from quokka_tpu.executors.linalg import (
            CombineGramianExecutor,
            GramianExecutor,
        )

        columns = [columns] if isinstance(columns, str) else list(columns)
        out_schema = ["column"] + columns
        local = logical.StatefulNode(
            [self.node_id],
            ["__row"] + columns,
            functools.partial(GramianExecutor, columns, covariance),
        )
        local_id = self.ctx.add_node(local)
        combine = logical.StatefulNode(
            [local_id], out_schema, functools.partial(CombineGramianExecutor, columns, covariance)
        )
        combine.channels = 1
        return DataStream(self.ctx, self.ctx.add_node(combine))

    def approximate_quantile(self, column: str, quantiles) -> "DataStream":
        from quokka_tpu.executors.linalg import (
            CombineQuantileExecutor,
            ReservoirQuantileExecutor,
        )

        quantiles = [quantiles] if isinstance(quantiles, (int, float)) else list(quantiles)
        out_schema = ["quantile", column]
        local = logical.StatefulNode(
            [self.node_id],
            ["__td_mean", "__td_weight"],  # serialized t-digest centroids
            functools.partial(ReservoirQuantileExecutor, column, quantiles),
        )
        local_id = self.ctx.add_node(local)
        combine = logical.StatefulNode(
            [local_id], out_schema, functools.partial(CombineQuantileExecutor, column, quantiles)
        )
        combine.channels = 1
        return DataStream(self.ctx, self.ctx.add_node(combine))

    # -- ordering --------------------------------------------------------------
    def top_k(self, by, k: int, descending=None) -> "DataStream":
        by = [by] if isinstance(by, str) else list(by)
        descending = descending or [False] * len(by)
        return self._child(logical.TopKNode([self.node_id], self.schema, by, k, descending))

    def sort(self, by, descending=None) -> "DataStream":
        by = [by] if isinstance(by, str) else list(by)
        descending = descending or [False] * len(by)
        node = logical.SortNode([self.node_id], self.schema, by, descending)
        # the output IS ordered: mark it at plan time so chained verbs lower
        # as sorted actors and the SAT-interleaved delivery preserves the
        # global order across a parallel (range-partitioned) sort's channels
        node.sorted_by = list(by)
        nid = self.ctx.add_node(node)
        return OrderedStream(self.ctx, nid)


class _HeadNode(logical.Node):
    def __init__(self, parents, schema, limit):
        super().__init__(parents, schema)
        self.limit = limit

    def derive_schema(self, parents):
        return list(parents[0])

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import TopKExecutor

        limit = self.limit

        class _Head(TopKExecutor):
            def __init__(self):
                super().__init__([], limit, [])

            def execute(self, batches, stream_id, channel):
                parts = [b for b in batches if b is not None]
                if self.state is not None:
                    parts.append(self.state)
                if not parts:
                    return None
                merged = bridge.concat_batches(parts) if len(parts) > 1 else parts[0]
                self.state = kernels.head(merged, self.k)
                return None

        actor_of[node_id] = graph.new_exec_node(
            _Head,
            {0: (actor_of[self.parents[0]], logical._passthrough_edge())},
            1,
            self.stage,
        )

    def describe(self):
        return f"Head({self.limit})"


class _UnionNode(logical.Node):
    def derive_schema(self, parents):
        # _Align selects self.schema from EVERY input stream, so the output
        # is the declared columns still present in all parents — early
        # projection may prune each side differently (e.g. one side keeps a
        # pushed predicate's column); re-deriving keeps the runtime select
        # legal instead of asking a pruned side for a column it dropped
        keep = set(parents[0])
        for p in parents[1:]:
            keep &= set(p)
        out = [c for c in self.schema if c in keep]
        if not out:
            raise ValueError(f"union inputs share no declared columns: {parents}")
        return out

    def lower(self, ctx, graph, actor_of, node_id):
        from quokka_tpu.executors.sql_execs import StorageExecutor

        schema = list(self.schema)

        class _Align(StorageExecutor):
            def execute(self, batches, stream_id, channel):
                out = StorageExecutor.execute(self, batches, stream_id, channel)
                return None if out is None else out.select(schema)

        actor_of[node_id] = graph.new_exec_node(
            _Align,
            {
                i: (actor_of[p], logical._passthrough_edge())
                for i, p in enumerate(self.parents)
            },
            self.channels or ctx.exec_channels,
            self.stage,
        )

    def describe(self):
        return "Union"


class GroupedDataStream:
    """groupby(...) handle -> agg / agg_sql (datastream.py:2066)."""

    def __init__(self, stream: DataStream, keys: List[str], orderby):
        self.stream = stream
        self.keys = keys
        self.orderby = orderby

    def agg(self, aggregations: Dict) -> DataStream:
        """{'col': 'sum' | ['sum','max'] | ...,  '*': 'count'} — output column
        naming matches the reference: col_sum, col_max, ..., count."""
        exprs: List[Expr] = []
        for c, specs in aggregations.items():
            specs = [specs] if isinstance(specs, str) else list(specs)
            for s in specs:
                s = s.lower()
                if c == "*":
                    if s != "count":
                        raise ValueError("only count supported for '*'")
                    exprs.append(Alias(Agg("count", None), "count"))
                else:
                    op = "avg" if s in ("mean", "avg") else s
                    exprs.append(Alias(Agg(op, ColRef(c)), f"{c}_{s}"))
        return self._agg_exprs(exprs)

    def agg_sql(self, sql: str) -> DataStream:
        exprs = sqlparse.parse_select_list(sql)
        named = []
        for i, e in enumerate(exprs):
            if isinstance(e, Alias):
                named.append(e)
            else:
                named.append(Alias(e, f"col{i}"))
        return self._agg_exprs(named)

    aggregate = agg
    aggregate_sql = agg_sql

    def _agg_exprs(self, exprs: List[Alias], having=None, order_by=None,
                   limit=None) -> DataStream:
        rewritten = self._rewrite_count_distinct(exprs, having, order_by, limit)
        if rewritten is not None:
            return rewritten
        plan = plan_aggregation(exprs)
        if having is not None:
            # aggregates inside HAVING become references to (possibly new)
            # partial columns of the same plan
            having = plan.rewrite(having)
        out_schema = self.keys + [n for n, _ in plan.finals if n not in self.keys]
        if self.orderby:
            order_by = [
                (c, False) if isinstance(c, str) else (c[0], c[1] == "desc")
                for c in ([self.orderby] if isinstance(self.orderby, str) else self.orderby)
            ]
        node = logical.AggNode(
            [self.stream.node_id], out_schema, self.keys, plan,
            having=having, order_by=order_by, limit=limit,
        )
        return self.stream._child(node)

    def _rewrite_count_distinct(self, exprs, having, order_by, limit):
        """count(distinct x) lowers to distinct-then-count: project keys + x,
        de-duplicate (a group-by), then count per key (reference:
        datastream.py:1769 _grouped_count_distinct).  Only the pure form is
        rewritten; mixing with other aggregates raises."""
        def is_cd(e):
            return isinstance(e, Agg) and e.distinct

        cds = [a for a in exprs if is_cd(a.expr)]
        if not cds:
            return None
        if len(cds) != len(exprs) or len(cds) != 1:
            raise ValueError(
                "count(distinct) cannot be mixed with other aggregates yet; "
                "compute it in a separate aggregation and join"
            )
        a = cds[0]
        agg = a.expr
        if agg.op != "count" or not isinstance(agg.arg, ColRef):
            raise ValueError("only count(distinct column) is supported")
        colname = agg.arg.name

        def subst(e):
            # over the deduped stream, count(distinct col) == count(*):
            # rewrite HAVING/ORDER references so the inner plan compiles
            if isinstance(e, Agg) and e.distinct:
                return Agg("count", None)
            kids = e.children()
            if not kids:
                return e
            from quokka_tpu.expression import _rebuild

            return _rebuild(e, [subst(k) for k in kids])

        d = (
            self.stream.filter(IsNull(ColRef(colname), True))  # nulls don't count
            .select(self.keys + [colname])
            .distinct()
        )
        having = None if having is None else subst(having)
        g = GroupedDataStream(d, self.keys, self.orderby)
        out = g._agg_exprs([Alias(Agg("count", None), a.name)],
                           having=having, order_by=order_by, limit=limit)
        return out


class OrderedStream(DataStream):
    """Sorted-stream subclass (reference: pyquokka/orderedstream.py:3-191).
    Carries time-order metadata through the plan; time-series verbs (asof
    join, window aggregation, pattern recognition, shift) attach here."""

    @property
    def sorted_by(self):
        return self._node.sorted_by

    @property
    def time_col(self) -> str:
        sb = self.sorted_by
        if not sb:
            raise ValueError("ordered stream has no sort column metadata")
        return sb[0]

    def _ordered(self, node: logical.Node) -> "OrderedStream":
        node.sorted_by = self.sorted_by
        nid = self.ctx.add_node(node)
        return OrderedStream(self.ctx, nid)

    def _rewrap(self, ds: DataStream) -> "OrderedStream":
        """Reuse the DataStream verb, then mark the node ordered — unless the
        sort column was projected away (the result is no longer ordered)."""
        node = ds._node
        if self.sorted_by and all(c in node.schema for c in self.sorted_by):
            node.sorted_by = self.sorted_by
            return OrderedStream(self.ctx, ds.node_id)
        return ds

    # order-preserving relational verbs stay ordered
    def filter(self, predicate):
        return self._rewrap(DataStream.filter(self, predicate))

    def filter_sql(self, sql):
        return self.filter(sql)

    def select(self, columns):
        return self._rewrap(DataStream.select(self, columns))

    def with_columns(self, exprs):
        return self._rewrap(DataStream.with_columns(self, exprs))

    # -- asof join (orderedstream.py:37 join_asof) ---------------------------
    def join_asof(
        self,
        right: "OrderedStream",
        on: Optional[str] = None,
        left_on: Optional[str] = None,
        right_on: Optional[str] = None,
        by=None,
        left_by=None,
        right_by=None,
        suffix: str = "_2",
        direction: str = "backward",
    ) -> "OrderedStream":
        from quokka_tpu.executors.ts_execs import SortedAsofExecutor
        from quokka_tpu.target_info import HashPartitioner, PassThroughPartitioner

        if direction not in ("backward", "forward"):
            raise NotImplementedError(f"join_asof direction {direction!r}")
        left_on = left_on or on or self.time_col
        right_on = right_on or on or right.time_col
        if by is not None:
            left_by = right_by = [by] if isinstance(by, str) else list(by)
        left_by = [left_by] if isinstance(left_by, str) else list(left_by or [])
        right_by = [right_by] if isinstance(right_by, str) else list(right_by or [])
        rpayload = [c for c in right.schema if c not in set(right_by) and c != right_on]
        out_schema = self.schema + [
            c + suffix if c in set(self.schema) else c for c in rpayload
        ]
        if left_by:
            parts = {0: HashPartitioner(left_by), 1: HashPartitioner(right_by)}
        else:
            parts = {0: PassThroughPartitioner(), 1: PassThroughPartitioner()}
        node = logical.AsofJoinNode(
            [self.node_id, right.node_id],
            out_schema,
            functools.partial(SortedAsofExecutor,
                left_on, right_on, left_by, right_by, suffix, direction=direction
            ),
            parts,
            [left_on],
            left_on=left_on, right_on=right_on,
            left_by=left_by, right_by=right_by,
            suffix=suffix, direction=direction,
        )
        nid = self.ctx.add_node(node)
        return OrderedStream(self.ctx, nid)

    # -- window aggregation (datastream.py:1650 windowed_transform +
    #    windowtypes compilation) --------------------------------------------
    def window_agg(self, window, aggs_sql: str, by=None, trigger=None) -> DataStream:
        from quokka_tpu import windows as W
        from quokka_tpu.executors.ts_execs import (
            HoppingWindowExecutor,
            SessionWindowExecutor,
            SlidingWindowExecutor,
        )
        from quokka_tpu.target_info import HashPartitioner, PassThroughPartitioner

        by = [by] if isinstance(by, str) else list(by or [])
        time_col = self.time_col
        exprs = sqlparse.parse_select_list(aggs_sql)
        named = [e if isinstance(e, Alias) else Alias(e, f"col{i}") for i, e in enumerate(exprs)]
        plan = plan_aggregation(named)
        if isinstance(window, (W.TumblingWindow, W.HoppingWindow)):
            factory = functools.partial(HoppingWindowExecutor, time_col, by, window, plan, trigger)
            extra = ["window_start", "window_end"]
        elif isinstance(window, W.SessionWindow):
            factory = functools.partial(SessionWindowExecutor, time_col, by, window, plan)
            extra = ["session_start", "session_end"]
        elif isinstance(window, W.SlidingWindow):
            factory = functools.partial(SlidingWindowExecutor, time_col, by, window, plan)
            extra = []
        else:
            raise TypeError(f"unknown window type {type(window)}")
        if isinstance(window, W.SlidingWindow):
            out_schema = self.schema + [n for n, _ in plan.finals]
            out_sorted = [time_col]  # per-event output keeps the time column
        else:
            out_schema = by + extra + [n for n, _ in plan.finals]
            out_sorted = [extra[0]]  # windows emit ordered by their start
        node = logical.WindowAggNode(
            [self.node_id],
            out_schema,
            factory,
            {0: HashPartitioner(by) if by else PassThroughPartitioner()},
            out_sorted,
            time_col=time_col, by=by, window=window, plan=plan, trigger=trigger,
        )
        nid = self.ctx.add_node(node)
        return OrderedStream(self.ctx, nid)

    def windowed_transform(self, window, aggs_sql: str, by=None, trigger=None):
        return self.window_agg(window, aggs_sql, by=by, trigger=trigger)

    # -- shift (orderedstream.py:13) -----------------------------------------
    def shift(self, columns, n: int = 1, by=None, fill_value=None) -> "OrderedStream":
        from quokka_tpu.executors.ts_execs import ShiftExecutor
        from quokka_tpu.target_info import HashPartitioner, PassThroughPartitioner

        columns = [columns] if isinstance(columns, str) else list(columns)
        by = [by] if isinstance(by, str) else list(by or [])
        out_schema = self.schema + [f"{c}_shifted_{n}" for c in columns]
        time_col = self.time_col
        node = logical.ShiftNode(
            [self.node_id],
            out_schema,
            functools.partial(ShiftExecutor, time_col, by, columns, n),
            {0: HashPartitioner(by) if by else PassThroughPartitioner()},
            [time_col],
            time_col=time_col, by=by, columns=columns, n=n,
        )
        return self._ordered(node)

    # -- pattern recognition (CEP, orderedstream.py:55 pattern_recognize) -----
    def pattern_recognize(self, events, within, by=None) -> DataStream:
        from quokka_tpu.executors.cep import CEPExecutor
        from quokka_tpu.target_info import HashPartitioner, PassThroughPartitioner

        by = [by] if isinstance(by, str) else list(by or [])
        time_col = self.time_col
        names = [n for n, _ in events]
        out_schema = by + [f"{n}_{time_col}" for n in names]
        node = logical.StatefulNode(
            [self.node_id],
            out_schema,
            functools.partial(CEPExecutor, time_col, events, within, by),
            partitioners={0: HashPartitioner(by) if by else PassThroughPartitioner()},
        )
        return self._child(node)

    def stateful_transform_sorted(self, executor, new_schema, by=None):
        ds = self.stateful_transform(executor, new_schema, by=by)
        ds._node.sorted_by = self.sorted_by
        return OrderedStream(self.ctx, ds.node_id)

