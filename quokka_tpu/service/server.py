"""QueryService: a persistent multi-query engine over one warm runtime.

One long-lived process hosts many concurrent queries:

- **shared, warm state** — one ControlStore (each query in its own
  namespace), the process-global device scan cache, and the process-global
  jit/XLA compile caches all outlive any single query, so the second query
  over the same files/kernel shapes starts hot;
- **a worker pool** — ``QK_SERVICE_WORKERS`` dispatch threads multiplex
  every running query.  Scheduling is round-robin ACROSS query namespaces
  at task granularity with a per-query in-flight cap
  (``QK_SERVICE_INFLIGHT``), so a heavy TPC-H Q5 cannot starve a
  concurrent Q1;
- **admission control** — a byte-budgeted gate (service/admission.py):
  queries whose estimated working set would overshoot
  ``QK_SERVICE_MEM_BUDGET`` wait in a bounded FIFO queue and fail with
  ``AdmissionTimeout`` if they never fit;
- **isolation** — per-query BatchCache, namespaced store tables, namespaced
  HBQ spill filenames and checkpoint names in ONE shared spill dir, and an
  explicit ``drop_namespace`` GC at query end.

Usage::

    svc = QueryService(pool_size=2)
    h1 = svc.submit(ctx.read_parquet(p).groupby("k").agg_sql("sum(v) as s"))
    h2 = svc.submit(other_stream)
    df1, df2 = h1.to_df(), h2.to_df()
    svc.shutdown()
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
import time
import weakref
from typing import Dict, List, Optional

from quokka_tpu import obs
from quokka_tpu.runtime.cache import BatchCache
from quokka_tpu.runtime.engine import TaskGraph, new_query_id
from quokka_tpu.runtime.tables import ControlStore
from quokka_tpu.service.admission import (
    AdmissionController,
    AdmissionTimeout,
    _env_float,
    _env_int,
    estimate_working_set,
)
from quokka_tpu.service.session import (
    DONE,
    FAILED,
    RUNNING,
    QueryHandle,
    QuerySession,
)


class ServiceShutdown(RuntimeError):
    """submit() after shutdown(), or a query torn down by shutdown()."""


class QueryStallTimeout(TimeoutError):
    """A running query made no progress within QK_SERVICE_QUERY_TIMEOUT."""


class QueryCancelled(RuntimeError):
    """The query was cancelled via QueryHandle.cancel(): dispatch stopped at
    the next task boundary, admission bytes released, namespace/spill/
    checkpoints/manifest GC'd.  Distinct from the stall timeout — this is a
    client decision, not a health judgment."""


class DeadlineExceeded(TimeoutError):
    """The query outlived its submit(..., deadline_s=...) budget and was
    cooperatively cancelled at the next task boundary.  Distinct from
    QueryStallTimeout (a PROGRESSING query past its deadline still dies;
    a stalled one dies even without a deadline)."""


class QueryService:
    """Persistent multi-query engine: ``submit(stream) -> QueryHandle``."""

    def __init__(self,
                 pool_size: Optional[int] = None,
                 exec_config: Optional[dict] = None,
                 *,
                 mem_budget: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 max_concurrent: Optional[int] = None,
                 admit_timeout: Optional[float] = None,
                 inflight_per_query: Optional[int] = None,
                 query_timeout: Optional[float] = None,
                 spill_dir: Optional[str] = None):
        from quokka_tpu import config as qconfig

        self.exec_config = dict(qconfig.DEFAULT_EXEC_CONFIG)
        if exec_config:
            self.exec_config.update(exec_config)
        self.pool_size = (
            _env_int("QK_SERVICE_WORKERS", 2) if pool_size is None
            else max(1, pool_size)
        )
        self.inflight_per_query = (
            _env_int("QK_SERVICE_INFLIGHT", 2)
            if inflight_per_query is None else max(1, inflight_per_query)
        )
        self.query_timeout = (
            _env_float("QK_SERVICE_QUERY_TIMEOUT", 600.0)
            if query_timeout is None else query_timeout
        )
        self.store = ControlStore()
        self.admission = AdmissionController(
            mem_budget=mem_budget, queue_depth=queue_depth,
            max_concurrent=max_concurrent, admit_timeout=admit_timeout)
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
            self._spill_dir = spill_dir
            self._own_spill = False
        else:
            base = self.exec_config.get("hbq_path", "/tmp/quokka_tpu_spill/")
            os.makedirs(base, exist_ok=True)
            self._spill_dir = tempfile.mkdtemp(prefix="service-", dir=base)
            self._own_spill = True
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._sessions: Dict[str, QuerySession] = {}  # LIVE queries only
        # every session ever enqueued, weakly: attach(query_id) keeps
        # working after the service drops its strong reference at finish,
        # for exactly as long as any client handle keeps the session alive
        self._by_id = weakref.WeakValueDictionary()
        self._queued: Dict[str, QuerySession] = {}
        self._running: List[str] = []  # round-robin order
        self._rr = 0
        self._finished = 0
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"qksvc-{i}")
            for i in range(self.pool_size)
        ]
        for t in self._threads:
            t.start()
        # the active chaos spec (QK_CHAOS) is part of the service's
        # identity: a soak triaging a failed run needs to see, in the
        # flight timeline, which fault plan this service ran under
        from quokka_tpu.chaos import CHAOS

        obs.RECORDER.record("service.start", f"pool={self.pool_size}",
                            chaos=CHAOS.describe())
        # QK_METRICS_PORT: external scrapers watch this service live
        # (/metrics Prometheus text + /status JSON of stats())
        self.metrics_server = obs.export.start_from_env(service=self)
        # health plane: the refcounted history sampler records registry
        # snapshots every QK_HISTORY_INTERVAL_S and drives the alert engine
        # (/history + /health); released at shutdown
        obs.history.acquire_sampler()
        # QK_PREWARM=1: load every recorded plan's persisted executables in
        # the background at startup, so even the first-ever submit of a
        # known plan shape dispatches against warm programs
        if os.environ.get("QK_PREWARM", "") not in ("", "0"):
            from quokka_tpu.runtime import compileplane

            compileplane.prewarm_all(wait=False)

    def prewarm(self, streams=None, timeout: float = 120.0) -> int:
        """Ahead-of-time warm the compile plane before traffic arrives.

        ``streams``: DataStreams whose plans this service will soon run —
        each is lowered into a throwaway graph to derive its plan
        fingerprint, and that plan's persisted executables are loaded
        synchronously (bounded by ``timeout``).  ``streams=None`` replays
        EVERY plan the ledger has ever recorded and returns the number of
        plans that loaded >= 1 persisted executable; with ``streams`` it
        returns the number of streams whose plan warmup was dispatched (an
        already-resident plan needs none and contributes 0).  Never raises
        (warmup is an optimization layer)."""
        import contextlib

        from quokka_tpu.runtime import compileplane
        from quokka_tpu.runtime.tables import ControlStore

        if streams is None:
            return compileplane.prewarm_all(wait=True, timeout=timeout)
        n = 0
        for stream in streams:
            # the throwaway graph exists only to derive plan_fp: restore the
            # context's latest_graph (introspection must keep answering from
            # the last EXECUTED graph) and tear down its spill dirs
            prev = getattr(stream.ctx, "latest_graph", None)
            graph = None
            try:
                graph = TaskGraph(self.exec_config, store=ControlStore())
                stream.ctx.lower_into(stream.node_id, graph)
                # lowering already fired this plan's background replay
                # (_lower_plan); wait on THAT thread rather than spawning
                # a duplicate that would race it over the same .aot files
                t = getattr(graph, "prewarm_thread", None)
                if t is not None:
                    t.join(timeout)
                n += t is not None
            except Exception as e:  # noqa: BLE001 — warm less, never fail
                obs.diag(f"[service] prewarm of a stream failed: {e!r}")
            finally:
                stream.ctx.latest_graph = prev
                if graph is not None:
                    with contextlib.suppress(Exception):
                        graph.cleanup()
        return n

    # -- client surface ------------------------------------------------------
    def submit(self, stream, *, working_set_bytes: Optional[int] = None,
               exec_config: Optional[dict] = None,
               durable: Optional[bool] = None,
               resume_from: Optional[str] = None,
               deadline_s: Optional[float] = None) -> QueryHandle:
        """Lower a DataStream's plan into this service's shared runtime and
        queue it for admission.  Returns immediately with a QueryHandle;
        raises AdmissionQueueFull when the wait queue is at capacity.

        ``durable=True`` (default from ``QK_DURABLE_BATCH``; requires
        ``fault_tolerance``) makes the query survive a full service process
        death: the engine rewrites a batch resume manifest (plan payload +
        fingerprint, per-channel checkpoint frontiers, sink floor) at every
        checkpoint cadence, and a restarted service re-admits it via
        ``recover_orphans()`` — or explicitly via
        ``submit(stream, resume_from=<manifest>)``, which verifies the
        resubmitted plan's structural fingerprint against the manifest and
        fails loudly (``ManifestMismatch``) on drift.

        ``deadline_s`` is a per-query wall-clock budget measured from
        submit: a query still unfinished past it is cooperatively cancelled
        at the next task boundary and fails with ``DeadlineExceeded``
        (default from ``QK_QUERY_DEADLINE_S``; distinct from the global
        stall timeout, which only fires on NO progress)."""
        with self._lock:
            if self._shutdown:
                raise ServiceShutdown("QueryService is shut down")
        ctx = stream.ctx
        cfg = self._merged_config(ctx, exec_config)
        if deadline_s is None:
            env_deadline = _env_float("QK_QUERY_DEADLINE_S", 0.0)
            deadline_s = env_deadline if env_deadline > 0 else None
        if resume_from is not None:
            from quokka_tpu.runtime import resume as bresume

            if not cfg.get("fault_tolerance"):
                raise ValueError(
                    "resume_from needs fault_tolerance=True: the resumed "
                    "query restores executor checkpoints and replays "
                    "spilled batches, neither of which exists without it")
            m = bresume.load(resume_from)
            return self._resume_orphan(m, resume_from, stream=stream,
                                       exec_config=exec_config,
                                       deadline_s=deadline_s)
        if durable is None:
            durable = bool(_env_int("QK_DURABLE_BATCH", 0))
        if durable and not cfg.get("fault_tolerance"):
            raise ValueError(
                "durable=True needs fault_tolerance=True: the resume "
                "manifest records checkpoint frontiers and replays spilled "
                "batches, neither of which exists without it")
        qid = new_query_id()
        graph = TaskGraph(cfg, store=self.store,
                          cache=BatchCache(owner=qid), query_id=qid,
                          spill_dir=self._spill_dir)
        try:
            sub, sink_id = ctx._prepare_plan(stream.node_id)
            blob = None
            if durable:
                # capture the PREPARED (pre-lowering) plan: recovery
                # re-lowers it in a fresh context, and the structural
                # fingerprint check proves the re-lowering is the same plan
                try:
                    blob = pickle.dumps({
                        "sub": sub, "sink_id": sink_id,
                        "exec_channels": ctx.exec_channels,
                        "exec_config": cfg,
                    })
                except Exception as e:
                    raise ValueError(
                        "durable=True needs a picklable plan (no lambdas/"
                        f"closures in map/filter payloads): {e!r}") from e
            sink_actor = ctx._lower_plan(sub, sink_id, graph)
            est = (int(working_set_bytes) if working_set_bytes is not None
                   else estimate_working_set(graph))
            if durable:
                from quokka_tpu.runtime import resume as bresume

                graph.resume_manifest = bresume.default_path(graph)
                graph.resume_plan_blob = blob
                graph.resume_est_bytes = est
            session = QuerySession(qid, graph, sink_actor, est,
                                   self.inflight_per_query)
            session.durable = durable
            if deadline_s is not None:
                session.deadline_at = session.submitted_at + float(deadline_s)
            self._enqueue_session(session)
            if durable:
                # initial manifest at submit: a crash before the first
                # checkpoint still re-admits (as a fresh run — no frontier
                # to resume, but no silently vanished query either)
                bresume.update(graph)
        except BaseException:
            graph.cleanup()
            raise
        # admit synchronously when it fits: the caller's next submit must
        # see this query CHARGED against the budget, not still in the queue
        self._admit_pending()
        obs.RECORDER.record("service.submit", qid, q=qid, est_bytes=est,
                            durable=durable)
        return session.handle

    def _enqueue_session(self, session: QuerySession) -> None:
        """Charge admission and queue a freshly built session — the one
        locked shutdown-recheck/offer/queue/notify block both submit paths
        share (a raced shutdown() must never strand an offered session)."""
        with self._lock:
            if self._shutdown:
                raise ServiceShutdown("QueryService is shut down")
            self.admission.offer(session.query_id, session.est_bytes)
            session._service = self
            self._sessions[session.query_id] = session
            self._by_id[session.query_id] = session
            self._queued[session.query_id] = session
            self._wake.notify_all()

    def _merged_config(self, ctx, exec_config: Optional[dict]) -> dict:
        """Service config overlaid with the context's NON-default keys (every
        QuokkaContext carries the full default dict, so a blind update()
        would silently revert the service-level exec_config to defaults on
        every submit), then any per-submit overrides."""
        from quokka_tpu import config as qconfig

        cfg = dict(self.exec_config)
        defaults = qconfig.DEFAULT_EXEC_CONFIG
        for k, v in ctx.exec_config.items():
            if k not in defaults or defaults[k] != v:
                cfg[k] = v
        if exec_config:
            cfg.update(exec_config)
        return cfg

    def submit_continuous(self, stream, *,
                          resume_from: Optional[str] = None,
                          delivered_floor: Optional[int] = None,
                          manifest_path: Optional[str] = None,
                          working_set_bytes: Optional[int] = None,
                          exec_config: Optional[dict] = None):
        """Run ``stream`` as a STANDING query over its unbounded sources
        (quokka_tpu/streaming/): batches keep flowing as the tailed inputs
        grow, windowed/asof operators emit finalized panes incrementally as
        the event-time watermark advances, and the returned
        ``StreamingHandle`` delivers them via ``poll_deltas()`` until
        ``stop()`` drains the stream (final state bit-exact with the
        equivalent one-shot batch run).

        With ``fault_tolerance`` on, incremental checkpoints (operator
        state + source offsets + watermark snapshot) flow through the normal
        checksummed atomic checkpoint path and additionally persist a resume
        manifest; ``resume_from=<manifest>`` resubmits the SAME plan after a
        full service restart and continues from the last checkpointed pane
        boundary — only post-frontier segments replay, never the whole
        stream.  A client that durably captured N delta tables before the
        crash passes ``delivered_floor=N`` so the resume point never
        postdates its capture frontier (closing the output-commit gap —
        every uncaptured pane re-emits, deduped by pane identity).
        Restart survival requires a stable ``spill_dir`` (and/or
        ``checkpoint_store``); standing queries share admission and fair
        scheduling with batch queries but are exempt from the query-stall
        timeout (idle is healthy).  Under an active ``QK_CHAOS`` kill spec,
        seeded kills of the streaming operators are injected and recovered
        through the tape-replay protocol, exactly-once."""
        from quokka_tpu.chaos import CHAOS
        from quokka_tpu.streaming import manifest as smanifest
        from quokka_tpu.streaming.handle import StreamingHandle

        with self._lock:
            if self._shutdown:
                raise ServiceShutdown("QueryService is shut down")
        ctx = stream.ctx
        cfg = self._merged_config(ctx, exec_config)
        if resume_from and not cfg.get("fault_tolerance"):
            raise ValueError(
                "resume_from needs fault_tolerance=True: the resumed "
                "stream restores executor checkpoints and replays spilled "
                "segments, neither of which exists without it")
        resume = smanifest.load(resume_from) if resume_from else None
        qid = resume["query_id"] if resume else new_query_id()
        with self._lock:
            if qid in self._sessions:
                # a duplicate resume of a LIVE stream would run two engines
                # against one store/spill/checkpoint namespace — interleaved
                # seq assignments and conflicting pane deltas, silently
                raise ValueError(
                    f"stream {qid} is already running in this service — "
                    "stop it before resuming its manifest again")
        graph = TaskGraph(cfg, store=self.store,
                          cache=BatchCache(owner=qid), query_id=qid,
                          spill_dir=self._spill_dir)
        resume_info = None
        try:
            sink_actor = ctx.lower_into(stream.node_id, graph)
            if not any(getattr(info.reader, "UNBOUNDED", False)
                       for info in graph.actors.values()
                       if info.kind == "input"):
                raise ValueError(
                    "submit_continuous needs at least one UNBOUNDED source "
                    "(a streaming.TailingCsvReader / TailingParquetDirReader"
                    "); use submit() for finite plans")
            if cfg.get("fault_tolerance"):
                graph.stream_manifest = (
                    manifest_path or smanifest.default_path(graph))
            if resume is not None:
                resume_info = smanifest.apply_resume(
                    graph, resume, delivered_floor=delivered_floor)
            est = (int(working_set_bytes) if working_set_bytes is not None
                   else estimate_working_set(graph))
            session = QuerySession(qid, graph, sink_actor, est,
                                   self.inflight_per_query)
            session.streaming = True
            # seeded chaos: standing queries take REPEATED kills of their
            # checkpointable streaming operators over the stream's lifetime
            if CHAOS.enabled and cfg.get("fault_tolerance"):
                chans = sorted(
                    (a, ch) for (a, ch), e in session.engine.execs.items()
                    if getattr(e, "SUPPORTS_CHECKPOINT", False))
                plan = CHAOS.plan_stream_kills(chans)
                if plan:
                    session.inject_plan = [
                        {"after_tasks": after, "channels": channels}
                        for after, channels in plan]
                    if session.inject is None:
                        session.inject = session.inject_plan.pop(0)
            self._enqueue_session(session)
        except BaseException:
            # an aborted submit never ran: durable resume state (if any)
            # must survive for the next attempt
            graph.cleanup(preserve_durable=resume_from is not None)
            raise
        self._admit_pending()
        obs.RECORDER.record("service.submit_continuous", qid, q=qid,
                            est_bytes=est, resumed=resume is not None)
        return StreamingHandle(session, resume_info=resume_info)

    # -- supervisor: durable-batch orphan recovery ---------------------------
    def recover_orphans(self, manifest_dir: Optional[str] = None
                        ) -> List[QueryHandle]:
        """Scan the manifest directory for orphaned durable batch queries (a
        previous service incarnation died with them in flight) and re-admit
        each through NORMAL admission — FIFO behind anything already queued,
        no barging — resuming from its last durable frontier.  Unreadable or
        foreign manifests are quarantined (``.corrupt``, counted on
        ``resume.quarantined``), never allowed to wedge the healthy orphans
        behind them.  Returns one QueryHandle per re-admitted query; call it
        right after constructing the restarted service (same ``spill_dir``)."""
        from quokka_tpu.runtime import resume as bresume

        if manifest_dir is None:
            manifest_dir = os.path.join(self._spill_dir, "ckpt")
        handles: List[QueryHandle] = []
        for path in bresume.scan(manifest_dir):
            m = bresume.load_or_quarantine(path)
            if m is None:
                continue
            with self._lock:
                if m["query_id"] in self._sessions:
                    continue  # live in THIS incarnation: not an orphan
            try:
                handles.append(self._resume_orphan(m, path))
            except bresume.ManifestMismatch as e:
                # foreign fingerprint / missing plan payload: same janitor
                # treatment as an unreadable manifest
                bresume.quarantine_manifest(path, repr(e))
        obs.REGISTRY.counter("resume.orphans").inc(len(handles))
        return handles

    def _resume_orphan(self, m: Dict, path: str, *, stream=None,
                       exec_config: Optional[dict] = None,
                       deadline_s: Optional[float] = None) -> QueryHandle:
        """Re-admit one manifest: re-lower its plan (from the manifest's own
        pickled plan payload, or from ``stream`` when the client resubmits
        explicitly), verify the structural fingerprint, apply the restart
        surgery, and enqueue through normal admission."""
        from quokka_tpu.runtime import resume as bresume

        qid = m["query_id"]
        with self._lock:
            if qid in self._sessions:
                # mirror of the streaming guard: a duplicate resume of a
                # LIVE query would run two engines against one store/spill/
                # checkpoint namespace — interleaved seq assignments and
                # conflicting results, silently
                raise ValueError(
                    f"query {qid} is already running in this service — "
                    "it cannot be resumed from its manifest again")
        blob = m.get("plan_blob")
        if stream is not None:
            ctx = stream.ctx
            cfg = self._merged_config(ctx, exec_config)
            sub, sink_id = ctx._prepare_plan(stream.node_id)
        else:
            if not blob:
                raise bresume.ManifestMismatch(
                    f"manifest {path} carries no plan payload — it cannot "
                    "be resumed without the original stream")
            from quokka_tpu.context import QuokkaContext

            payload = pickle.loads(blob)
            ctx = QuokkaContext()
            ctx.exec_channels = payload.get("exec_channels",
                                            ctx.exec_channels)
            sub, sink_id = payload["sub"], payload["sink_id"]
            cfg = dict(payload.get("exec_config") or self.exec_config)
        graph = TaskGraph(cfg, store=self.store,
                          cache=BatchCache(owner=qid), query_id=qid,
                          spill_dir=self._spill_dir)
        try:
            sink_actor = ctx._lower_plan(sub, sink_id, graph)
            graph.resume_manifest = path
            graph.resume_plan_blob = blob
            info = bresume.apply_resume(graph, m)
            est = int(m.get("est_bytes") or estimate_working_set(graph))
            graph.resume_est_bytes = est
            session = QuerySession(qid, graph, sink_actor, est,
                                   self.inflight_per_query)
            session.durable = True
            session.resume_info = info
            if deadline_s is not None:
                session.deadline_at = (session.submitted_at
                                       + float(deadline_s))
            self._enqueue_session(session)
        except BaseException:
            # an aborted resume never ran: the durable recovery trio must
            # survive for the next attempt
            graph.cleanup(preserve_durable=True)
            raise
        self._admit_pending()
        obs.RECORDER.record(
            "service.resume", qid, q=qid, est_bytes=est,
            execs=len(info["execs"]), replay_specs=info["replay_specs"],
            corrupt_spills=info["corrupt_spills"])
        return session.handle

    def attach(self, query_id: str,
               cursor: Optional[Dict[int, int]] = None) -> QueryHandle:
        """A fresh handle for a query by id — including one re-admitted by
        ``recover_orphans()`` or already finished (for as long as any handle
        keeps its session alive).  ``cursor`` ({channel: last seq the client
        durably captured}) seeds the handle's delivery cursor so its first
        ``poll_batches()`` drains exactly the undelivered tail — a resumed
        sink rebuilds the full seq-keyed result set, so replayed batches
        below the cursor never re-surface and nothing above it is skipped."""
        with self._lock:
            session = self._sessions.get(query_id)
        if session is None:
            session = self._by_id.get(query_id)
        if session is None:
            raise KeyError(
                f"query {query_id!r} is unknown to this service (never "
                "submitted here, or finished with every handle released)")
        handle = QueryHandle(session)
        if cursor:
            handle._cursor.update(cursor)
        return handle

    # -- cancellation + deadlines --------------------------------------------
    def _cancel_ping(self, session: QuerySession) -> None:
        """QueryHandle.cancel() entry: a QUEUED query cancels synchronously
        (it holds no slot to drain); a RUNNING one is flagged and the worker
        loop honors it at the next task boundary."""
        obs.REGISTRY.counter("cancel.requested").inc()
        with self._lock:
            queued = self._queued.pop(session.query_id, None) is not None
            if queued:
                self.admission.cancel(session.query_id)
            self._wake.notify_all()
        if queued:
            self._finish(session, QueryCancelled(
                f"query {session.query_id} cancelled while queued"))

    def _reap_deadlines(self) -> None:
        """Fail QUEUED sessions whose deadline expired before admission
        (RUNNING ones are checked at every slot grant)."""
        now = time.time()
        expired: List[QuerySession] = []
        with self._lock:
            for qid, s in list(self._queued.items()):
                if s.deadline_at is not None and now > s.deadline_at:
                    self._queued.pop(qid, None)
                    self.admission.cancel(qid)
                    expired.append(s)
        for s in expired:
            obs.REGISTRY.counter("cancel.deadline").inc()
            self._finish(s, DeadlineExceeded(
                f"query {s.query_id} exceeded its deadline while queued "
                f"({now - s.submitted_at:.1f}s since submit)"))

    def stats(self) -> Dict:
        from quokka_tpu.runtime import scancache

        now = time.time()
        # non-creating lookup: a scrape racing a query's teardown must not
        # resurrect the just-GC'd per-query histogram (it would leak one
        # empty labeled family per finished query, forever)
        hists = obs.REGISTRY.histograms()
        counters = obs.REGISTRY.snapshot()
        with self._lock:
            sessions = {}
            for qid, s in self._sessions.items():
                h = hists.get(f"task.latency_s.{qid}")
                lat = h.stats() if h is not None else \
                    obs.Histogram.empty_stats()
                sessions[qid] = {
                    "status": s.status, "est_bytes": s.est_bytes,
                    "inflight": s.inflight, "handled": s.handled,
                    # queue-wait so far (live) or final; task-latency
                    # quantiles from the per-query histogram
                    "queue_wait_s": round(
                        ((s.started_at or now) - s.submitted_at), 6),
                    "task_p50_s": lat["p50"],
                    "task_p95_s": lat["p95"],
                    "tasks": lat["count"],
                    # memory plane columns (obs/memplane.py): snapshot
                    # lookups, never creating — the per-query gauges GC
                    # with the namespace and must stay gone
                    "mem_live_bytes": counters.get(
                        f"mem.live_bytes.{qid}", 0),
                    "mem_peak_bytes": counters.get(
                        f"mem.peak_bytes.{qid}", 0),
                    "mem_spill_bytes": counters.get(
                        f"mem.spill_resident_bytes.{qid}", 0),
                    # EXPLAIN ANALYZE plane: the session's hottest operator
                    # (non-creating ledger lookup; None before first stats)
                    "top_operator": obs.OPSTATS.top_operator(qid),
                }
                if s.durable:
                    # durable-batch columns: manifest cadence (the RMT
                    # journal length), resume provenance, cancel/deadline
                    # state — the /status surface for the supervisor plane
                    sessions[qid].update({
                        "durable": True,
                        "manifest_writes": len(
                            s.graph.store.tget("RMT", ("hist",)) or []),
                        "resumed": s.resume_info is not None,
                        "cancel_requested": s.cancel_requested,
                        "deadline_in_s": (
                            round(s.deadline_at - now, 3)
                            if s.deadline_at is not None else None),
                    })
                if not s.streaming:
                    # health plane: completion estimate + ETA (a standing
                    # query has no completion fraction — its row carries
                    # the watermark/pane figures instead)
                    prog = (dict(s.progress_snap)
                            if s.progress_snap is not None
                            else obs.progress.TRACKER.snapshot(qid))
                    sessions[qid].update({
                        "progress": prog["fraction"] if prog else None,
                        "eta_s": prog["eta_s"] if prog else None,
                        "progress_basis": prog["basis"] if prog else None,
                    })
                if s.streaming:
                    # standing-query row: source watermarks + pane/late
                    # counters (snapshot lookups — a scrape must never
                    # resurrect a GC'd per-query instrument)
                    wms = {}
                    for info in s.graph.actors.values():
                        if info.kind != "input" or not getattr(
                                info.reader, "UNBOUNDED", False):
                            continue
                        for ch in range(info.channels):
                            wms[f"{info.id}.{ch}"] = s.graph.store.tget(
                                "SWMC", (info.id, ch))
                    sessions[qid].update({
                        "streaming": True,
                        "watermarks": wms,
                        "watermark_lag_s": counters.get(
                            f"stream.watermark_lag_s.{qid}", 0.0),
                        "panes": counters.get(f"stream.panes.{qid}", 0),
                        "late_dropped": counters.get(
                            f"stream.late_dropped.{qid}", 0),
                    })
        return {
            "pool_size": self.pool_size,
            "workers_alive": sum(t.is_alive() for t in self._threads),
            "admission": self.admission.stats(),
            "sessions": sessions,  # live only; finished sessions are GC'd
            "finished": self._finished,
            "scan_cache": scancache.GLOBAL.stats(),
            "queue_wait": obs.REGISTRY.histogram(
                "admission.queue_wait_s").stats(),
        }

    def shutdown(self, timeout: float = 30.0) -> None:
        """Stop the pool; unfinished queries fail with ServiceShutdown."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._wake.notify_all()
        for t in self._threads:
            t.join(timeout)
        for s in list(self._sessions.values()):
            if not s.finished:
                self.admission.cancel(s.query_id)
                s.finish(ServiceShutdown(
                    f"service shut down with query {s.query_id} unfinished"))
                self.admission.release(s.query_id)
        if self._own_spill:
            shutil.rmtree(self._spill_dir, ignore_errors=True)
        if self.metrics_server is not None:
            self.metrics_server.close()
        obs.history.release_sampler()
        obs.RECORDER.record("service.stop", "")

    close = shutdown

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -- scheduler -----------------------------------------------------------
    def _admit_pending(self) -> None:
        admitted, timed_out = self.admission.poll()
        if not admitted and not timed_out:
            return
        to_fail: List = []
        with self._lock:
            now = time.time()
            for qid in admitted:
                s = self._queued.pop(qid, None)
                if s is None:
                    continue
                s.status = RUNNING
                s.started_at = now
                s.last_progress = now
                self._running.append(qid)
                obs.REGISTRY.histogram("admission.queue_wait_s").observe(
                    now - s.submitted_at)
                obs.RECORDER.record("service.admit", qid, q=qid)
            for qid, waited in timed_out:
                s = self._queued.pop(qid, None)
                if s is not None:
                    to_fail.append((s, waited))
        for s, waited in to_fail:
            obs.RECORDER.record("service.admit_timeout", s.query_id,
                                q=s.query_id)
            s.finish(AdmissionTimeout(
                f"query {s.query_id} (est {s.est_bytes >> 20} MiB) waited "
                f"{waited:.1f}s for admission under the "
                f"QK_SERVICE_MEM_BUDGET byte budget"))
            with self._lock:
                self._sessions.pop(s.query_id, None)
                self._finished += 1

    def _next_slot(self) -> Optional[QuerySession]:
        """Round-robin pick of a running session with a free in-flight slot;
        takes the slot (caller MUST release via _release_slot)."""
        with self._lock:
            n = len(self._running)
            for i in range(n):
                idx = (self._rr + i) % n
                s = self._sessions.get(self._running[idx])
                if (s is None or s.status != RUNNING or s.want_exclusive
                        or s.inflight >= s.inflight_cap):
                    continue
                s.inflight += 1
                self._rr = (idx + 1) % max(1, n)
                return s
        return None

    def _release_slot(self, session: QuerySession) -> None:
        with self._lock:
            session.inflight -= 1

    def _worker_loop(self) -> None:
        fruitless = 0  # consecutive non-progress quanta on THIS thread
        while True:
            with self._lock:
                if self._shutdown:
                    return
                n_running = len(self._running)
            self._admit_pending()
            self._reap_deadlines()
            session = self._next_slot()
            if session is None:
                with self._wake:
                    if not self._shutdown:
                        self._wake.wait(0.005)
                continue
            # cooperative cancellation/deadline: honored at the task
            # boundary, before dispatching another quantum for this query
            if session.cancel_requested or (
                    session.deadline_at is not None
                    and time.time() > session.deadline_at):
                self._release_slot(session)
                if session.cancel_requested:
                    self._finish(session, QueryCancelled(
                        f"query {session.query_id} cancelled"))
                else:
                    obs.REGISTRY.counter("cancel.deadline").inc()
                    self._finish(session, DeadlineExceeded(
                        f"query {session.query_id} exceeded its deadline "
                        f"({time.time() - session.submitted_at:.1f}s since "
                        "submit)"))
                continue
            err: Optional[BaseException] = None
            outcome = None
            try:
                outcome = session.engine.service_step()
            except BaseException as e:  # noqa: BLE001 — fail THIS query only
                err = e
            finally:
                self._release_slot(session)
            if err is not None:
                self._finish(session, err)
                continue
            if outcome == "done":
                fruitless = 0
                self._finish(session, None)
            elif outcome == "progress":
                fruitless = 0
                session.last_progress = time.time()
                due = False
                with self._lock:
                    session.handled += 1
                    inj = session.inject
                    due = (inj is not None
                           and session.handled >= inj["after_tasks"])
                if due:
                    self._maybe_inject(session)
            else:  # "wait" / "idle": the query is blocked on its own pipeline
                # standing queries are exempt from the stall timeout — one
                # waiting for data is healthy, and keeps its slot
                # indefinitely (watermark-lag / /status surface staleness);
                # they share the batch queries' backoff below
                if (not session.streaming and
                        time.time() - session.last_progress
                        > self.query_timeout):
                    self._finish(session, QueryStallTimeout(
                        f"query {session.query_id} made no progress for "
                        f"{self.query_timeout:.0f}s "
                        f"(pending tasks: {session.graph.store.ntt_total()})"))
                    continue
                # back off only once every running query got a fruitless
                # quantum from this thread — a single blocked query must
                # neither hot-spin the pool nor throttle its neighbors
                fruitless += 1
                if fruitless >= max(2, 2 * n_running):
                    fruitless = 0
                    time.sleep(0.002)

    def _maybe_inject(self, session: QuerySession) -> None:
        """Run the query's configured fault injection (the
        test_fault_tolerance.py ``inject_failure`` discipline) with the
        session held EXCLUSIVELY — recovery rewrites executor state and
        queues, which must not race a concurrent dispatch of the same
        query.  Other queries keep running throughout."""
        with self._lock:
            inj = session.inject
            if inj is None or session.want_exclusive:
                return
            session.want_exclusive = True  # scheduler stops granting slots
        deadline = time.time() + 30.0
        while True:
            with self._lock:
                if session.inflight == 0:
                    session.inflight = 1
                    break
                if time.time() > deadline:
                    session.want_exclusive = False
                    return  # retry after the next progress quantum
            time.sleep(0.001)
        err = None
        try:
            obs.RECORDER.record("service.inject", session.query_id,
                                q=session.query_id,
                                channels=repr(inj["channels"]))
            session.engine.simulate_failure_and_recover(inj["channels"])
            # standing queries re-arm from the seeded stream-kill plan:
            # kills keep landing over the stream's lifetime, each recovered
            # through the tape-replay protocol
            session.inject = (session.inject_plan.pop(0)
                              if session.inject_plan else None)
        except BaseException as e:  # noqa: BLE001
            err = e
        finally:
            with self._lock:
                session.inflight -= 1
                session.want_exclusive = False
        if err is not None:
            self._finish(session, err)

    def _finish(self, session: QuerySession,
                err: Optional[BaseException]) -> None:
        qid = session.query_id
        # stop granting slots, then wait for in-flight quanta to drain so
        # teardown never races a live dispatch.  The drain window is the
        # query-stall timeout: a quantum still running past it is the same
        # wedged-dispatch judgment the stall detector makes — log loudly
        # and tear down anyway rather than leak the session forever.
        with self._lock:
            if session.status in (DONE, FAILED):
                return
            session.want_exclusive = True
        deadline = time.time() + self.query_timeout
        while time.time() < deadline:
            with self._lock:
                if session.inflight == 0:
                    break
            time.sleep(0.001)
        else:
            obs.diag(f"[service] tearing down {qid} with "
                     f"{session.inflight} dispatch quantum(s) still live "
                     f"after {self.query_timeout:.0f}s drain")
        first = session.finish(err)
        with self._lock:
            if qid in self._running:
                self._running.remove(qid)
            # drop the service-side reference: a persistent service would
            # otherwise retain every finished query's Engine/graph/results
            # forever (the client's QueryHandle keeps the session alive for
            # exactly as long as the client cares)
            self._sessions.pop(qid, None)
            self._finished += 1
            self._wake.notify_all()
        if first:
            self.admission.release(qid)
            kind = "service.fail" if err is not None else "service.done"
            obs.RECORDER.record(kind, qid, q=qid,
                                **({"error": repr(err)} if err else {}))
