"""Admission control for the query service: a byte-budgeted gate.

A query's estimated working set (reader size hints + a pipeline allowance
mirroring the BatchCache byte accounting in runtime/cache.py) is charged
against the service's memory budget (``QK_SERVICE_MEM_BUDGET``).  Queries
that fit start immediately; queries that would overshoot wait in a bounded
FIFO queue (``QK_SERVICE_QUEUE_DEPTH``) and are admitted head-of-line as
finishing queries return budget.  Waiters that outlive the admission
timeout (``QK_SERVICE_ADMIT_TIMEOUT``) fail with a named
``AdmissionTimeout``; a full queue rejects at submit time with
``AdmissionQueueFull``.

Head-of-line (no barging): a small query never jumps a large one that was
queued first — the starvation-freedom half of the fairness story (the
scheduler's round-robin across running queries is the other half).  A query
whose estimate alone exceeds the whole budget is not rejected: it is
admitted when it can run ALONE (budget elasticity, not a hard wall).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple


class AdmissionTimeout(TimeoutError):
    """A queued query waited past the admission timeout without fitting
    under the service memory budget."""


class AdmissionQueueFull(RuntimeError):
    """The admission queue is at QK_SERVICE_QUEUE_DEPTH; the submit is
    rejected outright (bounded queue — no unbounded submit backlog)."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# a query with no usable reader hints still charges something: admitting
# "free" queries without bound would make the gate vacuous
MIN_ESTIMATE_BYTES = 16 << 20
# decoded/device-resident data + in-flight partitions run larger than the
# on-disk bytes the hints report (dictionary decode, padding buckets, the
# pipeline's max_pipeline batches in the BatchCache)
PIPELINE_OVERHEAD = 1.25


def estimate_working_set(graph) -> int:
    """Estimated peak bytes a query holds across the scan cache + batch
    cache while running.

    Measured first: a plan that has run to completion before persisted its
    ledger-observed ``peak_bytes`` under its plan fingerprint
    (obs/memplane.py), and that figure beats any hint-derived guess — it
    already includes decode expansion, pipeline depth and join build state,
    so neither the PIPELINE_OVERHEAD scale nor the MIN_ESTIMATE_BYTES floor
    applies (a genuinely small query should be admitted as small).  Next
    preference: measured source cardinalities (obs/opstats.py cardprofile)
    — actual bytes the plan's scans produced last run, scaled for pipeline
    overhead but NOT floored to MIN_ESTIMATE_BYTES (measured-small stays
    small).  A NEW plan over already-profiled scans still gets measured
    treatment via per-source signatures (planner/cost.py identity) when
    every one of its sources has been measured under some prior plan.
    Only then do reader size hints (readers.py ``size_hint``) apply,
    floored and scaled for decode/pipeline overhead."""
    from quokka_tpu.obs import memplane, opstats

    fp = getattr(graph, "plan_fp", None)
    if fp:
        measured = memplane.measured_footprint(fp)
        if measured:
            return max(int(measured), 1 << 20)
        src_bytes = opstats.measured_source_bytes(fp)
        if src_bytes:
            return max(int(src_bytes * PIPELINE_OVERHEAD), 1 << 20)
    sigs = [getattr(info, "src_sig", None)
            for info in graph.actors.values() if info.kind == "input"]
    if sigs and all(sigs):
        by_sig = opstats.measured_sources()
        vals = [by_sig.get(s, {}).get("bytes") for s in sigs]
        if all(isinstance(v, (int, float)) and v > 0 for v in vals):
            # all sources measured (under whatever plan): charge actuals;
            # partial coverage falls through — mixing measured and hinted
            # sources would understate the unmeasured ones
            return max(int(sum(vals) * PIPELINE_OVERHEAD), 1 << 20)
    total = 0
    for info in graph.actors.values():
        if info.kind != "input" or info.reader is None:
            continue
        hint = None
        fn = getattr(info.reader, "size_hint", None)
        if fn is not None:
            try:
                hint = fn()
            except (OSError, ValueError, TypeError):
                hint = None
        if hint:
            total += int(hint)
    return max(int(total * PIPELINE_OVERHEAD), MIN_ESTIMATE_BYTES)


def mem_budget_bytes() -> int:
    """The configured service memory budget (``QK_SERVICE_MEM_BUDGET``) —
    what a controller constructed with defaults would use.  The alert
    engine reads this to turn ``mem.live_bytes`` gauges into a
    percent-of-budget verdict without holding a controller handle."""
    return _env_int("QK_SERVICE_MEM_BUDGET", 4 << 30)


class AdmissionController:
    """Budget ledger + bounded FIFO wait queue.  Driven by the service
    scheduler: ``offer`` at submit, ``poll`` each scheduling round (returns
    newly admitted ids), ``release`` at query end."""

    def __init__(self,
                 mem_budget: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 max_concurrent: Optional[int] = None,
                 admit_timeout: Optional[float] = None):
        self.mem_budget = (
            _env_int("QK_SERVICE_MEM_BUDGET", 4 << 30)
            if mem_budget is None else mem_budget
        )
        self.queue_depth = (
            _env_int("QK_SERVICE_QUEUE_DEPTH", 16)
            if queue_depth is None else queue_depth
        )
        self.max_concurrent = (
            _env_int("QK_SERVICE_MAX_QUERIES", 8)
            if max_concurrent is None else max_concurrent
        )
        self.admit_timeout = (
            _env_float("QK_SERVICE_ADMIT_TIMEOUT", 120.0)
            if admit_timeout is None else admit_timeout
        )
        self._lock = threading.Lock()
        self._admitted: Dict[str, int] = {}  # query_id -> charged bytes
        self._used = 0
        self._waiting: deque = deque()  # (query_id, est_bytes, enqueued_at)

    # -- submit side ---------------------------------------------------------
    def offer(self, query_id: str, est_bytes: int) -> None:
        """Enqueue a query for admission; raises AdmissionQueueFull."""
        with self._lock:
            if len(self._waiting) >= self.queue_depth:
                raise AdmissionQueueFull(
                    f"admission queue is full ({self.queue_depth} waiting); "
                    "raise QK_SERVICE_QUEUE_DEPTH or retry later"
                )
            self._waiting.append((query_id, int(est_bytes), time.time()))

    # -- scheduler side ------------------------------------------------------
    def _fits(self, est: int) -> bool:
        if len(self._admitted) >= self.max_concurrent:
            return False
        if self._used + est <= self.mem_budget:
            return True
        # oversized query: may run alone rather than never
        return not self._admitted

    def poll(self) -> Tuple[List[str], List[Tuple[str, float]]]:
        """One admission round.  Returns (admitted ids, timed-out
        (id, waited_s) pairs).  FIFO: admission stops at the first waiter
        that does not fit — later waiters cannot barge past it."""
        admitted: List[str] = []
        timed_out: List[Tuple[str, float]] = []
        now = time.time()
        with self._lock:
            while self._waiting:
                qid, est, t0 = self._waiting[0]
                if self._fits(est):
                    self._waiting.popleft()
                    self._admitted[qid] = est
                    self._used += est
                    admitted.append(qid)
                    continue
                if now - t0 > self.admit_timeout:
                    self._waiting.popleft()
                    timed_out.append((qid, now - t0))
                    continue
                break  # head-of-line blocks: no barging
        return admitted, timed_out

    def cancel(self, query_id: str) -> bool:
        """Drop a still-waiting query from the queue (submit error paths)."""
        with self._lock:
            for i, (qid, _est, _t0) in enumerate(self._waiting):
                if qid == query_id:
                    del self._waiting[i]
                    return True
        return False

    def release(self, query_id: str) -> None:
        with self._lock:
            est = self._admitted.pop(query_id, None)
            if est is not None:
                self._used -= est

    def stats(self) -> Dict:
        with self._lock:
            return {
                "budget_bytes": self.mem_budget,
                "used_bytes": self._used,
                "admitted": dict(self._admitted),
                "waiting": [(q, e) for q, e, _t in self._waiting],
                "queue_depth": self.queue_depth,
                "max_concurrent": self.max_concurrent,
            }
