"""Per-query session state + the client-facing QueryHandle.

A session is one query's life inside the service: its namespaced TaskGraph,
its Engine (executors + partition fns + per-query BatchCache), scheduling
state (in-flight count, round-robin bookkeeping, injection hooks), and the
completion plumbing the handle waits on.  The handle is the only object
clients hold; it stays valid after the service GCs the query's namespace
(results, metrics and scan-cache attribution are snapshotted at finish).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

# status values a session moves through (strictly forward)
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


class QuerySession:
    """Internal per-query record.  The service's scheduler lock guards the
    scheduling fields (inflight, want_exclusive); the session's own lock
    guards the one-shot finish transition."""

    def __init__(self, query_id: str, graph, sink_actor: int, est_bytes: int,
                 inflight_cap: int):
        from quokka_tpu.runtime.engine import Engine

        self.query_id = query_id
        self.graph = graph
        self.sink_actor = sink_actor
        self.est_bytes = est_bytes
        self.engine = Engine(graph)
        self.status = QUEUED
        self.error: Optional[BaseException] = None
        self.handle = QueryHandle(self)
        self._done = threading.Event()
        self._finish_lock = threading.Lock()
        # scheduling state (guarded by the SERVICE lock, not this session's)
        self.inflight = 0
        self.inflight_cap = max(1, inflight_cap)
        self.want_exclusive = False
        self.handled = 0  # successfully dispatched tasks (injection trigger)
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # the service's stall detector measures QK_SERVICE_QUERY_TIMEOUT
        # against this (server._worker_loop)
        self.last_progress = time.time()
        # task-latency quantiles snapshotted at finish (the per-query
        # histogram GCs with the namespace; the handle keeps answering)
        self.latency_stats: Optional[Dict] = None
        # fault-injection hook (the test_fault_tolerance.py discipline):
        # {"after_tasks": n, "channels": [(actor, ch), ...]} — consumed once
        self.inject = dict(graph.exec_config.get("inject_failure") or {}) or None
        # standing queries re-arm injection from this queue after each kill
        # (the chaos plane's seeded stream-kill plan) — cumulative
        # after_tasks thresholds, consumed in order
        self.inject_plan: list = []
        # submit_continuous sets True: exempt from the query-stall timeout
        # (an idle standing query is healthy), torn down with its durable
        # recovery state preserved, surfaced as a standing row in /status
        self.streaming = False
        # submit(durable=True) / recover_orphans set True: the engine
        # rewrites a batch resume manifest at each checkpoint, and a
        # service-shutdown teardown preserves the durable recovery trio
        self.durable = False
        # cooperative cancellation + per-query deadline: the worker loop
        # honors both at the next task boundary (server._worker_loop);
        # deadline_at is an absolute time.time() cutoff
        self.cancel_requested = False
        self.deadline_at: Optional[float] = None
        # the resume report from runtime/resume.apply_resume, when this
        # session was re-admitted from an orphaned manifest
        self.resume_info: Optional[Dict] = None
        # backref set by QueryService._enqueue_session (cancel plumbing)
        self._service = None
        # snapshotted at finish, before the namespace GC
        self.scan_stats: Optional[Dict] = None
        # memory-plane footprint ({live, peak, spill_resident} bytes),
        # snapshotted at finish before the ledger drops the query
        self.mem_stats: Optional[Dict] = None
        # operator-statistics snapshot (obs/opstats.py), taken at finish
        # before on_query_gc drops the per-query ledger state
        self.opstats: Optional[Dict] = None
        # final progress snapshot (obs/progress.py), stamped fraction=1.0
        # at finish before the tracker drops the query
        self.progress_snap: Optional[Dict] = None

    # -- finish (exactly once) ----------------------------------------------
    def finish(self, error: Optional[BaseException] = None) -> bool:
        """Transition to DONE/FAILED; returns False if already finished.
        Tears the query down: flush emitters/metrics, snapshot per-query
        stats, then GC the namespace (store tables, spill, checkpoints)."""
        with self._finish_lock:
            if self.status in (DONE, FAILED):
                return False
            self.status = FAILED if error is not None else DONE
            self.error = error
        try:
            try:
                self.engine.service_finalize()
            except Exception as e:  # noqa: BLE001 — keep first error
                if error is None:
                    self.status = FAILED
                    self.error = error = e
            from quokka_tpu.runtime import scancache

            stats = scancache.GLOBAL.stats()["by_query"].get(self.query_id)
            self.scan_stats = dict(stats) if stats else {"hits": 0,
                                                         "misses": 0}
            from quokka_tpu import obs

            h = obs.REGISTRY.histograms().get(
                f"task.latency_s.{self.query_id}")
            self.latency_stats = (h.stats() if h is not None
                                  else obs.Histogram.empty_stats())
            from quokka_tpu.obs import memplane

            self.mem_stats = memplane.LEDGER.query_footprint(self.query_id)
            from quokka_tpu.obs import opstats

            self.opstats = opstats.OPSTATS.snapshot(self.query_id)
            from quokka_tpu.obs import progress as progress_mod

            # a clean finish pins the bar at 1.0; a failed query keeps its
            # last honest estimate — it did NOT complete
            self.progress_snap = progress_mod.TRACKER.on_query_gc(
                self.query_id, finished=error is None)
            try:
                # a standing query that FAILED (or was shut down mid-stream)
                # keeps its durable recovery trio — checkpoints, HBQ spill,
                # resume manifest — so a restarted replica resumes it; a
                # cleanly stopped stream is complete and GCs everything.
                # A DURABLE BATCH query keeps its trio only on service
                # shutdown (the restart/recover_orphans path); success,
                # cancel, deadline and plain failure all GC fully —
                # manifests never accumulate from completed queries
                preserve = self.streaming and error is not None
                if not preserve and self.durable and error is not None:
                    from quokka_tpu.service.server import ServiceShutdown

                    preserve = isinstance(error, ServiceShutdown)
                self.graph.cleanup(preserve_durable=preserve)
            except Exception as e:  # noqa: BLE001 — teardown must not kill
                from quokka_tpu import obs  # the pool thread running it

                obs.diag(f"[service] cleanup of {self.query_id} failed: "
                         f"{e!r}")
        finally:
            self.finished_at = time.time()
            self._done.set()
        return True

    @property
    def finished(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class QueryHandle:
    """What ``QueryService.submit`` returns: completion waiting, the
    (incrementally filling) ResultDataset, per-query metrics and scan-cache
    attribution.  Safe to use from any thread."""

    def __init__(self, session: QuerySession):
        self._s = session
        # per-handle delivery cursor ({channel: last seen seq}) for
        # poll_batches(): a re-attached client seeds it with its own capture
        # frontier and drains exactly the undelivered tail
        self._cursor: Dict[int, int] = {}

    @property
    def query_id(self) -> str:
        return self._s.query_id

    @property
    def status(self) -> str:
        return self._s.status

    @property
    def done(self) -> bool:
        return self._s.finished

    @property
    def error(self) -> Optional[BaseException]:
        return self._s.error

    @property
    def dataset(self):
        """The LIVE ResultDataset — partial while the query streams, the
        full result once ``done``."""
        return self._s.graph.result(self._s.sink_actor)

    @property
    def resume_info(self) -> Optional[Dict]:
        """The resume report ({execs, inputs, replay_specs, ...}) when this
        query was re-admitted from an orphaned manifest; None otherwise."""
        return self._s.resume_info

    @property
    def manifest_path(self) -> Optional[str]:
        """The durable resume-manifest path for a ``durable=True`` query
        (None otherwise) — what ``QueryService.recover_orphans`` scans for
        after a crash."""
        return getattr(self._s.graph, "resume_manifest", None)

    def poll_batches(self):
        """Drain result batches this handle has not seen yet: a list of
        ``(channel, seq, table)`` strictly after the handle's cursor, which
        advances past everything returned.  Seq-keyed, so a resumed query's
        replayed batches never surface twice through one handle."""
        ds = self.dataset
        if ds is None:
            return []
        items = ds.items_since(self._cursor)
        for ch, s, _t in items:
            self._cursor[ch] = s
        return items

    def cancel(self, wait: bool = True,
               timeout: Optional[float] = 60.0) -> "QueryHandle":
        """Cooperatively cancel this query: dispatch stops at the next task
        boundary, admission bytes release, and the namespace/spill/
        checkpoints/manifest GC.  The handle then reports a
        ``QueryCancelled`` error.  Idempotent; a no-op once finished."""
        s = self._s
        s.cancel_requested = True
        svc = s._service
        if svc is not None:
            svc._cancel_ping(s)
        if wait:
            s.wait(timeout)
        return self

    @staticmethod
    def attach(service, query_id: str,
               cursor: Optional[Dict[int, int]] = None) -> "QueryHandle":
        """Re-attach to a query by id (``QueryService.attach``) — a fresh
        handle whose delivery cursor starts at ``cursor`` ({channel: last
        seq the client durably captured}), so the first ``poll_batches``
        returns exactly the undelivered tail."""
        return service.attach(query_id, cursor=cursor)

    def wait(self, timeout: Optional[float] = None) -> "QueryHandle":
        if not self._s.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} did not finish within {timeout}s "
                f"(status={self.status})"
            )
        return self

    def result(self, timeout: Optional[float] = None):
        """Block until the query finishes and return its ResultDataset;
        re-raises the query's error if it failed."""
        self.wait(timeout)
        if self._s.error is not None:
            raise self._s.error
        return self.dataset

    def to_arrow(self, timeout: Optional[float] = None):
        return self.result(timeout).to_arrow()

    def to_df(self, timeout: Optional[float] = None):
        return self.result(timeout).to_df()

    def metrics(self) -> Dict:
        """Per-(actor, channel) progress counters (TaskGraph.metrics shape)
        — answered from the finish-time snapshot after teardown."""
        return self._s.graph.metrics()

    def scan_cache_stats(self) -> Optional[Dict]:
        """This query's shared-scan-cache attribution ({hits, misses}) —
        live while running, snapshotted at finish."""
        if self._s.scan_stats is not None:
            return dict(self._s.scan_stats)
        from quokka_tpu.runtime import scancache

        return scancache.GLOBAL.stats()["by_query"].get(self.query_id)

    def latency_stats(self) -> Optional[Dict]:
        """Per-query task-latency quantiles ({count, sum, p50, p95, p99})
        — live from the typed histogram while running, snapshotted at
        finish (the histogram itself GCs with the query's namespace)."""
        if self._s.latency_stats is not None:
            return dict(self._s.latency_stats)
        from quokka_tpu import obs

        h = obs.REGISTRY.histograms().get(
            f"task.latency_s.{self.query_id}")
        return h.stats() if h is not None else obs.Histogram.empty_stats()

    def memory_stats(self) -> Dict:
        """This query's memory-ledger footprint ({live_bytes, peak_bytes,
        spill_resident_bytes}) — live while running, snapshotted at finish
        (the ledger drops the query's accounting with its namespace)."""
        if self._s.mem_stats is not None:
            return dict(self._s.mem_stats)
        from quokka_tpu.obs import memplane

        return memplane.LEDGER.query_footprint(self.query_id)

    def progress(self) -> Optional[Dict]:
        """Live completion estimate ({fraction, eta_s, basis, ...},
        obs/progress.py): monotone 0→1 fraction blending scanned source
        bytes against the plan's profiled (or size-hinted) totals with
        per-operator row completion, plus an EWMA-throughput ETA.  The
        finish-time snapshot (fraction pinned 1.0 on success) after."""
        if self._s.progress_snap is not None:
            return dict(self._s.progress_snap)
        from quokka_tpu.obs import progress as progress_mod

        return progress_mod.TRACKER.snapshot(self.query_id)

    def explain(self, as_dict: bool = False):
        """EXPLAIN ANALYZE: the plan DAG annotated with measured actuals —
        per-operator rows/selectivity/time share, the per-exchange-edge skew
        report, top hot operators.  Live over the operator-stats ledger
        while the query runs; the finish-time snapshot after.  ``as_dict``
        returns the raw snapshot instead of the rendered text."""
        from quokka_tpu.obs import explain as explain_mod, opstats

        snap = (dict(self._s.opstats) if self._s.opstats is not None
                else opstats.OPSTATS.snapshot(self.query_id))
        if as_dict:
            return snap
        return explain_mod.render(snap)

    def timings(self) -> Dict[str, Optional[float]]:
        s = self._s
        return {
            "submitted_at": s.submitted_at,
            "started_at": s.started_at,
            "finished_at": s.finished_at,
            "queue_s": (s.started_at - s.submitted_at)
            if s.started_at else None,
            "run_s": (s.finished_at - s.started_at)
            if s.started_at and s.finished_at else None,
        }
