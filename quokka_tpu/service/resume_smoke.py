"""resume-smoke: durable batch queries survive a service SIGKILL, end to end.

    python -m quokka_tpu.service.resume_smoke [--seed N] [--dir D]

Two durable batch queries — a grouped aggregate and a TPC-H-shaped
scan-join-aggregate — are killed mid-flight with the service that runs
them, then resumed by a fresh service's supervisor:

1. ground truth: both queries run one-shot through the batch engine
   (integer-valued f64 workloads: sums are order-exact under ANY
   accumulation order, so "bit-exact" is a real claim — and the runs warm
   the process-wide jit caches for the host-sync gate below);
2. a CHILD process hosts a QueryService (stable spill dir) and submits
   both queries with ``durable=True``; once both resume manifests record
   checkpointed progress (state_seq >= 2) the parent SIGKILLs the child —
   a real crash, not a graceful shutdown;
3. the parent starts a fresh service on the same spill dir and calls
   ``recover_orphans()``: both queries re-admit through normal admission
   and resume from their last durable frontier;
4. asserts: both results BIT-EXACT vs the one-shot runs, replay bounded
   (input segments below the frontier are skipped, never re-read — gated
   off under injected corruption, where lineage recompute is the point),
   ``shuffle.host_syncs`` delta ZERO across the resumed run, zero orphan
   manifests left after the clean finishes, and admission bytes back to
   baseline.

``run(d, seed)`` raises AssertionError on any violation — the chaos soak
calls it in-process as its ``batch-resume`` mode (spill/checkpoint
corruption layered on top of the SIGKILL).  Exit nonzero from the CLI.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pandas as pd

N_ROWS = 600_000
N_KEYS = 50
ROW_GROUP = 3_000
CKPT_INTERVAL = 2
KILL_AFTER_STATE = 4  # SIGKILL once every query checkpointed this deep


def _datasets(d: str, seed: int) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    li = pd.DataFrame({
        "k": r.integers(0, N_KEYS, N_ROWS).astype(np.int64),
        "v": r.integers(0, 100, N_ROWS).astype(np.float64),
        "w": r.integers(1, 10, N_ROWS).astype(np.float64),
    })
    dim = pd.DataFrame({
        "k": np.arange(N_KEYS, dtype=np.int64),
        "g": (np.arange(N_KEYS, dtype=np.int64) % 5),
    })
    pq.write_table(pa.Table.from_pandas(li, preserve_index=False),
                   os.path.join(d, "li.parquet"), row_group_size=ROW_GROUP)
    pq.write_table(pa.Table.from_pandas(dim, preserve_index=False),
                   os.path.join(d, "dim.parquet"))


def _build_queries(d: str):
    """The two batch queries — ONE shared definition so the child, the
    one-shot baselines and any debugging rerun lower identical plans
    (identical structural fingerprints are what lets the supervisor
    verify an orphan manifest belongs to this plan)."""
    from quokka_tpu import QuokkaContext

    ctx = QuokkaContext()
    agg = (ctx.read_parquet(os.path.join(d, "li.parquet"))
           .groupby("k").agg_sql("sum(v) as sv, sum(w) as sw, count(*) as n"))
    ctx2 = QuokkaContext()
    join = (ctx2.read_parquet(os.path.join(d, "li.parquet"))
            .join(ctx2.read_parquet(os.path.join(d, "dim.parquet")), on="k")
            .groupby("g").agg_sql("sum(v) as sv, count(*) as n"))
    return agg, join


def _service(d: str):
    from quokka_tpu.service import QueryService

    return QueryService(
        pool_size=2, spill_dir=os.path.join(d, "spill"),
        exec_config={"fault_tolerance": True,
                     "checkpoint_interval": CKPT_INTERVAL})


_SORTS = {"agg": ["k"], "join": ["g"]}


def _truth(d: str):
    agg, join = _build_queries(d)
    return {"agg": agg.collect().sort_values("k").reset_index(drop=True),
            "join": join.collect().sort_values("g").reset_index(drop=True)}


# -- child: killed with SIGKILL mid-query -------------------------------------

def run_child(d: str) -> None:
    agg, join = _build_queries(d)
    svc = _service(d)
    handles = {"agg": svc.submit(agg, durable=True),
               "join": svc.submit(join, durable=True)}
    with open(os.path.join(d, "child_manifests"), "w") as f:
        json.dump({k: h.manifest_path for k, h in handles.items()}, f)
    os.replace(os.path.join(d, "child_manifests"),
               os.path.join(d, "childready"))
    for h in handles.values():
        h.wait(timeout=600)
    # finishing before the SIGKILL means the parent raced too slowly — it
    # checks for this marker and fails loudly instead of "passing" a resume
    # that never resumed anything
    open(os.path.join(d, "childdone"), "w").close()
    while True:  # hold the process for the (now pointless) SIGKILL
        time.sleep(1.0)


def _checkpointed(path: str) -> bool:
    """True once the manifest at ``path`` records a checkpointed exec
    channel at least ``KILL_AFTER_STATE`` deep.  Mid-rewrite manifests
    read as not-yet."""
    from quokka_tpu.runtime import resume as bresume

    try:
        m = bresume.load(path)
    except Exception:
        return False
    return any(e["lct"][0] >= KILL_AFTER_STATE for e in m["execs"].values())


def _exact(got: pd.DataFrame, want: pd.DataFrame, sort_by, what: str) -> None:
    got = got.sort_values(sort_by).reset_index(drop=True)[
        want.columns.tolist()]
    for c in want.columns:
        got[c] = got[c].astype(want[c].dtype)
    pd.testing.assert_frame_equal(got, want, check_exact=True, obj=what)


def run(d: str, seed: int, log=print) -> dict:
    """Full parent flow; raises AssertionError on any violation.  Returns
    a summary dict (replayed/skipped/corrupt counts) for the caller."""
    from quokka_tpu import obs

    os.makedirs(d, exist_ok=True)
    _datasets(d, seed)
    t0 = time.time()
    truth = _truth(d)
    log(f"[resume-smoke] one-shot baselines in {time.time() - t0:.1f}s "
        f"({len(truth['agg'])} keys, {len(truth['join'])} groups)")

    env = dict(os.environ)  # QK_CHAOS passes through when the soak set it
    env.setdefault("JAX_PLATFORMS", "cpu")
    child = subprocess.Popen(
        [sys.executable, "-m", "quokka_tpu.service.resume_smoke",
         "--child", "--dir", d],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    try:
        ready = os.path.join(d, "childready")
        deadline = time.time() + 180
        while not os.path.exists(ready):
            assert child.poll() is None, \
                f"child died before submitting (rc={child.returncode})"
            assert time.time() < deadline, "child never became ready"
            time.sleep(0.1)
        manifests = json.load(open(ready))
        # kill once BOTH manifests record checkpointed progress — mid-query
        while not all(_checkpointed(p) for p in manifests.values()):
            assert not os.path.exists(os.path.join(d, "childdone")), \
                "child finished before the SIGKILL landed (nothing resumed)"
            assert child.poll() is None, \
                f"child exited early (rc={child.returncode})"
            assert time.time() < deadline, \
                "no checkpointed progress before deadline"
            time.sleep(0.02)
    except BaseException:
        child.kill()
        child.wait()
        raise
    os.kill(child.pid, signal.SIGKILL)
    child.wait()
    log("[resume-smoke] child SIGKILLed with both queries mid-flight")

    snap0 = obs.REGISTRY.snapshot()
    svc = _service(d)
    try:
        handles = {h.manifest_path: h for h in svc.recover_orphans()}
        assert len(handles) == 2, \
            f"expected 2 orphans, recovered {len(handles)}"
        summary: dict = {}
        for name, path in manifests.items():
            h = handles[path]
            rep = h.resume_info
            got = h.to_df(timeout=300)
            _exact(got, truth[name], _SORTS[name],
                   f"resumed {name} vs one-shot batch")
            replayed = sum(r["replayed_segments"]
                           for r in rep["inputs"].values())
            skipped = sum(r["skipped_segments"]
                          for r in rep["inputs"].values())
            summary[name] = {
                "replayed_segments": replayed, "skipped_segments": skipped,
                "corrupt_spills": rep["corrupt_spills"],
                "execs": {k: v["state_seq"]
                          for k, v in rep["execs"].items()}}
            log(f"[resume-smoke] resume[{name}]: replayed {replayed} "
                f"segments, skipped {skipped}, corrupt spills "
                f"{rep['corrupt_spills']}, restored {summary[name]['execs']}")
            assert rep["execs"], \
                f"{name}: no exec channel restored from its checkpoint"
            clean = (rep["corrupt_spills"] == 0
                     and not any(v["rewound"]
                                 for v in rep["execs"].values()))
            if clean:
                # bounded replay: the pre-frontier input segments must be
                # SKIPPED (served from durable spill / restored state), not
                # re-read — skipping zero means full recomputation
                assert skipped > 0, \
                    f"{name}: resume replayed from segment zero " \
                    f"(full recomputation)"
        snap1 = obs.REGISTRY.snapshot()
        syncs = (snap1.get("shuffle.host_syncs", 0)
                 - snap0.get("shuffle.host_syncs", 0))
        assert syncs == 0, \
            f"resumed run forced {syncs} blocking host syncs (warm path)"
        assert snap1.get("resume.replayed_tasks", 0) > 0
        leftovers = glob.glob(os.path.join(
            svc._spill_dir, "ckpt", "batch-*.manifest"))
        assert not leftovers, \
            f"orphan manifests left after clean finish: {leftovers}"
        used = svc.admission.stats()["used_bytes"]
        assert used == 0, f"admission bytes not released: {used}"
        summary["host_syncs"] = syncs
        summary["corrupt_detected"] = (
            snap1.get("integrity.corrupt", 0)
            - snap0.get("integrity.corrupt", 0))
    finally:
        svc.shutdown()
    log("[resume-smoke] OK: both durable queries resumed bit-exact through "
        "SIGKILL, bounded replay, 0 host syncs, 0 orphan manifests")
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=20260807)
    ap.add_argument("--dir", default=None,
                    help="stable working dir (default: a fresh tempdir)")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.child:
        run_child(args.dir)
        return 0
    d = args.dir or tempfile.mkdtemp(prefix="resume-smoke-")
    print(f"[resume-smoke] dir={d} seed={args.seed}", flush=True)
    try:
        run(d, args.seed)
    except AssertionError as e:
        print(f"[resume-smoke] FAIL: {e}", flush=True)
        print(f"[resume-smoke] replay: python -m quokka_tpu.service."
              f"resume_smoke --seed {args.seed}", flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
