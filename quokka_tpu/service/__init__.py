"""Query service: persistent multi-query execution over one warm runtime.

``QueryService`` keeps a worker pool, a shared namespaced ControlStore, the
process-global device scan cache and the jit compile caches alive across
queries; ``submit(stream)`` runs many queries concurrently against them
with byte-budgeted admission control and fair round-robin scheduling.
``submit(durable=True)`` adds crash consistency: the engine rewrites a
batch resume manifest at every checkpoint, and a restarted service's
``recover_orphans()`` re-admits every orphaned in-flight query from its
last durable frontier.  ``QueryHandle.cancel()`` and
``submit(deadline_s=...)`` stop dispatch cooperatively at the next task
boundary with full GC (``QueryCancelled`` / ``DeadlineExceeded``).
"""

from quokka_tpu.service.admission import (
    AdmissionController,
    AdmissionQueueFull,
    AdmissionTimeout,
    estimate_working_set,
)
from quokka_tpu.service.server import (
    DeadlineExceeded,
    QueryCancelled,
    QueryService,
    QueryStallTimeout,
    ServiceShutdown,
)
from quokka_tpu.service.session import QueryHandle

__all__ = [
    "AdmissionController",
    "AdmissionQueueFull",
    "AdmissionTimeout",
    "DeadlineExceeded",
    "QueryCancelled",
    "QueryHandle",
    "QueryService",
    "QueryStallTimeout",
    "ServiceShutdown",
    "estimate_working_set",
]
