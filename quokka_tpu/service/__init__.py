"""Query service: persistent multi-query execution over one warm runtime.

``QueryService`` keeps a worker pool, a shared namespaced ControlStore, the
process-global device scan cache and the jit compile caches alive across
queries; ``submit(stream)`` runs many queries concurrently against them
with byte-budgeted admission control and fair round-robin scheduling.
"""

from quokka_tpu.service.admission import (
    AdmissionController,
    AdmissionQueueFull,
    AdmissionTimeout,
    estimate_working_set,
)
from quokka_tpu.service.server import (
    QueryService,
    QueryStallTimeout,
    ServiceShutdown,
)
from quokka_tpu.service.session import QueryHandle

__all__ = [
    "AdmissionController",
    "AdmissionQueueFull",
    "AdmissionTimeout",
    "QueryHandle",
    "QueryService",
    "QueryStallTimeout",
    "ServiceShutdown",
    "estimate_working_set",
]
