"""Window specifications and triggers.

API parity with the reference's windowtypes (pyquokka/windowtypes.py:6-102):
Hopping/Tumbling/Sliding/Session windows plus OnEventTrigger /
OnCompletionTrigger.  Sizes are expressed in the time column's native units
(int days for date32, the timestamp's unit for timestamps, or plain numbers),
or as IntervalLit for convenience.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from quokka_tpu.expression import IntervalLit


def _to_units(v) -> int:
    if isinstance(v, IntervalLit):
        if v.months:
            raise ValueError("calendar-month windows not supported")
        return v.micros  # callers scale to the column's unit
    return v


class Window:
    def __init__(self, size_before, size_after=0, hop=None):
        self.size_before = _to_units(size_before)
        self.size_after = _to_units(size_after)
        self.hop = _to_units(hop) if hop is not None else None


class TumblingWindow(Window):
    """Non-overlapping fixed windows: window_id = t // size."""

    def __init__(self, size):
        super().__init__(size)
        self.size = _to_units(size)
        self.hop = self.size


class HoppingWindow(Window):
    """Fixed windows of `size` starting every `hop` (size % hop == 0 keeps the
    replication factor static — a TPU-friendly constraint)."""

    def __init__(self, size, hop):
        size, hop = _to_units(size), _to_units(hop)
        if size % hop != 0:
            raise ValueError("hopping window requires size % hop == 0")
        super().__init__(size, hop=hop)
        self.size = size


class SlidingWindow(Window):
    """Per-event trailing window [t - size_before, t] (groupby_rolling)."""

    def __init__(self, size_before, size_after=0):
        if _to_units(size_after) != 0:
            raise NotImplementedError("forward-looking sliding windows (todo)")
        super().__init__(size_before, size_after)


class SessionWindow(Window):
    """Gap-based sessions: a new session starts when the gap to the previous
    event (per key) exceeds `timeout`."""

    def __init__(self, timeout):
        super().__init__(timeout)
        self.timeout = _to_units(timeout)


class Trigger:
    pass


class OnEventTrigger(Trigger):
    """Emit incrementally as windows complete (watermark-driven)."""


class OnCompletionTrigger(Trigger):
    """Emit everything once the stream ends."""
