"""Catalog: sampling-based cardinality estimation.

Reference role (pyquokka/catalog.py:12-98): sample a slice of each source,
run the pushed-down predicate on the sample, scale the selectivity by the
full-source size.  Used by the optimizer to order joins and choose broadcast
vs shuffle builds.
"""

from __future__ import annotations

from typing import Dict, Optional

import pyarrow as pa

from quokka_tpu.expression import Expr
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops.expr_compile import CompileError, evaluate_predicate

SAMPLE_ROWS = 8192


class Catalog:
    def __init__(self):
        self._cache: Dict[tuple, Optional[float]] = {}

    def estimate_source(self, reader, predicate: Optional[Expr]) -> Optional[float]:
        """Estimated output rows of a source under `predicate`; None if the
        reader can't report size.  Cached per (reader, predicate) so repeated
        optimize() calls don't re-read Parquet footers and samples."""
        # key on the reader object itself (identity hash): keeping it as a dict
        # key pins it alive, so — unlike id() — the key can't be reused after GC
        key = (reader, predicate.sql() if predicate is not None else None)
        if key in self._cache:
            return self._cache[key]
        est = self._estimate(reader, predicate)
        self._cache[key] = est
        return est

    def _estimate(self, reader, predicate: Optional[Expr]) -> Optional[float]:
        total = self._total_rows(reader)
        if total is None:
            return None
        if predicate is None:
            return float(total)
        sample = self._sample(reader)
        if sample is None or sample.num_rows == 0:
            return float(total)
        try:
            b = bridge.arrow_to_device(sample)
            mask = evaluate_predicate(predicate, b)
            kept = kernels.apply_mask(b, mask).count_valid()
        except CompileError:
            return float(total)
        sel = kept / sample.num_rows
        return float(total) * sel

    def _total_rows(self, reader) -> Optional[int]:
        import pyarrow.parquet as pq

        from quokka_tpu.dataset.readers import (
            InputArrowDataset,
            InputParquetDataset,
            _expand_paths,
        )

        if isinstance(reader, InputArrowDataset):
            return reader.table.num_rows
        if isinstance(reader, InputParquetDataset):
            n = 0
            for f in _expand_paths(reader.path):
                n += pq.ParquetFile(f).metadata.num_rows
            return n
        return None

    def _sample(self, reader) -> Optional[pa.Table]:
        import pyarrow.parquet as pq

        from quokka_tpu.dataset.readers import (
            InputArrowDataset,
            InputParquetDataset,
            _expand_paths,
        )

        if isinstance(reader, InputArrowDataset):
            return reader.table.slice(0, SAMPLE_ROWS)
        if isinstance(reader, InputParquetDataset):
            f = _expand_paths(reader.path)[0]
            pf = pq.ParquetFile(f)
            batches = pf.iter_batches(batch_size=SAMPLE_ROWS)
            try:
                return pa.Table.from_batches([next(batches)])
            except StopIteration:
                return pf.schema_arrow.empty_table()
        return None
