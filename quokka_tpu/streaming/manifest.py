"""Stream resume manifest: what survives a full service restart.

The in-process recovery protocol (chaos kills) replays from the control
store's tapes — but the control store is memory.  For a standing query to
survive a PROCESS death, the durable trio is:

- executor snapshots (CheckpointStore — already durable, checksummed,
  atomic),
- the HBQ spill (already durable when the service runs on a stable
  ``spill_dir``),
- and this manifest: the source segment log (seq -> frozen lineage), the
  per-seq watermarks, and each checkpointed exec channel's recovery point
  ``(state_seq, out_seq)`` + input frontier (the IRT rows).

The engine rewrites the manifest atomically (tmp + integrity frame +
rename) after EVERY successful incremental checkpoint; a crash between
checkpoints resumes from the previous manifest, whose checkpoint blobs are
still on disk (snapshots are only GC'd at clean stream teardown).

``apply_resume`` performs the restart surgery on a freshly lowered graph
(same plan -> same actor ids, verified via the compile plane's structural
plan fingerprint): seed LT/LIT/SWM/IRT/LCT, seed the tailing readers'
discovery state from the recorded segmentation, and replace the initial
NTT tasks with a TapedExecutorTask per checkpointed channel (empty-tape
replay = restore snapshot, then live) plus a TapedInputTask covering only
the segments at/after the checkpointed frontier — zero full-stream
recomputation by construction.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Dict, Optional

from quokka_tpu import obs
from quokka_tpu.runtime import integrity, resume as _resume
from quokka_tpu.runtime.task import ExecutorTask, TapedInputTask

MANIFEST_VERSION = _resume.MANIFEST_VERSION


class StreamResumeError(_resume.ManifestMismatch):
    """The manifest cannot resume this plan (fingerprint mismatch, missing
    actors, or an unreadable manifest) — loud, never a silent fresh start."""


# the structural-fingerprint machinery is shared with batch resume
# (runtime/resume.py) — kept as module names here for existing callers
_exec_desc = _resume._exec_desc


def stream_plan_fingerprint(graph) -> str:
    """Structural fingerprint for resume verification (shared with batch
    resume): stable across process restarts of the SAME standing query — no
    reader size buckets (a tailed file grows between restarts) and no object
    reprs, just topology + operator configuration."""
    return _resume.structural_fingerprint(graph)


def default_path(graph) -> str:
    return os.path.join(_resume.manifest_root(graph),
                        f"stream-{graph.query_id}.manifest")


def _stream_inputs(graph):
    for info in graph.actors.values():
        if info.kind == "input" and getattr(info.reader, "UNBOUNDED", False):
            yield info


def update(graph) -> None:
    """Write the current resume point; called by the engine after each
    successful incremental checkpoint.  A failed write is a SKIPPED manifest
    (the previous one stays valid), never a dead stream."""
    path = getattr(graph, "stream_manifest", None)
    if not path:
        return
    store = graph.store
    m: Dict = {
        "version": MANIFEST_VERSION,
        "kind": "stream",
        "query_id": graph.query_id,
        "plan_fp": stream_plan_fingerprint(graph),
        "written_at": time.time(),
        "inputs": {},
        "execs": {},
    }
    with store.transaction():
        m["execs"] = _resume.collect_exec_channels(graph)
        # retained-history floor per input channel: the oldest segment any
        # RECORDED checkpoint's frontier can still ask for.  Serializing
        # only from there keeps the per-checkpoint manifest work (and its
        # on-disk size) proportional to the checkpointed tail, not the
        # stream's age; a delivered_floor rewind below it fails loudly in
        # apply_resume rather than starving on unlogged segments.
        retain: Dict = {}
        for e in m["execs"].values():
            for hist in e["ckpts"]:
                for src, chans_ in e["irts"].get(hist[0], {}).items():
                    for sch, nxt in chans_.items():
                        key = (src, sch)
                        retain[key] = min(retain.get(key, nxt), nxt)
        for info in _stream_inputs(graph):
            chans = {}
            for ch in range(info.channels):
                last = store.tget("LIT", (info.id, ch), -1)
                # never trim the NEWEST segment: the readers re-derive
                # their discovery position (byte offset / max filename)
                # from the retained tail, which must not be empty
                first = min(retain.get((info.id, ch), 0), max(last, 0))
                segments = []
                swm = {}
                for s in range(first, last + 1):
                    lin = store.tget("LT", (info.id, ch, s))
                    if lin is None:
                        continue
                    segments.append((s, lin))
                    wm = store.tget("SWM", (info.id, ch, s))
                    if wm is not None:
                        swm[s] = wm
                chans[ch] = {"segments": segments, "swm": swm,
                             "last": last,  # true LIT: the tail may be empty
                             "wm": store.tget("SWMC", (info.id, ch))}
            m["inputs"][info.id] = chans
    try:
        # own chaos site (see runtime/resume.py): manifest corruption is a
        # distinct failure domain from checkpoint corruption
        integrity.write_framed_atomic(path, pickle.dumps(m), site="manifest")
    except OSError as e:
        obs.REGISTRY.counter("stream.manifest_skipped").inc()
        obs.diag(f"[stream] manifest write to {path} skipped: {e!r}")
        return
    # a successful manifest write is the durability point: anything below
    # the recorded-checkpoint floor is now unreachable by every recovery
    # path, so the control store can finally drop it
    gc(graph)


def gc(graph) -> Dict[str, int]:
    """Drop control-store rows no RECORDED checkpoint can ask for — the
    ROADMAP's "SWM/segment-log/tape rows grow unboundedly for very long
    streams" leftover.  Standing queries only (``update`` calls this after
    each successful manifest write): the batch engine keeps full lineage
    because its recovery contract includes the ``(0, 0, 0)`` full-replay
    fallback, while a standing query's incremental contract already
    excludes full-stream recompute — resume replays from the recorded
    checkpoint frontier or fails loudly.

    Floor discipline (protocol rule QK015 checks the write/GC pairing):

    - per SOURCE channel: the min input-requirement frontier over every
      recorded checkpoint of every consumer, AND the state-0 frontier of
      any exec channel with no recorded checkpoint yet (its recovery still
      rewinds to ``(0, 0, 0)``, so nothing is dropped until every channel
      has checkpointed past warmup);
    - per EXEC channel: additionally its own oldest recorded checkpoint;
      the tape is trimmed to the COVERING checkpoint for that floor (the
      oldest one whose out_seq is at or below it), so every choice the
      rewind planner can still make has its full tape suffix retained
      (``_recover_channel`` fails loudly if recovery ever points below the
      trimmed base).

    Every per-seq growing row class is reclaimed here — segment log rows
    (LT), watermark rows (SWM), committed-seq membership (GIT, with
    ``_recover_channel`` clamping its rebuild range at the floor), the
    lineage tape (trim), checkpoint HISTORY entries older than the covering
    checkpoint, and their IRT frontier rows — so protocol rule QK015 can
    demand a GC site for every growth-class write.

    Returns {"segments", "swm", "tape", "git", "ckpts"} dropped counts."""
    store = graph.store
    retain: Dict = {}
    exec_hist: Dict = {}
    for info in graph.actors.values():
        if info.kind != "exec":
            continue
        for ch in range(info.channels):
            hist = [tuple(h) for h in
                    (store.tget("LT", ("ckpts", info.id, ch)) or [])]
            exec_hist[(info.id, ch)] = hist
            # a channel with no recorded checkpoint recovers via (0,0,0)
            states = [h[0] for h in hist] or [0]
            for state in states:
                reqs = store.tget("IRT", (info.id, ch, state)) or {}
                for src, chans_ in reqs.items():
                    for sch, nxt in chans_.items():
                        key = (src, sch)
                        retain[key] = min(retain.get(key, nxt), nxt)
    dropped = {"segments": 0, "swm": 0, "tape": 0, "git": 0, "ckpts": 0}
    with store.transaction():
        # 1) input segment log + watermark trail + committed-seq membership
        # below the floor
        for info in _stream_inputs(graph):
            for ch in range(info.channels):
                floor = retain.get((info.id, ch))
                if floor is None:
                    continue
                # never drop the NEWEST segment: readers re-derive their
                # discovery position from the retained tail (same rule as
                # the manifest's serialization floor above)
                last = store.tget("LIT", (info.id, ch), -1)
                floor = min(floor, max(last, 0))
                base = store.tget("LT", ("gc_floor", info.id, ch), 0)
                for s in range(base, floor):
                    store.tdel("LT", (info.id, ch, s))
                    store.tdel("SWM", (info.id, ch, s))
                    store.srem("GIT", (info.id, ch), s)
                    dropped["segments"] += 1
                    dropped["git"] += 1
                if floor > base:
                    store.tset("LT", ("gc_floor", info.id, ch), floor)
        # 2) exec tapes, replayed-emission watermark rows, and checkpoint
        # history older than the covering checkpoint (a history entry whose
        # state precedes the cover can never be chosen by the rewind
        # planner again: every seq the planner may still need is >= the
        # floor, and the cover or a newer checkpoint covers it)
        for (aid, ch), hist in exec_hist.items():
            if not hist:
                continue
            floor = min(h[1] for h in hist)
            if (aid, ch) in retain:
                floor = min(floor, retain[(aid, ch)])
            cover = max((h for h in hist if h[1] <= floor),
                        key=lambda h: h[0], default=None)
            if cover is None:
                continue  # only (0,0,0) covers: nothing is trimmable yet
            tape_base = store.tget("LT", ("tape_base", aid, ch), 0)
            if cover[2] > tape_base:
                dropped["tape"] += cover[2] - tape_base
                store.tape_trim(aid, ch, cover[2])
            base = store.tget("LT", ("gc_floor_swm", aid, ch), 0)
            for s in range(base, cover[1]):
                store.tdel("SWM", (aid, ch, s))
                dropped["swm"] += 1
            if cover[1] > base:
                store.tset("LT", ("gc_floor_swm", aid, ch), cover[1])
            keep = [h for h in hist if h[0] >= cover[0]]
            if len(keep) < len(hist):
                dropped["ckpts"] += len(hist) - len(keep)
                # drop-and-reappend (atomic inside this transaction): the
                # retained suffix survives, the pruned prefix's IRT rows go
                store.tdel("LT", ("ckpts", aid, ch))
                for h in keep:
                    store.tappend("LT", ("ckpts", aid, ch), h)
                for h in hist:
                    if h[0] < cover[0]:
                        store.tdel("IRT", (aid, ch, h[0]))
    if any(dropped.values()):
        obs.REGISTRY.counter("stream.gc_rows").inc(sum(dropped.values()))
    return dropped


def load(path: str) -> Dict:
    """Read and verify a manifest; loud on corruption or version drift —
    resume is an explicit operator request, never a best-effort guess."""
    m = _resume.load_framed(path, err=StreamResumeError)
    if m.get("kind", "stream") != "stream":
        raise StreamResumeError(
            f"{path} is a {m.get('kind')!r} manifest — standing-query "
            "resume needs a stream manifest (batch queries resume through "
            "QueryService.recover_orphans / submit(resume_from=...))")
    return m


def apply_resume(graph, m: Dict, delivered_floor: Optional[int] = None) -> Dict:
    """Rewire a freshly lowered graph to continue from the manifest.
    Returns a resume report: segments replayed per input channel, restored
    exec recovery points.  The graph must have been built with the
    manifest's query_id (checkpoint/spill namespaces must line up).

    ``delivered_floor`` closes the output-commit gap for HARD crashes: a
    pane can be finalized, checkpointed, and lost with the dying process
    before the client ever polled it — resuming from the NEWEST checkpoint
    would then never re-emit it.  A client that durably captured N delta
    tables passes ``delivered_floor=N``; each exec channel restores from
    its newest recovery point whose out_seq <= N (ultimately (0,0,0)), so
    every delta at-or-after the client's capture frontier re-emits
    (at-least-once, deduped downstream by pane identity).  The extra
    replay is bounded by how far the client's capture lagged the
    checkpointer — one poll interval in practice."""
    if graph.query_id != m["query_id"]:
        raise StreamResumeError(
            f"graph namespace {graph.query_id!r} != manifest namespace "
            f"{m['query_id']!r}")
    fp = stream_plan_fingerprint(graph)
    if m.get("plan_fp") is not None and fp != m["plan_fp"]:
        raise StreamResumeError(
            "the resubmitted plan's structural fingerprint differs from the "
            "manifest's — resuming a DIFFERENT query from this checkpoint "
            f"state would corrupt it (manifest {m['plan_fp']!r}, "
            f"plan {fp!r})")
    if graph.hbq is not None:
        # The dead incarnation's spill is NOT replayable across a restart:
        # segments it discovered after the last manifest write carry seq
        # numbers this incarnation will re-assign to DIFFERENTLY-SPLIT
        # re-discoveries, and the seq-keyed cache/HBQ names would collide
        # across incarnations — mixed coverage reads as silent row loss
        # plus a watermark jumped past unconsumed data (rows then drop as
        # late).  Nothing below the restored frontiers is ever consumed,
        # and everything at/after them regenerates deterministically from
        # the manifest's frozen lineages + fresh discovery: wipe the
        # namespace spill and let this incarnation own its own names.
        graph.hbq.wipe()
    store = graph.store
    missing = [a for a in m["inputs"] if a not in graph.actors] + [
        a for (a, _ch) in m["execs"] if a not in graph.actors]
    if missing:
        raise StreamResumeError(
            f"manifest actors {sorted(set(missing))} are not in the lowered "
            "plan — actor ids diverged")
    if delivered_floor is not None:
        for e in m["execs"].values():
            hist = [(0, 0, 0)] + [tuple(h) for h in e["ckpts"]]
            best = max((h for h in hist if h[1] <= delivered_floor),
                       key=lambda h: h[0])
            e["lct"] = (best[0], best[1], 0)
    # the checkpointed input frontier: the minimum next-seq any restored
    # exec channel still needs from each (input actor, channel)
    frontier: Dict = {}
    for (_a, _ch), e in m["execs"].items():
        state_seq = e["lct"][0]
        for src, chans in e["irts"].get(state_seq, {}).items():
            for sch, nxt in chans.items():
                key = (src, sch)
                frontier[key] = min(frontier.get(key, nxt), nxt)
    report = {"inputs": {}, "execs": {}, "frontier": dict(frontier)}
    # -- inputs: seed segment log + watermark trail, replay from frontier --
    for aid, chans in m["inputs"].items():
        info = graph.actors[aid]
        all_segments = []
        for ch, rec in chans.items():
            store.ntt_remove_channel(aid, ch)
            start = frontier.get((aid, ch), 0)
            logged = [s for s, _l in rec["segments"]]
            last = rec.get("last", max(logged, default=-1))
            if start <= last and (not logged or start < min(logged)):
                raise StreamResumeError(
                    f"resume of input ({aid}, {ch}) needs segments from "
                    f"{start} but the manifest retains only "
                    f"{min(logged) if logged else 'none'}..{last} — the "
                    "delivered_floor rewinds past the retained history "
                    "(the client's capture lagged too far behind the "
                    "checkpointer)")
            with store.transaction():
                for s, lin in rec["segments"]:
                    if s >= start:
                        store.tset("LT", (aid, ch, s), lin)
                store.tset("LIT", (aid, ch), last)
                if rec.get("wm") is not None:
                    store.tset("SWMC", (aid, ch), rec["wm"])
                for s, wm in rec["swm"].items():
                    if s >= start:
                        store.tset("SWM", (aid, ch, s), wm)
            tape = sorted(s for s, _l in rec["segments"] if s >= start)
            store.ntt_push(aid, TapedInputTask(aid, ch, tape))
            all_segments.extend(lin for _s, lin in rec["segments"])
            report["inputs"][(aid, ch)] = {
                "replayed_segments": len(tape),
                "skipped_segments": last + 1 - len(tape),
            }
        if hasattr(info.reader, "seed"):
            info.reader.seed(all_segments)
    # -- checkpointed exec channels: empty-tape replay restores the snapshot
    # (shared surgery: re-based recovery point + history, IRT rows, EWT
    # consumption watermarks, TapedExecutorTask — runtime/resume.py)
    for (a, ch), e in m["execs"].items():
        store.ntt_remove_channel(a, ch)
        state_seq, out_seq = _resume.seed_exec_channel(store, a, ch, e)
        report["execs"][(a, ch)] = {"state_seq": state_seq,
                                    "out_seq": out_seq}
    # -- unmanifested exec channels (sinks / stateless passthroughs): their
    # consumption frontier fast-forwards to each resumed producer's out_seq
    # (everything before it was delivered pre-restart)
    for info in graph.actors.values():
        if info.kind != "exec":
            continue
        for ch in range(info.channels):
            if (info.id, ch) in m["execs"]:
                continue
            reqs = store.tget("IRT", (info.id, ch, 0))
            if reqs is None:
                continue
            reqs = {a: dict(c) for a, c in reqs.items()}
            changed = False
            for src in reqs:
                for sch in reqs[src]:
                    prod = m["execs"].get((src, sch))
                    if prod is not None:
                        reqs[src][sch] = max(reqs[src][sch],
                                             prod["lct"][1])
                        changed = True
            if not changed:
                continue
            store.ntt_remove_channel(info.id, ch)
            with store.transaction():
                store.tset("IRT", (info.id, ch, 0), copy.deepcopy(reqs))
                for src, chans in reqs.items():  # same EWT re-basing
                    for sch, nxt in chans.items():
                        store.tset("EWT", (src, sch, info.id, ch), nxt - 1)
            store.ntt_push(info.id,
                           ExecutorTask(info.id, ch, 0, 0, reqs))
    obs.RECORDER.record(
        "stream.resume", graph.query_id, q=graph.query_id,
        replayed=sum(r["replayed_segments"]
                     for r in report["inputs"].values()),
        execs=len(report["execs"]))
    return report
