"""Streaming plane: standing queries over unbounded sources.

The batch engine runs a fixed lineage tape to completion; this package turns
the same push-based runtime into a continuous one (ROADMAP item 4 — the
reference's whole identity: time-series asof joins, windowed aggregates, CEP,
the rottnest backtester, all push-based over *arriving* data):

- **unbounded sources** (``source.py``): a tailing reader watches a growing
  CSV file or a directory of appended Parquet segments and emits new batches
  with monotone segment offsets; every discovered segment is recorded in the
  control store (and the resume manifest), so a segment is read exactly once
  per consumption and re-reads are byte-identical (the lineage discipline).
- **event-time watermarks** (``watermark.py``): each source batch carries the
  watermark ``max_event_time_seen - delay``; the engine threads it through
  the partitioned push path and recovery replay, and streaming executors
  combine per-channel watermarks with a min-clock.
- **incremental executors** (``executors.py``): windowed aggregation and asof
  join that emit *finalized panes* as the watermark passes them instead of
  waiting for end-of-input, drop-and-count late data, and checkpoint through
  the engine's existing checksummed atomic snapshot path.
- **chaos-survivable resume** (``manifest.py``): every incremental checkpoint
  also writes an atomic, integrity-framed stream manifest (source offsets +
  executor recovery points).  A ``QK_CHAOS``-killed worker recovers through
  the normal tape-replay protocol; a full service restart resumes the stream
  from the manifest — replaying only the segments past the checkpointed
  frontier, never the whole stream.
- **service surface** (``service/server.py``):
  ``QueryService.submit_continuous(stream) -> StreamingHandle`` with
  ``poll_deltas()`` / ``stop()``; standing queries coexist with batch
  queries under the same admission/fair-scheduling planes.

Capstone: ``make stream-smoke`` (``python -m quokka_tpu.streaming.smoke``).
"""

from quokka_tpu.streaming.executors import (
    StreamingAsofJoinExecutor,
    StreamingWindowAggExecutor,
)
from quokka_tpu.streaming.handle import StreamingHandle
from quokka_tpu.streaming.plan import tail_asof_join, tail_window_agg
from quokka_tpu.streaming.source import (
    StreamTruncatedError,
    TailingCsvReader,
    TailingParquetDirReader,
)
from quokka_tpu.streaming.watermark import WatermarkClock

__all__ = [
    "StreamTruncatedError",
    "StreamingAsofJoinExecutor",
    "StreamingHandle",
    "StreamingWindowAggExecutor",
    "TailingCsvReader",
    "TailingParquetDirReader",
    "WatermarkClock",
    "tail_asof_join",
    "tail_window_agg",
]
