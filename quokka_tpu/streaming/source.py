"""Unbounded sources: tailing readers over growing inputs.

Same reader protocol as ``dataset/readers.py`` (``get_own_state`` /
``execute``) plus the streaming extensions the engine drives:

- ``UNBOUNDED = True`` marks the reader as a standing source: when the input
  task's tape runs dry the engine calls ``poll(channel)`` for newly appended
  data instead of marking the channel done.
- ``poll(channel)`` returns NEW lineage entries (monotone: each covers bytes
  / files strictly after everything previously discovered).  A lineage, once
  discovered, is FROZEN — ``execute`` re-reads exactly those bytes, so fault-
  tolerant replay and the scan path see byte-identical tables.
- ``lineage_time_max(lineage)`` answers the segment's max event time (parsed
  once at discovery), which the engine turns into the channel watermark
  ``max_seen - watermark_delay`` without any device sync on the push path.
- ``seed(segments)`` (resume): re-adopts a manifest's segment log so
  discovery continues from the recorded offset with the recorded
  segmentation — a restarted replica never re-splits (and never re-reads)
  bytes an executor checkpoint already covers.

Truncation (the tailed file shrinking, or a recorded segment's bytes
changing length) is detected LOUDLY via ``StreamTruncatedError`` — a tailing
source that silently re-reads different bytes would poison exactly-once
recovery.
"""

from __future__ import annotations

import glob as globmod
import os
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as pq


class StreamTruncatedError(RuntimeError):
    """The tailed input lost bytes it already emitted (file truncated or a
    segment rewritten) — the stream's lineage contract is broken and no
    silent recovery is possible."""


class TailingCsvReader:
    """Tail a growing headerless CSV file.

    ``schema``: a ``pa.Schema`` naming + typing the columns (no header row in
    the tailed file — appends are raw data rows).  ``time_col`` names the
    event-time column; ``watermark_delay`` is the allowed disorder in the
    time column's own units (events may arrive up to ``delay`` behind the
    max time seen; anything later is dropped-and-counted by the executors).

    Segments split at newline boundaries; a partial trailing line (an append
    racing the poll) is left unread until its newline lands, so a segment's
    bytes never change after discovery.  Lineage: ``("tail", offset, length,
    t_max)``.
    """

    UNBOUNDED = True

    def __init__(self, path: str, schema: pa.Schema, time_col: str,
                 watermark_delay: float = 0.0,
                 min_segment_bytes: int = 1):
        if time_col not in schema.names:
            raise ValueError(f"time_col {time_col!r} not in schema "
                             f"{schema.names}")
        self.path = path
        self.schema = schema
        self.time_col = time_col
        self.watermark_delay = float(watermark_delay)
        self.min_segment_bytes = int(min_segment_bytes)
        self._next_offset = 0

    # -- reader protocol -----------------------------------------------------
    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        """All segments go to channel 0: a tailed stream is one monotone
        sequence (the streaming plan helpers pin source channels to 1)."""
        out: Dict[int, List] = {ch: [] for ch in range(num_channels)}
        out[0] = self.poll(0) or []
        return out

    def poll(self, channel: int) -> List:
        """Discover bytes appended since the last poll; returns new lineage
        entries (or []).  Only channel 0 produces."""
        if channel != 0:
            return []
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []  # not created yet: an empty stream so far
        if size < self._next_offset:
            raise StreamTruncatedError(
                f"tailed file {self.path} shrank to {size} bytes below the "
                f"already-emitted offset {self._next_offset} — segment "
                "lineage is no longer replayable")
        if size - self._next_offset < self.min_segment_bytes:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._next_offset)
            chunk = f.read(size - self._next_offset)
        # never consume a partial trailing line: the writer may still be
        # mid-append; the segment freezes only at a newline boundary
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        chunk = chunk[: end + 1]
        t_max = self._chunk_time_max(chunk)
        lineage = ("tail", self._next_offset, len(chunk), t_max)
        self._next_offset += len(chunk)
        return [lineage]

    def execute(self, channel: int, lineage) -> pa.Table:
        _, offset, length, _t_max = lineage
        try:
            with open(self.path, "rb") as f:
                f.seek(offset)
                data = f.read(length)
        except OSError as e:
            raise StreamTruncatedError(
                f"tailed file {self.path} unreadable for segment at "
                f"{offset}+{length}: {e}") from e
        if len(data) != length:
            raise StreamTruncatedError(
                f"tailed file {self.path} segment at {offset} expected "
                f"{length} bytes, got {len(data)} — file was truncated "
                "under a live stream")
        return self._parse(data)

    def lineage_time_max(self, lineage) -> float:
        return float(lineage[3])

    def size_hint(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # -- resume ---------------------------------------------------------------
    def seed(self, segments: Sequence) -> None:
        """Adopt a manifest's segment log: discovery continues from the end
        of the recorded segmentation."""
        nxt = 0
        for lin in segments:
            if lin[0] != "tail":
                raise ValueError(f"foreign lineage {lin!r} for a CSV tail")
            nxt = max(nxt, int(lin[1]) + int(lin[2]))
        self._next_offset = nxt

    # -- internals -------------------------------------------------------------
    def _parse(self, data: bytes) -> pa.Table:
        import io

        return pacsv.read_csv(
            io.BytesIO(data),
            read_options=pacsv.ReadOptions(column_names=self.schema.names),
            convert_options=pacsv.ConvertOptions(
                column_types={f.name: f.type for f in self.schema}),
        )

    def _chunk_time_max(self, chunk: bytes) -> float:
        # one extra parse per segment at discovery time (host-side, off the
        # push path) buys a sync-free watermark: the engine reads t_max from
        # the lineage instead of reducing the device column
        t = self._parse(chunk)
        col = t.column(self.time_col)
        import pyarrow.compute as pc

        v = pc.max(col).as_py()
        return float(v) if v is not None else float("-inf")


class TailingParquetDirReader:
    """Tail a directory of appended Parquet segment files.

    The writer contract is atomic appends: each segment file appears fully
    written (write-to-temp + rename).  New files are discovered in sorted
    filename order — the append order must be filename-monotone (e.g.
    zero-padded sequence numbers).  Lineage: ``("pqseg", filename, t_max)``
    with ``t_max`` taken from row-group statistics (or a column scan when
    stats are absent).
    """

    UNBOUNDED = True

    def __init__(self, path: str, time_col: str,
                 watermark_delay: float = 0.0, pattern: str = "*.parquet"):
        self.path = path
        self.time_col = time_col
        self.watermark_delay = float(watermark_delay)
        self.pattern = pattern
        self._seen: set = set()

    @property
    def schema(self) -> pa.Schema:
        files = self._list()
        if not files:
            raise ValueError(
                f"cannot derive a schema from empty segment dir {self.path}; "
                "write at least one segment first")
        return pq.ParquetFile(os.path.join(self.path, files[0])).schema_arrow

    def get_own_state(self, num_channels: int) -> Dict[int, List]:
        out: Dict[int, List] = {ch: [] for ch in range(num_channels)}
        out[0] = self.poll(0) or []
        return out

    def poll(self, channel: int) -> List:
        if channel != 0:
            return []
        new = []
        for f in self._list():
            if f in self._seen:
                continue
            self._seen.add(f)
            new.append(("pqseg", f, self._file_time_max(f)))
        return new

    def execute(self, channel: int, lineage) -> pa.Table:
        _, fname, _t_max = lineage
        full = os.path.join(self.path, fname)
        try:
            return pq.read_table(full)
        except (OSError, pa.ArrowInvalid) as e:
            raise StreamTruncatedError(
                f"parquet segment {full} vanished or became unreadable "
                f"under a live stream: {e}") from e

    def lineage_time_max(self, lineage) -> float:
        return float(lineage[2])

    def size_hint(self) -> int:
        total = 0
        for f in self._list():
            try:
                total += os.path.getsize(os.path.join(self.path, f))
            except OSError:
                continue  # segment raced a writer rename: skip the estimate
        return total

    def seed(self, segments: Sequence) -> None:
        names = {lin[1] for lin in segments}
        self._seen = set(names)
        if names:
            # the manifest's segment log may be trimmed to the retained
            # checkpoint tail: discovery is filename-monotone, so anything
            # sorting at/below the newest logged segment was consumed by
            # the previous incarnation and must not re-discover
            hi = max(names)
            self._seen.update(f for f in self._list() if f <= hi)

    def _list(self) -> List[str]:
        try:
            return sorted(
                os.path.basename(p)
                for p in globmod.glob(os.path.join(self.path, self.pattern)))
        except OSError:
            return []

    def _file_time_max(self, fname: str) -> float:
        pf = pq.ParquetFile(os.path.join(self.path, fname))
        idx = pf.schema_arrow.names.index(self.time_col)
        best: Optional[float] = None
        for rg in range(pf.metadata.num_row_groups):
            st = pf.metadata.row_group(rg).column(idx).statistics
            if st is None or not st.has_min_max:
                best = None
                break
            v = float(st.max)
            best = v if best is None else max(best, v)
        if best is None:  # no stats: scan the one column
            import pyarrow.compute as pc

            v = pc.max(pf.read([self.time_col]).column(0)).as_py()
            best = float(v) if v is not None else float("-inf")
        return best
