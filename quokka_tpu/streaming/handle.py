"""StreamingHandle: the client surface of a standing query.

What ``QueryService.submit_continuous`` returns.  Deltas are the finalized
panes the sink has received, delivered in (channel, seq) order and at most
once per seq within this handle's lifetime (recovery replay OVERWRITES seqs
with byte-identical tables, so the cursor also makes redelivery invisible).

Across a full service restart, delivery is at-least-once with deterministic
pane identities: the resumed stream re-emits everything after the last
incremental checkpoint, and each windowed-agg row carries its
``(window_start, *keys)`` pane key (asof rows carry their probe row) — a
client that merges deltas by pane key converges to the exactly-once final
state, which is what ``make stream-smoke`` asserts bit-exactly against the
one-shot batch run.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class StreamingHandle:
    """Poll deltas, stop, and observe a standing query.  Thread-safe for a
    single polling consumer."""

    def __init__(self, session, resume_info: Optional[Dict] = None):
        self._s = session
        self._cursor: Dict[int, int] = {}
        self.resume_info = resume_info
        # newest pane key seen in delivered deltas (monotone by max): a
        # standing query has no completion fraction, so its progress is
        # "how far has finalized output gotten" — the pane frontier
        self._pane_frontier: Optional[float] = None

    # -- identity / status ----------------------------------------------------
    @property
    def query_id(self) -> str:
        return self._s.query_id

    @property
    def status(self) -> str:
        return self._s.status

    @property
    def done(self) -> bool:
        return self._s.finished

    @property
    def error(self):
        return self._s.error

    @property
    def manifest_path(self) -> Optional[str]:
        return getattr(self._s.graph, "stream_manifest", None)

    def watermark(self) -> Optional[float]:
        """Min source watermark across the query's unbounded inputs (None
        until every channel has produced)."""
        wms = []
        g = self._s.graph
        for info in g.actors.values():
            if info.kind != "input" or not getattr(info.reader, "UNBOUNDED",
                                                   False):
                continue
            for ch in range(info.channels):
                wms.append(g.store.tget("SWMC", (info.id, ch)))
        if not wms or any(w is None for w in wms):
            return None
        return min(wms)

    # -- deltas ---------------------------------------------------------------
    def poll_deltas(self) -> List:
        """New finalized-pane tables since the last poll (possibly []).
        Non-blocking; tables are pyarrow, in sink (channel, seq) order."""
        ds = self._s.graph.result(self._s.sink_actor)
        if ds is None:
            return []
        out = []
        for ch, seq, table in ds.items_since(self._cursor):
            self._cursor[ch] = max(self._cursor.get(ch, -1), seq)
            self._note_frontier(table)
            out.append(table)
        return out

    def _note_frontier(self, table) -> None:
        """Advance the pane frontier from a delivered delta's pane keys
        (windowed-agg rows carry ``window_start``; deltas without it —
        asof probe rows — don't move the frontier)."""
        try:
            cols = getattr(table, "column_names", None) or []
            if "window_start" not in cols:
                return
            col = table.column("window_start")
            if len(col) == 0:
                return
            import pyarrow.compute as pc

            newest = pc.max(col).as_py()
        except Exception:
            return  # a malformed delta must not break delivery
        if newest is None:
            return
        newest = float(newest)
        if self._pane_frontier is None or newest > self._pane_frontier:
            self._pane_frontier = newest

    def progress(self) -> Dict:
        """The standing-query progress view: not a completion fraction (an
        unbounded query never completes) but the stream's forward motion —
        source watermark, the newest finalized pane delivered to THIS
        handle, pane/late counters, and the current watermark lag.  Counter
        lookups are snapshot reads: a poll must never resurrect a GC'd
        per-query instrument."""
        from quokka_tpu import obs

        qid = self.query_id
        snap = obs.REGISTRY.snapshot()
        return {
            "query_id": qid,
            "streaming": True,
            "watermark": self.watermark(),
            "pane_frontier": self._pane_frontier,
            "panes": snap.get(f"stream.panes.{qid}", 0),
            "late_dropped": snap.get(f"stream.late_dropped.{qid}", 0),
            "watermark_lag_s": snap.get(
                f"stream.watermark_lag_s.{qid}", 0.0),
        }

    # -- lifecycle ------------------------------------------------------------
    def stop(self, timeout: Optional[float] = 120.0) -> "StreamingHandle":
        """Graceful end-of-stream: sources stop at their currently
        discovered segments, every open pane flushes through the normal
        end-of-input path, and the query completes — final state is the
        bit-exact equivalent of a one-shot batch run over everything
        consumed.  Blocks until drained; re-raises the query's error."""
        g = self._s.graph
        for info in g.actors.values():
            if info.kind == "input" and getattr(info.reader, "UNBOUNDED",
                                                False):
                g.store.tset("SST", info.id, True)
        if not self._s.wait(timeout):
            raise TimeoutError(
                f"standing query {self.query_id} did not drain within "
                f"{timeout}s of stop() (status={self.status})")
        if self._s.error is not None:
            raise self._s.error
        return self

    def wait(self, timeout: Optional[float] = None) -> "StreamingHandle":
        if not self._s.wait(timeout):
            raise TimeoutError(
                f"standing query {self.query_id} still running after "
                f"{timeout}s (status={self.status})")
        return self

    def metrics(self) -> Dict:
        return self._s.graph.metrics()
