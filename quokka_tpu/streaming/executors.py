"""Incremental streaming executors: watermark-driven pane emission.

Both executors follow the engine's normal Executor protocol — watermark
handling rides entirely on the batches (``_stream_wm`` / ``_stream_ch``
attrs stamped by the engine, persisted per seq in the control store so
recovery replay re-presents the identical watermark sequence).  That keeps
``execute()`` a pure function of (restored state, batch sequence): the tape
replay's determinism assertion holds for streams exactly as it does for
batch queries.

Emission model: each ``execute`` call may return ONE delta batch — the panes
the current watermark just finalized.  ``done`` flushes everything that
remains (end-of-stream finalizes all state), which is what makes a stopped
stream bit-exact with the equivalent one-shot batch query.  Late events —
rows belonging to an already-finalized pane — are dropped and counted
(``stream.late_dropped``; a per-query twin GCs with the namespace).

State is host-side (pandas) and picklable: these operators are bounded by
the number of OPEN panes / pending rows, not by stream length, and their
``checkpoint()``/``restore()`` ride the engine's checksummed atomic
checkpoint path (SUPPORTS_CHECKPOINT).  Counters are resolved at
``bind_query`` time (called by the engine after the per-channel factory
copy), never deep-copied, and never included in checkpoints — replayed
drops may recount, which is the usual at-least-once counter semantic.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from quokka_tpu.executors.base import Executor
from quokka_tpu.ops import bridge
from quokka_tpu.ops.batch import DeviceBatch
from quokka_tpu.streaming.watermark import WatermarkClock

_AGG_FNS = ("sum", "count", "min", "max")


class _StreamingExecutor(Executor):
    SUPPORTS_CHECKPOINT = True

    def bind_query(self, query_id: Optional[str]) -> None:
        """Resolve pane/late counters once per live instance (global family
        plus per-query twins, GC'd with the namespace in TaskGraph.cleanup)."""
        from quokka_tpu import obs

        self._counters = {}
        for name in ("stream.panes", "stream.late_dropped"):
            insts = [obs.REGISTRY.counter(name)]
            if query_id is not None:
                insts.append(obs.REGISTRY.counter(f"{name}.{query_id}"))
            self._counters[name] = insts

    def _count(self, name: str, n: int) -> None:
        for c in getattr(self, "_counters", {}).get(name, ()):
            c.inc(n)

    def _observe_batch(self, clock: WatermarkClock, batch: DeviceBatch,
                       stream_id: int) -> None:
        wm = getattr(batch, "_stream_wm", None)
        if wm is not None:
            clock.observe(stream_id, getattr(batch, "_stream_ch", 0), wm)

    @staticmethod
    def _to_table(df: pd.DataFrame) -> DeviceBatch:
        return bridge.arrow_to_device(
            pa.Table.from_pandas(df, preserve_index=False))


class StreamingWindowAggExecutor(_StreamingExecutor):
    """Tumbling-window aggregation with incremental, watermark-driven pane
    emission.

    ``aggs``: ``[(out_name, fn, col), ...]`` with fn in sum/count/min/max
    (combinable partials — pane state is one scalar per agg per key, bounded
    by open panes, never by stream length).  Output schema:
    ``[window_start, window_end, *by, *out_names]``; a pane (one window,
    every key) is emitted exactly once, in window order, when the watermark
    passes its end.  Pane identity for client-side delta dedup is
    ``(window_start, *by)``.
    """

    def __init__(self, time_col: str, by: Sequence[str], size,
                 aggs: Sequence[Tuple[str, str, Optional[str]]],
                 n_source_channels: int = 1):
        for name, fn, _col in aggs:
            if fn not in _AGG_FNS:
                raise ValueError(f"agg {name}={fn!r} not in {_AGG_FNS}")
        self.time_col = time_col
        self.by = list(by)
        self.size = size
        self.aggs = [(n, f, c) for n, f, c in aggs]
        self.clock = WatermarkClock({0: n_source_channels})
        # (window_id, key_tuple) -> [partial per agg]
        self.panes: Dict[Tuple, List] = {}
        self.finalized_upto: float = -math.inf  # window ids below are closed
        self.late_rows = 0

    def plan_signature(self):
        """Stable operator identity for the resume manifest's plan check."""
        return ("winagg", self.time_col, tuple(self.by), self.size,
                tuple(self.aggs))

    # -- engine protocol -----------------------------------------------------
    def current_watermark(self, channel: int) -> float:
        return self.clock.current()

    def execute(self, batches: List[DeviceBatch], stream_id: int,
                channel: int) -> Optional[DeviceBatch]:
        for b in batches:
            df = bridge.to_pandas(b)
            if df is not None and len(df):
                self._absorb(df)
            # the batch's own rows are never late against its own watermark:
            # absorb first, then advance the clock
            self._observe_batch(self.clock, b, stream_id)
        return self._finalize(self.clock.current())

    def source_done(self, stream_id: int, channel: int) -> Optional[DeviceBatch]:
        self.clock.stream_done(stream_id)
        return self._finalize(self.clock.current())

    def done(self, channel: int) -> Optional[DeviceBatch]:
        return self._finalize(None, flush_all=True)

    # -- state ----------------------------------------------------------------
    def checkpoint(self):
        return {
            "clock": self.clock.snapshot(),
            "panes": {k: list(v) for k, v in self.panes.items()},
            "finalized_upto": self.finalized_upto,
            "late_rows": self.late_rows,
        }

    def restore(self, state) -> None:
        self.clock.restore(state["clock"])
        self.panes = {k: list(v) for k, v in state["panes"].items()}
        self.finalized_upto = state["finalized_upto"]
        self.late_rows = state["late_rows"]

    # -- internals -------------------------------------------------------------
    def _absorb(self, df: pd.DataFrame) -> None:
        t = df[self.time_col].to_numpy()
        wid = np.floor_divide(t, self.size)
        late = wid < self.finalized_upto
        n_late = int(late.sum())
        if n_late:
            self.late_rows += n_late
            self._count("stream.late_dropped", n_late)
            df = df.loc[~late]
            wid = wid[~late]
        if not len(df):
            return
        # EXPLAIN ANALYZE: rows absorbed into open panes (post-late-drop)
        from quokka_tpu.obs import opstats

        opstats.note(pane_rows=len(df))
        # de-duplicated selection: two aggs over one column (min+max) or an
        # agg column doubling as a key would otherwise produce duplicate
        # labels, and gdf[col] would hand back a DataFrame instead of a
        # Series (a Series-valued pane partial poisons finalization)
        cols = list(dict.fromkeys(
            [c for _n, _f, c in self.aggs if c is not None] + self.by))
        work = df[cols].copy() if self.by else df.copy()
        work["__wid"] = wid
        grouped = work.groupby(["__wid"] + self.by, sort=True)
        for gkey, gdf in grouped:
            gkey = gkey if isinstance(gkey, tuple) else (gkey,)
            pane = (gkey[0], tuple(gkey[1:]))
            cur = self.panes.get(pane)
            if cur is None:
                cur = self.panes[pane] = [None] * len(self.aggs)
            for i, (_name, fn, col) in enumerate(self.aggs):
                if fn == "count":
                    part = len(gdf)
                    cur[i] = part if cur[i] is None else cur[i] + part
                    continue
                vals = gdf[col]
                part = getattr(vals, fn)()
                if cur[i] is None:
                    cur[i] = part
                elif fn == "sum":
                    cur[i] = cur[i] + part
                elif fn == "min":
                    cur[i] = min(cur[i], part)
                else:
                    cur[i] = max(cur[i], part)

    def _finalize(self, wm: Optional[float],
                  flush_all: bool = False) -> Optional[DeviceBatch]:
        if flush_all:
            close = sorted(self.panes)
        else:
            if wm is None or wm == -math.inf:
                return None
            # pane [w*size, (w+1)*size) is complete once every event time
            # strictly below the watermark is final: end <= wm closes it
            close = sorted(k for k in self.panes if (k[0] + 1) * self.size <= wm)
        if not close:
            return None
        rows = []
        for key in close:
            wid, gkey = key
            partials = self.panes.pop(key)
            rows.append((wid * self.size, (wid + 1) * self.size)
                        + gkey + tuple(partials))
        if not flush_all:
            self.finalized_upto = max(self.finalized_upto, close[-1][0] + 1)
        else:
            self.finalized_upto = math.inf
        names = (["window_start", "window_end"] + self.by
                 + [n for n, _f, _c in self.aggs])
        df = pd.DataFrame.from_records(rows, columns=names)
        self._count("stream.panes", len(close))
        return self._to_table(df)


class StreamingAsofJoinExecutor(_StreamingExecutor):
    """Continuous backward asof join (trades ⟕ last quote at-or-before).

    Streams: 0 = left (probe, e.g. trades), 1 = right (reference, e.g.
    quotes).  Rows finalize when the combined watermark passes their event
    time: every quote at or before a finalized trade has, by the watermark
    claim, already arrived — so the pandas ``merge_asof`` over the finalized
    slice matches what the one-shot batch asof produces (pandas tie
    semantics, the same contract the batch asof kernels are tested against).

    Right-side state is pruned to the last quote per key at the finalized
    boundary plus everything after it — bounded by key cardinality + open
    disorder window.  Late rows on either side (event time below the
    finalized boundary) are dropped and counted: a late quote could rewrite
    already-emitted joins, which exactly-once delivery forbids.
    """

    def __init__(self, on: str, left_by: Sequence[str],
                 right_by: Sequence[str], left_cols: Sequence[str],
                 right_cols: Sequence[str], suffix: str = "_2",
                 n_left_channels: int = 1, n_right_channels: int = 1):
        self.on = on
        self.left_by = list(left_by)
        self.right_by = list(right_by)
        self.left_cols = list(left_cols)
        self.right_cols = list(right_cols)
        self.suffix = suffix
        self.rpayload = [c for c in self.right_cols
                         if c not in set(self.right_by) and c != on]
        self.out_cols = self.left_cols + [
            c + suffix if c in set(self.left_cols) else c
            for c in self.rpayload
        ]
        self.clock = WatermarkClock({0: n_left_channels,
                                     1: n_right_channels})
        self.left_buf: List[pd.DataFrame] = []
        self.right_buf: List[pd.DataFrame] = []
        self.finalized_to: float = -math.inf
        self.late_rows = 0

    def plan_signature(self):
        """Stable operator identity for the resume manifest's plan check."""
        return ("stream_asof", self.on, tuple(self.left_by),
                tuple(self.right_by), tuple(self.left_cols),
                tuple(self.right_cols), self.suffix)

    # -- engine protocol -----------------------------------------------------
    def current_watermark(self, channel: int) -> float:
        return self.clock.current()

    def execute(self, batches: List[DeviceBatch], stream_id: int,
                channel: int) -> Optional[DeviceBatch]:
        for b in batches:
            df = bridge.to_pandas(b)
            if df is not None and len(df):
                self._absorb(df, stream_id)
            self._observe_batch(self.clock, b, stream_id)
        return self._finalize(self.clock.current())

    def source_done(self, stream_id: int, channel: int) -> Optional[DeviceBatch]:
        self.clock.stream_done(stream_id)
        return self._finalize(self.clock.current())

    def done(self, channel: int) -> Optional[DeviceBatch]:
        return self._finalize(None, flush_all=True)

    # -- state ----------------------------------------------------------------
    def checkpoint(self):
        return {
            "clock": self.clock.snapshot(),
            "left": list(self.left_buf),
            "right": list(self.right_buf),
            "finalized_to": self.finalized_to,
            "late_rows": self.late_rows,
        }

    def restore(self, state) -> None:
        self.clock.restore(state["clock"])
        self.left_buf = list(state["left"])
        self.right_buf = list(state["right"])
        self.finalized_to = state["finalized_to"]
        self.late_rows = state["late_rows"]

    # -- internals -------------------------------------------------------------
    def _absorb(self, df: pd.DataFrame, stream_id: int) -> None:
        late = df[self.on].to_numpy() < self.finalized_to
        n_late = int(late.sum())
        if n_late:
            self.late_rows += n_late
            self._count("stream.late_dropped", n_late)
            df = df.loc[~late]
        if not len(df):
            return
        (self.left_buf if stream_id == 0 else self.right_buf).append(df)

    def _finalize(self, wm: Optional[float],
                  flush_all: bool = False) -> Optional[DeviceBatch]:
        if flush_all:
            boundary = math.inf
        else:
            if wm is None or wm == -math.inf:
                return None
            boundary = wm
        if boundary <= self.finalized_to and not flush_all:
            return None
        trades = (pd.concat(self.left_buf, ignore_index=True)
                  if self.left_buf else None)
        if trades is not None:
            # events strictly below the watermark are final; == wm may still
            # gain an earlier quote, so it stays pending
            fin = trades[self.on].to_numpy() < boundary
            chunk, rest = trades.loc[fin], trades.loc[~fin]
            self.left_buf = [rest.reset_index(drop=True)] if len(rest) else []
        else:
            chunk = None
        quotes = (pd.concat(self.right_buf, ignore_index=True)
                  if self.right_buf else None)
        usable = None
        if quotes is not None:
            qfin = quotes[self.on].to_numpy() < boundary
            usable = quotes.loc[qfin]
            # prune: the last usable quote per key still answers future
            # trades; everything at/after the boundary stays whole
            keep = []
            if len(usable):
                tail = (usable.sort_values(self.on, kind="mergesort")
                        .groupby(self.right_by, sort=False).tail(1)
                        if self.right_by else
                        usable.sort_values(self.on, kind="mergesort").tail(1))
                keep.append(tail)
            pend = quotes.loc[~qfin]
            if len(pend):
                keep.append(pend)
            self.right_buf = ([pd.concat(keep, ignore_index=True)]
                              if keep else [])
        self.finalized_to = max(self.finalized_to, boundary)
        if chunk is None or not len(chunk):
            return None
        out = self._join(chunk, usable)
        self._count("stream.panes", 1)
        return self._to_table(out)

    def _join(self, chunk: pd.DataFrame,
              usable: Optional[pd.DataFrame]) -> pd.DataFrame:
        chunk = chunk.sort_values(self.on, kind="mergesort") \
                     .reset_index(drop=True)
        if usable is None or not len(usable):
            out = chunk.copy()
            for c in self.rpayload:
                name = c + self.suffix if c in set(self.left_cols) else c
                out[name] = np.nan
            return out[self.out_cols]
        usable = usable.sort_values(self.on, kind="mergesort") \
                       .reset_index(drop=True)
        kw = {}
        if self.left_by:
            kw = {"left_by": self.left_by, "right_by": self.right_by}
        out = pd.merge_asof(
            chunk, usable[[self.on] + self.right_by + self.rpayload],
            on=self.on, direction="backward",
            suffixes=("", self.suffix), **kw)
        return out[self.out_cols]
