"""Event-time watermarks for standing queries.

A watermark is a claim: *no future batch on this channel carries an event
with time < wm*.  Sources derive it as ``max_event_time_seen -
watermark_delay`` (tailing readers record each segment's max event time in
its lineage at discovery); the engine stamps it onto every pushed batch and
persists it per output seq in the control store (``SWM``), so fault-tolerant
tape replay re-presents the exact watermark sequence and replayed emission
decisions stay deterministic.

Executors combine per-channel watermarks with :class:`WatermarkClock` — the
min across every feeding channel of every live input stream (Flink's
low-watermark rule).  A finalized pane is one whose window end ``<=`` the
clock; events that arrive for an already-finalized pane are late and are
dropped-and-counted (``stream.late_dropped``).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple


class WatermarkClock:
    """Min-combine of per-(stream, channel) watermark high-water marks.

    ``channels_per_stream`` declares every feeding channel up front, so the
    clock stays at ``-inf`` until EVERY channel has reported (a pane must
    never finalize because a slow channel hasn't spoken yet).  A stream
    marked done contributes ``+inf`` (its channels are complete).  Picklable:
    snapshots ride executor checkpoints.
    """

    def __init__(self, channels_per_stream: Dict[int, int]):
        self._wm: Dict[Tuple[int, int], float] = {
            (s, ch): -math.inf
            for s, n in channels_per_stream.items() for ch in range(n)
        }
        self._done: set = set()

    def observe(self, stream: int, channel: int, wm: float) -> None:
        """Record a channel watermark; watermarks only move forward."""
        key = (stream, channel)
        cur = self._wm.get(key, -math.inf)
        if wm > cur:
            self._wm[key] = float(wm)

    def stream_done(self, stream: int) -> None:
        """An exhausted stream stops gating the clock (contributes +inf)."""
        self._done.add(stream)

    def current(self) -> float:
        live = [wm for (s, _ch), wm in self._wm.items() if s not in self._done]
        return min(live) if live else math.inf

    # -- checkpoint ----------------------------------------------------------
    def snapshot(self) -> Dict:
        return {"wm": dict(self._wm), "done": sorted(self._done)}

    def restore(self, snap: Dict) -> None:
        self._wm = dict(snap["wm"])
        self._done = set(snap["done"])
