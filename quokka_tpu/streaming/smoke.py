"""stream-smoke: chaos-survivable standing queries, end to end.

    python -m quokka_tpu.streaming.smoke [--seed N] [--dir D]

Two standing queries — a continuous tumbling-window aggregate and a
continuous asof join — run over tailed CSV sources that a writer thread
keeps appending to, under a seeded ``QK_CHAOS`` kill plan, THROUGH a hard
process death:

1. ground truth: both queries run one-shot through the batch engine over
   the complete inputs (integer-valued f64 workloads: sums are order-exact,
   so "bit-exact" is a real claim);
2. phase A: a CHILD process hosts a QueryService (stable spill dir),
   submits both standing queries, and streams every delta it polls to
   JSONL.  Seeded chaos kills land on the streaming operators mid-stream
   and recover through the tape-replay protocol.  Once both resume
   manifests exist and deltas are flowing, the parent SIGKILLs the child —
   a real crash, not a graceful shutdown;
3. phase B: the parent resumes BOTH streams from their manifests in a
   fresh service while the writers are still appending, waits for the
   watermarks to catch up, stops, and merges phase A + B deltas by pane
   identity (duplicate deliveries must be byte-identical — that is the
   exactly-once state claim);
4. asserts: merged final state BIT-EXACT vs the one-shot batch runs, zero
   late drops, and the resume replayed only the post-frontier segment tail
   (bounded by the checkpoint interval), never the whole stream.

Exit nonzero on any violation; prints the seed for replay.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa

WINDOW = 200
CKPT_INTERVAL = 4
N_EVENTS = 9000
N_TRADES = 5000
N_QUOTES = 4000
N_KEYS = 6
T_MAX = 4000


def _datasets(seed: int):
    r = np.random.default_rng(seed)
    ev = pd.DataFrame({
        "t": np.sort(r.integers(0, T_MAX, N_EVENTS)),
        "k": r.integers(0, N_KEYS, N_EVENTS),
        "v": r.integers(0, 100, N_EVENTS).astype(np.float64),
    })
    tr = pd.DataFrame({
        "t": np.sort(r.integers(10, T_MAX, N_TRADES)),
        "k": r.integers(0, N_KEYS, N_TRADES),
        "tid": np.arange(N_TRADES, dtype=np.int64),
        "size": r.integers(1, 50, N_TRADES).astype(np.float64),
    })
    qt = np.concatenate([np.zeros(N_KEYS, np.int64),
                         np.sort(r.integers(0, T_MAX, N_QUOTES))])
    qk = np.concatenate([np.arange(N_KEYS),
                         r.integers(0, N_KEYS, N_QUOTES)])
    px = np.concatenate([np.full(N_KEYS, 100.0),
                         r.integers(100, 200, N_QUOTES).astype(np.float64)])
    order = np.argsort(qt, kind="stable")
    qu = pd.DataFrame({"t": qt[order], "k": qk[order], "px": px[order]})
    return ev, tr, qu


def _csv_rows(df: pd.DataFrame):
    return [",".join(str(x) for x in row) + "\n"
            for row in df.itertuples(index=False)]


_EV_SCHEMA = pa.schema([("t", pa.int64()), ("k", pa.int64()),
                        ("v", pa.float64())])
_TR_SCHEMA = pa.schema([("t", pa.int64()), ("k", pa.int64()),
                        ("tid", pa.int64()), ("size", pa.float64())])
_QU_SCHEMA = pa.schema([("t", pa.int64()), ("k", pa.int64()),
                        ("px", pa.float64())])


def _build_queries(d: str):
    """The standing queries — ONE shared definition so the child (phase A)
    and the resuming parent (phase B) lower byte-identical plans."""
    from quokka_tpu import QuokkaContext
    from quokka_tpu.streaming import (
        TailingCsvReader,
        tail_asof_join,
        tail_window_agg,
    )

    ctx = QuokkaContext()
    agg = tail_window_agg(
        ctx, TailingCsvReader(os.path.join(d, "events.csv"), _EV_SCHEMA, "t"),
        size=WINDOW, by="k",
        aggs=[("s", "sum", "v"), ("n", "count", None)])
    ctx2 = QuokkaContext()
    asof = tail_asof_join(
        ctx2,
        TailingCsvReader(os.path.join(d, "trades.csv"), _TR_SCHEMA, "t"),
        TailingCsvReader(os.path.join(d, "quotes.csv"), _QU_SCHEMA, "t"),
        on="t", by="k")
    return agg, asof


def _service(d: str):
    from quokka_tpu.service import QueryService

    return QueryService(
        pool_size=2, spill_dir=os.path.join(d, "spill"),
        exec_config={"fault_tolerance": True,
                     "checkpoint_interval": CKPT_INTERVAL})


def _truth(ev: pd.DataFrame, tr: pd.DataFrame, qu: pd.DataFrame):
    """One-shot batch runs through the ENGINE (not pandas): the smoke's
    equivalence claim is streaming-vs-batch of this repo, not vs a model."""
    from quokka_tpu import QuokkaContext

    ctx = QuokkaContext()
    ev2 = ev.copy()
    ev2["ws"] = (ev2.t // WINDOW) * WINDOW
    agg_truth = (
        ctx.from_arrow(pa.Table.from_pandas(ev2, preserve_index=False))
        .groupby(["ws", "k"]).agg_sql("sum(v) as s, count(*) as n")
        .collect().sort_values(["ws", "k"]).reset_index(drop=True))
    lt = ctx.from_arrow_sorted(pa.Table.from_pandas(tr, preserve_index=False),
                               "t")
    rt = ctx.from_arrow_sorted(pa.Table.from_pandas(qu, preserve_index=False),
                               "t")
    asof_truth = (lt.join_asof(rt, on="t", by="k").collect()
                  .sort_values("tid").reset_index(drop=True))
    return agg_truth, asof_truth


# -- child (phase A): killed with SIGKILL mid-stream --------------------------

def run_child(d: str) -> None:
    agg, asof = _build_queries(d)
    svc = _service(d)
    h_agg = svc.submit_continuous(agg)
    h_asof = svc.submit_continuous(asof)
    with open(os.path.join(d, "child_manifests"), "w") as f:
        json.dump({"agg": h_agg.manifest_path,
                   "asof": h_asof.manifest_path}, f)
    os.replace(os.path.join(d, "child_manifests"),
               os.path.join(d, "childready"))
    fa = open(os.path.join(d, "deltas_agg.jsonl"), "w")
    fz = open(os.path.join(d, "deltas_asof.jsonl"), "w")
    while True:  # until SIGKILL
        for h, f in ((h_agg, fa), (h_asof, fz)):
            if h.error is not None:
                raise h.error
            # ONE JSON line per delta TABLE: complete lines == the durably
            # captured delta count, which phase B passes as delivered_floor
            # (a SIGKILL mid-write leaves a torn last line the parent drops)
            for tb in h.poll_deltas():
                f.write(json.dumps({"rows": tb.to_pylist()}) + "\n")
                f.flush()
                os.fsync(f.fileno())
        time.sleep(0.05)


# -- delta merging ------------------------------------------------------------

def _merge(rows, key_of, what: str):
    merged = {}
    for row in rows:
        key = key_of(row)
        val = tuple(sorted(row.items()))
        if key in merged and merged[key] != val:
            raise AssertionError(
                f"{what}: pane {key} delivered twice with DIFFERENT "
                f"content:\n  {merged[key]}\n  {val}")
        merged[key] = val
    return pd.DataFrame([dict(v) for v in merged.values()])


def _exact(got: pd.DataFrame, want: pd.DataFrame, sort_by, what: str) -> None:
    got = got.sort_values(sort_by).reset_index(drop=True)[want.columns.tolist()]
    want = want.sort_values(sort_by).reset_index(drop=True)
    for c in want.columns:
        got[c] = got[c].astype(np.float64)
        want[c] = want[c].astype(np.float64)
    pd.testing.assert_frame_equal(got, want, check_exact=True, obj=what)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--dir", default=None,
                    help="stable working dir (default: a fresh tempdir)")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.child:
        run_child(args.dir)
        return 0

    d = args.dir or tempfile.mkdtemp(prefix="stream-smoke-")
    os.makedirs(d, exist_ok=True)
    seed = args.seed
    print(f"[stream-smoke] dir={d} seed={seed}", flush=True)
    ev, tr, qu = _datasets(seed)
    t0 = time.time()
    agg_truth, asof_truth = _truth(ev, tr, qu)
    print(f"[stream-smoke] one-shot batch baselines in "
          f"{time.time() - t0:.1f}s ({len(agg_truth)} panes, "
          f"{len(asof_truth)} joined trades)", flush=True)

    # tailed files start with a prefix; writers append the rest in chunks
    streams = [("events.csv", _csv_rows(ev), 400, 280),
               ("trades.csv", _csv_rows(tr), 250, 170),
               ("quotes.csv", _csv_rows(qu), 250, 140)]
    for name, rows, prefix, _chunk in streams:
        with open(os.path.join(d, name), "w") as f:
            f.writelines(rows[:prefix])

    go = threading.Event()

    def writer(name, rows, prefix, chunk):
        go.wait()
        i = prefix
        while i < len(rows):
            j = min(i + chunk, len(rows))
            with open(os.path.join(d, name), "a") as f:
                f.writelines(rows[i:j])
            i = j
            time.sleep(0.12)

    threads = [threading.Thread(target=writer, args=s, daemon=True)
               for s in streams]
    for th in threads:
        th.start()

    # -- phase A: child service under seeded chaos, SIGKILLed mid-stream ----
    env = dict(os.environ)
    env["QK_CHAOS"] = f"seed={seed},kill=3,kill_after=8"
    env.setdefault("JAX_PLATFORMS", "cpu")
    child = subprocess.Popen(
        [sys.executable, "-m", "quokka_tpu.streaming.smoke",
         "--child", "--dir", d],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    ready = os.path.join(d, "childready")
    deadline = time.time() + 120
    while not os.path.exists(ready):
        if child.poll() is not None:
            print("[stream-smoke] FAIL: child died before submitting "
                  f"(rc={child.returncode})", flush=True)
            return 1
        if time.time() > deadline:
            child.kill()
            print("[stream-smoke] FAIL: child never became ready", flush=True)
            return 1
        time.sleep(0.2)
    manifests = json.load(open(ready))
    go.set()  # start the writers only once the standing queries are live

    def _tables(name):
        """Durably captured delta tables (torn trailing line dropped)."""
        out = []
        try:
            with open(os.path.join(d, name)) as f:
                for ln in f:
                    try:
                        out.append(json.loads(ln)["rows"])
                    except (json.JSONDecodeError, KeyError):
                        break  # SIGKILL tore this line; nothing follows
        except OSError:
            return out  # child hasn't created the file yet: zero captured
        return out

    # kill once both manifests exist and deltas are flowing — mid-stream,
    # with the writers still appending
    while True:
        if child.poll() is not None:
            print(f"[stream-smoke] FAIL: child exited early "
                  f"(rc={child.returncode})", flush=True)
            return 1
        if time.time() > deadline:
            child.kill()
            print("[stream-smoke] FAIL: no checkpointed progress before "
                  "deadline", flush=True)
            return 1
        if (os.path.exists(manifests["agg"])
                and os.path.exists(manifests["asof"])
                and len(_tables("deltas_agg.jsonl")) >= 3
                and len(_tables("deltas_asof.jsonl")) >= 3):
            break
        time.sleep(0.2)
    os.kill(child.pid, signal.SIGKILL)
    child.wait()
    a_agg, a_asof = _tables("deltas_agg.jsonl"), _tables("deltas_asof.jsonl")
    print(f"[stream-smoke] child SIGKILLed mid-stream: {len(a_agg)} agg + "
          f"{len(a_asof)} asof delta tables captured before the crash",
          flush=True)

    # -- phase B: resume from the manifests in a fresh service.  The
    # delivered_floor (tables the JSONL durably captured) pins each resume
    # point at-or-before the capture frontier: a pane checkpointed in the
    # instant between the child's last flush and the SIGKILL re-emits
    # instead of vanishing (the output-commit gap).
    agg, asof = _build_queries(d)
    svc = _service(d)
    h_agg = svc.submit_continuous(agg, resume_from=manifests["agg"],
                                  delivered_floor=len(a_agg))
    h_asof = svc.submit_continuous(asof, resume_from=manifests["asof"],
                                   delivered_floor=len(a_asof))
    for h, what in ((h_agg, "agg"), (h_asof, "asof")):
        rep = sum(r["replayed_segments"]
                  for r in h.resume_info["inputs"].values())
        skip = sum(r["skipped_segments"]
                   for r in h.resume_info["inputs"].values())
        print(f"[stream-smoke] resume[{what}]: replayed {rep} segments, "
              f"skipped {skip}, restored "
              f"{ {k: v['state_seq'] for k, v in h.resume_info['execs'].items()} }",
              flush=True)
        if skip == 0:
            print(f"[stream-smoke] FAIL: {what} resume replayed from offset "
                  "zero (full-stream recomputation)", flush=True)
            return 1
        # bounded replay: the un-checkpointed tail is at most the checkpoint
        # interval's worth of batch-sets per exec channel (+1 in-flight),
        # plus the delivered_floor's capture lag (a few poll intervals)
        bound = (CKPT_INTERVAL + 1) * max(
            1, len(h.resume_info["execs"])) * 2 + 8
        if rep > bound:
            print(f"[stream-smoke] FAIL: {what} replayed {rep} segments "
                  f"( > bound {bound}) — checkpoint frontier not honored",
                  flush=True)
            return 1
    for th in threads:
        th.join()
    final_wm = {"agg": float(ev.t.max()), "asof": float(min(tr.t.max(),
                                                            qu.t.max()))}
    b_agg, b_asof = [], []
    deadline = time.time() + 180
    while time.time() < deadline:
        b_agg.extend(t.to_pylist() for t in h_agg.poll_deltas())
        b_asof.extend(t.to_pylist() for t in h_asof.poll_deltas())
        wa, wz = h_agg.watermark(), h_asof.watermark()
        if (wa is not None and wa >= final_wm["agg"]
                and wz is not None and wz >= final_wm["asof"]):
            break
        time.sleep(0.2)
    else:
        print("[stream-smoke] FAIL: watermarks never caught up "
              f"(agg={h_agg.watermark()}, asof={h_asof.watermark()})",
              flush=True)
        return 1
    h_agg.stop(timeout=180)
    h_asof.stop(timeout=180)
    b_agg.extend(t.to_pylist() for t in h_agg.poll_deltas())
    b_asof.extend(t.to_pylist() for t in h_asof.poll_deltas())
    svc.shutdown()

    # -- merge phase A + B by pane identity and compare bit-exactly ---------
    agg_rows = [r for tb in a_agg + b_agg for r in tb]
    asof_rows = [r for tb in a_asof + b_asof for r in tb]
    try:
        got_agg = _merge(agg_rows,
                         lambda r: (r["window_start"], r["k"]), "window-agg")
        got_asof = _merge(asof_rows, lambda r: r["tid"], "asof")
        want_agg = agg_truth.rename(columns={"ws": "window_start"})
        got_agg = got_agg.drop(columns=["window_end"])
        _exact(got_agg, want_agg, ["window_start", "k"],
               "continuous window-agg vs one-shot batch")
        _exact(got_asof, asof_truth, ["tid"],
               "continuous asof vs one-shot batch")
    except AssertionError as e:
        print(f"[stream-smoke] FAIL: {e}", flush=True)
        print(f"[stream-smoke] replay: python -m quokka_tpu.streaming.smoke "
              f"--seed {seed}", flush=True)
        return 1
    from quokka_tpu import obs

    late = obs.REGISTRY.snapshot().get("stream.late_dropped", 0)
    if late:
        print(f"[stream-smoke] FAIL: {late} rows dropped as late on an "
              "in-order source", flush=True)
        return 1
    print(f"[stream-smoke] OK: {len(got_agg)} panes + {len(got_asof)} "
          "joined trades bit-exact vs one-shot batch, through seeded kills "
          "+ SIGKILL + manifest resume, 0 late drops", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
