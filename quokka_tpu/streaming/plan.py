"""Plan helpers for standing queries.

Streaming plans are ordinary logical plans — a SourceNode over a tailing
reader feeding StatefulNodes that hold streaming executors — so they lower
through the normal context machinery and coexist with batch queries in the
service.  The helpers here pin the v1 streaming shape: ONE source channel
per unbounded reader (a tail is one monotone sequence) and ONE channel per
streaming operator (what makes the resume manifest's frontier arithmetic
exact; parallelism lives inside the batch kernels, as everywhere else in
this engine).
"""

from __future__ import annotations

import copy
import functools
from typing import Optional, Sequence, Tuple

from quokka_tpu import logical
from quokka_tpu.streaming.executors import (
    StreamingAsofJoinExecutor,
    StreamingWindowAggExecutor,
)
from quokka_tpu.target_info import HashPartitioner, PassThroughPartitioner


def _single_channel_source(ctx, reader):
    ds = ctx.read_dataset(reader)
    ds._node.channels = 1
    return ds


def tail_window_agg(ctx, reader, *, size,
                    aggs: Sequence[Tuple[str, str, Optional[str]]],
                    by=None, time_col: Optional[str] = None):
    """Continuous tumbling-window aggregation over a tailed source.

    ``aggs``: ``[(out_name, fn, col), ...]`` with fn in sum/count/min/max.
    Output stream schema: ``[window_start, window_end, *by, *out_names]``;
    panes emit incrementally as the source watermark passes each window end.
    """
    time_col = time_col or getattr(reader, "time_col", None)
    if time_col is None:
        raise ValueError("time_col is required (reader carries none)")
    by = [by] if isinstance(by, str) else list(by or [])
    src = _single_channel_source(ctx, reader)
    ex = StreamingWindowAggExecutor(time_col, by, size, aggs,
                                    n_source_channels=1)
    out_schema = (["window_start", "window_end"] + by
                  + [n for n, _f, _c in aggs])
    ds = src.stateful_transform(ex, out_schema, by=by or None)
    ds._node.channels = 1
    return ds


def tail_asof_join(ctx, left_reader, right_reader, *, on: str, by=None,
                   suffix: str = "_2"):
    """Continuous backward asof join of two tailed sources (probe stream 0,
    reference stream 1), emitting joined probe rows as the combined
    watermark finalizes them.  Mirrors ``OrderedStream.join_asof`` schema
    conventions (right payload, clash-suffixed)."""
    by = [by] if isinstance(by, str) else list(by or [])
    left = _single_channel_source(ctx, left_reader)
    right = _single_channel_source(ctx, right_reader)
    left_cols, right_cols = list(left.schema), list(right.schema)
    ex = StreamingAsofJoinExecutor(on, by, by, left_cols, right_cols,
                                   suffix=suffix,
                                   n_left_channels=1, n_right_channels=1)
    part = (HashPartitioner(by) if by else PassThroughPartitioner())
    node = logical.StatefulNode(
        [left.node_id, right.node_id],
        list(ex.out_cols),
        functools.partial(copy.deepcopy, ex),
        partitioners={0: part,
                      1: HashPartitioner(by) if by else
                      PassThroughPartitioner()},
    )
    node.channels = 1
    return left._child(node)
