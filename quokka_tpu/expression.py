"""Backend-independent expression AST.

Role parallel to the reference's Expression wrapper over sqlglot columns
(pyquokka/expression.py:5) — but since this framework owns its whole compile
path (sqlglot is not a dependency), the AST here is first-class: the DataStream
API builds it via operator overloading, the SQL parser (quokka_tpu.sqlparse)
builds it from text, the optimizer rewrites it, and ops/expr_compile lowers it
to jitted JAX kernels.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional, Sequence, Tuple

EPOCH = datetime.date(1970, 1, 1)


def date_to_days(d) -> int:
    if isinstance(d, str):
        d = datetime.date.fromisoformat(d)
    return (d - EPOCH).days


class Expr:
    """Base expression node."""

    # -- operator overloading ------------------------------------------------
    def _bin(self, op, other, reverse=False):
        other = lit_wrap(other)
        return BinOp(op, other, self) if reverse else BinOp(op, self, other)

    def __add__(self, o):
        return self._bin("+", o)

    def __radd__(self, o):
        return self._bin("+", o, True)

    def __sub__(self, o):
        return self._bin("-", o)

    def __rsub__(self, o):
        return self._bin("-", o, True)

    def __mul__(self, o):
        return self._bin("*", o)

    def __rmul__(self, o):
        return self._bin("*", o, True)

    def __truediv__(self, o):
        return self._bin("/", o)

    def __rtruediv__(self, o):
        return self._bin("/", o, True)

    def __floordiv__(self, o):
        return self._bin("//", o)

    def __mod__(self, o):
        return self._bin("%", o)

    def __eq__(self, o):  # type: ignore[override]
        return self._bin("=", o)

    def __ne__(self, o):  # type: ignore[override]
        return self._bin("!=", o)

    def __lt__(self, o):
        return self._bin("<", o)

    def __le__(self, o):
        return self._bin("<=", o)

    def __gt__(self, o):
        return self._bin(">", o)

    def __ge__(self, o):
        return self._bin(">=", o)

    def __and__(self, o):
        return self._bin("and", o)

    def __rand__(self, o):
        return self._bin("and", o, True)

    def __or__(self, o):
        return self._bin("or", o)

    def __ror__(self, o):
        return self._bin("or", o, True)

    def __invert__(self):
        return UnaryOp("not", self)

    def __neg__(self):
        return UnaryOp("-", self)

    def __hash__(self):
        return id(self)

    # -- methods -------------------------------------------------------------
    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def is_in(self, values: Sequence) -> "InList":
        return InList(self, list(values))

    def is_null(self) -> "IsNull":
        return IsNull(self, False)

    def is_not_null(self) -> "IsNull":
        return IsNull(self, True)

    def between(self, lo, hi) -> "Expr":
        return (self >= lit_wrap(lo)) & (self <= lit_wrap(hi))

    def cast(self, to: str) -> "Cast":
        return Cast(self, to)

    def abs(self):
        return Func("abs", [self])

    def round(self, n=0):
        return Func("round", [self, Literal(n)])

    def sqrt(self):
        return Func("sqrt", [self])

    def exp(self):
        return Func("exp", [self])

    def ln(self):
        return Func("ln", [self])

    def floor(self):
        return Func("floor", [self])

    def ceil(self):
        return Func("ceil", [self])

    @property
    def str(self):
        return StrNamespace(self)

    @property
    def dt(self):
        return DtNamespace(self)

    # -- aggregation builders (usable in agg contexts) -----------------------
    def sum(self):
        return Agg("sum", self)

    def mean(self):
        return Agg("avg", self)

    def avg(self):
        return Agg("avg", self)

    def min(self):
        return Agg("min", self)

    def max(self):
        return Agg("max", self)

    def count(self):
        return Agg("count", self)

    # -- analysis ------------------------------------------------------------
    def required_columns(self) -> set:
        out = set()
        _walk_required(self, out)
        return out

    def children(self) -> List["Expr"]:
        return []

    def sql(self) -> str:
        raise NotImplementedError

    def __repr__(self):
        try:
            return f"Expr({self.sql()})"
        except Exception:
            return object.__repr__(self)

    def __bool__(self):
        raise TypeError(
            "Expression truth value is ambiguous; use & / | instead of and / or"
        )


def _walk_required(e: Expr, out: set):
    if isinstance(e, ColRef):
        out.add(e.name)
    for c in e.children():
        _walk_required(c, out)


def lit_wrap(v) -> Expr:
    if isinstance(v, Expr):
        return v
    if isinstance(v, (datetime.date, datetime.datetime)):
        return DateLit(v)
    return Literal(v)


class ColRef(Expr):
    def __init__(self, name: str):
        self.name = name

    def sql(self):
        return self.name


class Literal(Expr):
    def __init__(self, value: Any):
        self.value = value

    def sql(self):
        if isinstance(self.value, str):
            return "'" + self.value.replace("'", "''") + "'"
        if self.value is None:
            return "NULL"
        return repr(self.value)


class DateLit(Expr):
    """A date (or timestamp) literal, held as days since epoch (date) or a
    datetime (timestamp)."""

    def __init__(self, value):
        if isinstance(value, str):
            if len(value) > 10:
                value = datetime.datetime.fromisoformat(value)
            else:
                value = datetime.date.fromisoformat(value)
        self.value = value

    @property
    def days(self) -> int:
        v = self.value
        if isinstance(v, datetime.datetime):
            v = v.date()
        return date_to_days(v)

    def sql(self):
        return f"date '{self.value.isoformat()}'"


class IntervalLit(Expr):
    """interval 'n' unit — value normalized to (months, microseconds)."""

    UNIT_US = {
        "second": 1_000_000,
        "minute": 60_000_000,
        "hour": 3_600_000_000,
        "day": 86_400_000_000,
        "week": 7 * 86_400_000_000,
    }

    def __init__(self, n: float, unit: str):
        unit = unit.rstrip("s").lower()
        self.n = n
        self.unit = unit
        if unit in ("month", "year"):
            self.months = int(n) * (12 if unit == "year" else 1)
            self.micros = 0
        else:
            self.months = 0
            self.micros = int(n * self.UNIT_US[unit])

    @property
    def days(self) -> int:
        return self.micros // 86_400_000_000

    def sql(self):
        return f"interval '{self.n}' {self.unit}"


class BinOp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op
        self.left = left
        self.right = right

    def children(self):
        return [self.left, self.right]

    def sql(self):
        return f"({self.left.sql()} {self.op} {self.right.sql()})"


class UnaryOp(Expr):
    def __init__(self, op: str, operand: Expr):
        self.op = op
        self.operand = operand

    def children(self):
        return [self.operand]

    def sql(self):
        return f"({self.op} {self.operand.sql()})"


class Func(Expr):
    def __init__(self, name: str, args: List[Expr]):
        self.name = name.lower()
        self.args = args

    def children(self):
        return list(self.args)

    def sql(self):
        return f"{self.name}({', '.join(a.sql() for a in self.args)})"


class Cast(Expr):
    def __init__(self, expr: Expr, to: str):
        self.expr = expr
        self.to = to.lower()

    def children(self):
        return [self.expr]

    def sql(self):
        return f"cast({self.expr.sql()} as {self.to})"


class Alias(Expr):
    def __init__(self, expr: Expr, name: str):
        self.expr = expr
        self.name = name

    def children(self):
        return [self.expr]

    def sql(self):
        return f"{self.expr.sql()} as {self.name}"


class InList(Expr):
    def __init__(self, expr: Expr, values: List, negated: bool = False):
        self.expr = expr
        self.values = values
        self.negated = negated

    def children(self):
        return [self.expr]

    def sql(self):
        neg = "not " if self.negated else ""
        vals = ", ".join(Literal(v).sql() if not isinstance(v, Expr) else v.sql() for v in self.values)
        return f"({self.expr.sql()} {neg}in ({vals}))"


class IsNull(Expr):
    def __init__(self, expr: Expr, negated: bool):
        self.expr = expr
        self.negated = negated

    def children(self):
        return [self.expr]

    def sql(self):
        return f"({self.expr.sql()} is {'not ' if self.negated else ''}null)"


class Case(Expr):
    def __init__(self, whens: List[Tuple[Expr, Expr]], default: Optional[Expr]):
        self.whens = whens
        self.default = default

    def children(self):
        out = []
        for c, v in self.whens:
            out.extend([c, v])
        if self.default is not None:
            out.append(self.default)
        return out

    def sql(self):
        parts = ["case"]
        for c, v in self.whens:
            parts.append(f"when {c.sql()} then {v.sql()}")
        if self.default is not None:
            parts.append(f"else {self.default.sql()}")
        parts.append("end")
        return " ".join(parts)


class Agg(Expr):
    """An aggregate call.  op in sum/avg/min/max/count/count_distinct;
    arg None means count(*)."""

    def __init__(self, op: str, arg: Optional[Expr], distinct: bool = False):
        self.op = op.lower()
        self.arg = arg
        self.distinct = distinct

    def children(self):
        return [] if self.arg is None else [self.arg]

    def sql(self):
        inner = "*" if self.arg is None else self.arg.sql()
        d = "distinct " if self.distinct else ""
        return f"{self.op}({d}{inner})"


class StrOp(Expr):
    """String predicate/transform evaluated on the dictionary host-side."""

    def __init__(self, op: str, expr: Expr, args: List):
        self.op = op
        self.expr = expr
        self.args = args

    def children(self):
        return [self.expr]

    def sql(self):
        if self.op == "like":
            return f"({self.expr.sql()} like {Literal(self.args[0]).sql()})"
        return f"{self.op}({self.expr.sql()}, {', '.join(map(repr, self.args))})"


class StrNamespace:
    def __init__(self, expr: Expr):
        self._e = expr

    def contains(self, pat: str):
        return StrOp("contains", self._e, [pat])

    def starts_with(self, pat: str):
        return StrOp("starts_with", self._e, [pat])

    def ends_with(self, pat: str):
        return StrOp("ends_with", self._e, [pat])

    def like(self, pat: str):
        return StrOp("like", self._e, [pat])

    def lower(self):
        return StrOp("lower", self._e, [])

    def upper(self):
        return StrOp("upper", self._e, [])

    def strip(self):
        return StrOp("strip", self._e, [])

    def length(self):
        return StrOp("length", self._e, [])

    def slice(self, offset: int, length: Optional[int] = None):
        return StrOp("slice", self._e, [offset, length])

    def json_extract(self, path: str):
        return StrOp("json_extract", self._e, [path])

    def hash(self):
        return StrOp("hash", self._e, [])


class DtField(Expr):
    def __init__(self, field: str, expr: Expr):
        self.field = field
        self.expr = expr

    def children(self):
        return [self.expr]

    def sql(self):
        return f"extract({self.field} from {self.expr.sql()})"


class DtNamespace:
    def __init__(self, expr: Expr):
        self._e = expr

    @property
    def year(self):
        return DtField("year", self._e)

    @property
    def month(self):
        return DtField("month", self._e)

    @property
    def day(self):
        return DtField("day", self._e)

    @property
    def hour(self):
        return DtField("hour", self._e)

    @property
    def minute(self):
        return DtField("minute", self._e)

    @property
    def second(self):
        return DtField("second", self._e)

    @property
    def weekday(self):
        return DtField("weekday", self._e)

    def offset_by(self, interval: "IntervalLit"):
        return BinOp("+", self._e, interval)

    def truncate(self, every: str):
        return Func("date_trunc", [Literal(every), self._e])


# ---------------------------------------------------------------------------
# public constructors
# ---------------------------------------------------------------------------


def col(name: str) -> ColRef:
    return ColRef(name)


def lit(v) -> Expr:
    return lit_wrap(v)


def date(s) -> DateLit:
    return DateLit(s)


def interval(n, unit: str) -> IntervalLit:
    return IntervalLit(n, unit)


def when(cond: Expr):
    """when(cond).then(v).otherwise(d) builder."""

    class _When:
        def __init__(self, whens):
            self._whens = whens

        def then(self, v):
            w = self._whens + [(cond, lit_wrap(v))]

            class _Then:
                def when(self, c2):
                    return when_chain(w, c2)

                def otherwise(self, d):
                    return Case(w, lit_wrap(d))

                def end(self):
                    return Case(w, None)

            return _Then()

    return _When([])


def when_chain(whens, cond):
    class _When:
        def then(self, v):
            w = whens + [(cond, lit_wrap(v))]

            class _Then:
                def when(self, c2):
                    return when_chain(w, c2)

                def otherwise(self, d):
                    return Case(w, lit_wrap(d))

                def end(self):
                    return Case(w, None)

            return _Then()

    return _When()


# ---------------------------------------------------------------------------
# rewriting / analysis helpers used by the optimizer
# ---------------------------------------------------------------------------


def split_conjuncts(e: Expr) -> List[Expr]:
    """Flatten a predicate into CNF-ish top-level AND conjuncts (the unit of
    predicate pushdown, as in the reference's per-parent conjunct routing,
    pyquokka/df.py:1029-1139)."""
    if isinstance(e, BinOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def conjoin(exprs: Sequence[Expr]) -> Optional[Expr]:
    exprs = list(exprs)
    if not exprs:
        return None
    out = exprs[0]
    for e in exprs[1:]:
        out = BinOp("and", out, e)
    return out


def rename_columns(e: Expr, mapping: Dict[str, str]) -> Expr:
    """Return a copy of e with column refs renamed (schema_mapping walks)."""
    if isinstance(e, ColRef):
        return ColRef(mapping.get(e.name, e.name))
    return _rebuild(e, [rename_columns(c, mapping) for c in e.children()])


def substitute_columns(e: Expr, mapping: Dict[str, Expr]) -> Expr:
    """Replace column refs by expressions (used by map folding)."""
    if isinstance(e, ColRef):
        return mapping.get(e.name, e)
    return _rebuild(e, [substitute_columns(c, mapping) for c in e.children()])


def _rebuild(e: Expr, kids: List[Expr]) -> Expr:
    if isinstance(e, BinOp):
        return BinOp(e.op, kids[0], kids[1])
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, kids[0])
    if isinstance(e, Func):
        return Func(e.name, kids)
    if isinstance(e, Cast):
        return Cast(kids[0], e.to)
    if isinstance(e, Alias):
        return Alias(kids[0], e.name)
    if isinstance(e, InList):
        return InList(kids[0], e.values, e.negated)
    if isinstance(e, IsNull):
        return IsNull(kids[0], e.negated)
    if isinstance(e, StrOp):
        return StrOp(e.op, kids[0], e.args)
    if isinstance(e, DtField):
        return DtField(e.field, kids[0])
    if isinstance(e, Agg):
        return Agg(e.op, kids[0] if kids else None, e.distinct)
    if isinstance(e, Case):
        n = len(e.whens)
        whens = [(kids[2 * i], kids[2 * i + 1]) for i in range(n)]
        default = kids[2 * n] if len(kids) > 2 * n else None
        return Case(whens, default)
    if not kids:
        return e
    raise NotImplementedError(type(e))
