"""Whole-stage-fusion smoke: a linear join chain must run as ONE device
program chain, warm and sync-free, and match the unfused plan bit-exactly.

    python -m quokka_tpu.runtime.fusion_smoke      (or: make fusion-smoke)

A seeded Q3-shaped pipeline (fact filter, two broadcast dim joins, grouped
aggregate — exactly the linear chain ops/stagefuse.py collapses) runs warm
and then steady-state; the steady run must show

1. at least one FUSED stage actually dispatching batches (the
   ``stagefuse.exec`` counter FusedStageExecutor increments per intake),
2. ZERO real backend compiles (the sanitizer's recompile sentinel,
   ``analysis/sanitize.check_no_recompiles`` with force=True), and
3. ZERO blocking host readbacks on the push path (``shuffle.host_syncs``
   stays flat).

The same query is then re-planned IN-PROCESS with ``QK_STAGE_FUSE=0`` (the
optimizer reads the switch per plan) and the unfused result must be
BIT-EXACT vs the fused one — integer-valued columns, so any drift is a
wrong answer, not a rounding story.  Exit nonzero on any violation.
"""

from __future__ import annotations

import os
import sys
import tempfile


def _make_tables(tmp: str, seed: int = 20260805):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    n_fact, n_dim1, n_dim2 = 300_000, 8_000, 500
    fact = pa.table({
        "fk": r.integers(0, n_dim1, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim1 = pa.table({
        "pk": np.arange(n_dim1, dtype=np.int64),
        "ck": r.integers(0, n_dim2, n_dim1).astype(np.int64),
    })
    dim2 = pa.table({
        "pk2": np.arange(n_dim2, dtype=np.int64),
        "grp": r.integers(0, 32, n_dim2).astype(np.int64),
    })
    paths = []
    for name, tbl in (("fact", fact), ("dim1", dim1), ("dim2", dim2)):
        p = os.path.join(tmp, f"{name}.parquet")
        pq.write_table(tbl, p, row_group_size=1 << 17)
        paths.append(p)
    return paths


def _query(ctx, fp, d1, d2):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fp)
    dim1 = ctx.read_parquet(d1)
    dim2 = ctx.read_parquet(d2)
    return (
        fact.filter(col("flag") < 3)
        .join(dim1, left_on="fk", right_on="pk")
        .join(dim2, left_on="ck", right_on="pk2")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def _canon(df):
    """Order-independent canonical form: the fused and unfused plans are
    free to emit groups in different orders; the CONTENT must be identical."""
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def main() -> int:
    from quokka_tpu import QuokkaContext, obs
    from quokka_tpu.analysis import sanitize
    from quokka_tpu.utils import compilestats

    with tempfile.TemporaryDirectory(prefix="qk-fusion-smoke-") as tmp:
        fp, d1, d2 = _make_tables(tmp)
        ctx = QuokkaContext(io_channels=2, exec_channels=2)
        warm = _query(ctx, fp, d1, d2).collect()  # pays the compiles
        assert len(warm) > 0, "smoke query returned no rows"

        c0 = compilestats.snapshot()
        snap0 = obs.REGISTRY.snapshot()
        steady = _query(ctx, fp, d1, d2).collect()
        c1 = compilestats.snapshot()
        snap1 = obs.REGISTRY.snapshot()

        assert warm.equals(steady), "steady-state run changed the result"
        fused = snap1.get("stagefuse.exec", 0) - snap0.get("stagefuse.exec", 0)
        syncs = snap1.get("shuffle.host_syncs", 0) - snap0.get(
            "shuffle.host_syncs", 0)
        print(f"fusion-smoke: steady-state stagefuse.exec={fused} "
              f"host_syncs={syncs} real_compiles="
              f"{c1['real_compiles'] - c0['real_compiles']}")
        if fused <= 0:
            print("fusion-smoke: FAIL — no fused stage dispatched on a "
                  "linear join chain (optimizer.fuse_stages planned "
                  "nothing, or FusedStageExecutor never ran)",
                  file=sys.stderr)
            return 1
        if syncs > 0:
            print(f"fusion-smoke: FAIL — {syncs} blocking host readback(s) "
                  "during the steady fused run (shuffle.host_syncs)",
                  file=sys.stderr)
            return 1
        # recompile sentinel: the warmed fused pipeline must reuse its
        # executables (raises RecompileError on violation)
        sanitize.check_no_recompiles(c0, c1, context="fusion-smoke steady run",
                                     force=True)

        # the escape hatch must exist AND agree: re-plan the same query
        # unfused in this very process and compare content bit-exactly
        os.environ["QK_STAGE_FUSE"] = "0"
        try:
            u0 = obs.REGISTRY.snapshot()
            unfused = _query(ctx, fp, d1, d2).collect()
            u1 = obs.REGISTRY.snapshot()
        finally:
            os.environ.pop("QK_STAGE_FUSE", None)
        leaked = u1.get("stagefuse.exec", 0) - u0.get("stagefuse.exec", 0)
        if leaked > 0:
            print("fusion-smoke: FAIL — QK_STAGE_FUSE=0 still dispatched "
                  f"{leaked} fused intake(s); the kill switch is dead",
                  file=sys.stderr)
            return 1
        if not _canon(steady).equals(_canon(unfused)):
            print("fusion-smoke: FAIL — fused and unfused plans disagree "
                  "on integer-valued data (bit-exactness violated)",
                  file=sys.stderr)
            return 1
    print("fusion-smoke: OK — fused chain ran warm with zero recompiles, "
          "zero host syncs, bit-exact vs QK_STAGE_FUSE=0")
    return 0


if __name__ == "__main__":
    sys.exit(main())
