"""Worker process: runs a subset of the TaskGraph's channels.

The reference spreads channels across Ray TaskManager actors
(pyquokka/core.py:54-151); here each worker process owns a set of
(actor, channel) pairs, reuses the embedded Engine's task handlers verbatim
against a ControlStoreClient, keeps a LOCAL BatchCache served over the socket
data plane, and routes pushes by the channel-location table (CLT).

Recovery: on a peer's death the coordinator mails surviving workers
("adopt", actor, channel) messages; the adopter replays checkpoint + tape +
HBQ with the same Engine recovery code the embedded runtime uses.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple


from quokka_tpu import obs
from quokka_tpu.runtime.cache import BatchCache
from quokka_tpu.runtime.dataplane import DataPlaneClient, serve_cache, table_to_ipc
from quokka_tpu.runtime.engine import ActorInfo, Engine
from quokka_tpu.runtime.state import WorkerState
from quokka_tpu.runtime.store_service import ControlStoreClient



class WorkerGraph:
    """Duck-typed TaskGraph for Engine: store client + local cache + actors."""

    def __init__(self, store, cache, actors, exec_config, hbq, ckpt_dir,
                 query_id=None):
        self.store = store
        self.cache = cache
        self.actors = actors
        self.exec_config = exec_config
        self.hbq = hbq
        self.ckpt_dir = ckpt_dir
        # distributed sessions run one query per served store today, so this
        # stays None there; the engine's query tagging/namespacing keys off it
        self.query_id = query_id


def _actors_from_spec(spec: Dict) -> Dict[int, ActorInfo]:
    actors = {}
    for aid, d in spec["actors"].items():
        info = ActorInfo(aid, d["kind"], d["channels"], d["stage"], d["sorted_actor"])
        info.reader = d["reader"]
        info.executor_factory = d["factory"]
        info.targets = d["targets"]
        info.source_streams = d["source_streams"]
        info.sorted_by = d["sorted_by"]
        info.predicate = d["predicate"]
        info.projection = d["projection"]
        info.blocking = d["blocking"]
        info.channel_major = d.get("channel_major", False)
        info.placement = d.get("placement")
        info.blocking_dataset = None
        actors[aid] = info
    return actors


class Worker(Engine):
    # never rewind a LIVE peer-owned channel from this process: the owner's
    # in-flight dispatch would race the rewind (engine._maybe_force_
    # producer_rewind) — distributed loss escalation stays with the
    # coordinator's co-dead planning + the loud wait-deadline
    _allow_forced_rewind = False

    def __init__(self, spec: Dict, store, cache: BatchCache, worker_id: int,
                 owned: Dict[int, List[int]], hbq=None):
        actors = _actors_from_spec(spec)
        if hbq is None and spec["hbq_path"]:
            hbq = _worker_hbq(spec, worker_id)
        g = WorkerGraph(store, cache, actors, spec["exec_config"], hbq,
                        spec["ckpt_dir"], query_id=spec.get("query_id"))
        self.worker_id = worker_id
        self._init_latency_hists(g)
        self.owned = {a: set(chs) for a, chs in owned.items()}
        self._peers: Dict[int, DataPlaneClient] = {}
        self._peer_addrs: Dict[int, Tuple[str, int]] = {}
        self._clt: Dict[Tuple[int, int], int] = {}
        # Engine.__init__ builds every exec channel; do it owned-only
        self.g = g
        self.store = store
        self.cache = cache
        self.max_batches = g.exec_config.get("max_pipeline_batches", 8)
        self.execs = {}
        self._partition_fns = {}
        for info in actors.values():
            if info.kind == "exec":
                for ch in self.owned.get(info.id, ()):
                    self.execs[(info.id, ch)] = info.executor_factory()
        # AST/SAT are write-once at graph build: snapshot from the spec so the
        # scheduling hot loop never round-trips them through the store
        self._stages_cache = {a.id: a.stage for a in actors.values()}
        self._sorted_cache = {a.id for a in actors.values() if a.sorted_actor}
        self._cm_cache = {
            a.id for a in actors.values() if getattr(a, "channel_major", False)
        }

    def _actor_stages(self):
        return self._stages_cache

    def _sorted_actors(self):
        return self._sorted_cache

    def _channel_major_actors(self):
        return self._cm_cache

    # -- routing --------------------------------------------------------------
    def _refresh_clt(self):
        self._clt = dict(self.store.titems("CLT"))

    def _peer(self, worker_id: int) -> DataPlaneClient:
        cli = self._peers.get(worker_id)
        if cli is None:
            addr = self._peer_addrs.get(worker_id)
            if addr is None:
                self._peer_addrs = dict(self.store.get("worker_addrs") or {})
                addr = self._peer_addrs[worker_id]
            cli = self._peers[worker_id] = DataPlaneClient(addr)
        return cli

    def _cache_put(self, name, part):
        tgt = (name[3], name[5])
        deadline = time.time() + 30
        compacted = False
        while True:
            owner = self._clt.get(tgt)
            if owner is None:
                self._refresh_clt()
                owner = self._clt[tgt]
            if owner == self.worker_id:
                self.cache.put(name, part)
                return
            try:
                if not compacted and part.padded_len > (1 << 16):
                    # remote put serializes the batch whole: a masked-view
                    # partition would ship the full PARENT padded buffers
                    # (fan-out times the bytes) — compact before the wire,
                    # same discipline as the spill worker (_spill_one)
                    from quokka_tpu.ops import kernels

                    part = kernels.compact(part)
                    compacted = True
                self._peer(owner).put(name, part, part.sorted_by)
                return
            except (ConnectionError, OSError):
                # peer died mid-push: drop the stale client and wait for the
                # coordinator to repoint the channel in CLT
                self._peers.pop(owner, None)
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
                self._refresh_clt()

    def _result_append(self, info, channel, seq, table):
        self.store.result_append(info.id, channel, seq, table_to_ipc(table))

    # -- HBQ across workers ---------------------------------------------------
    # Spill is producer-local (each worker's PRIVATE dir — no shared
    # filesystem assumed); recovery aggregates this worker's HBQ with every
    # reachable peer's, served over the data plane.  An unreachable peer is
    # negative-cached for a while (a dead REMOTE host otherwise costs a full
    # connect timeout per probe), and per-target holder maps are TTL-cached
    # so resolving N lost objects costs ~P listing calls, not N*P probes.
    _PEER_DOWN_TTL = 15.0
    _HOLDER_TTL = 1.0

    def _iter_peer_clients(self, refresh_addrs: bool = True):
        if refresh_addrs:
            now = time.time()
            if now - getattr(self, "_addrs_at", 0) > 2.0:
                self._peer_addrs = dict(self.store.get("worker_addrs") or {})
                self._addrs_at = now
        down = getattr(self, "_peers_down", None)
        if down is None:
            down = self._peers_down = {}
        for w in sorted(self._peer_addrs):
            if w == self.worker_id:
                continue
            if time.time() < down.get(w, 0):
                continue
            try:
                yield w, self._peer(w)
            except (ConnectionError, OSError):
                self._peers.pop(w, None)
                down[w] = time.time() + self._PEER_DOWN_TTL

    def _hbq_holders(self, tgt: Tuple[int, int]):
        """name -> peer worker id, one listing RPC per live peer, TTL-cached
        (listings grow while co-dead producers replay, so the cache is
        deliberately short-lived)."""
        cache = getattr(self, "_holder_cache", None)
        if cache is None:
            cache = self._holder_cache = {}
        hit = cache.get(tgt)
        if hit is not None and time.time() - hit[0] < self._HOLDER_TTL:
            return hit[1]
        holders = {}
        for w, cli in self._iter_peer_clients():
            try:
                for name in cli.hbq_names_for_target(*tgt):
                    holders[name] = w
            except (ConnectionError, OSError):
                self._peers.pop(w, None)
                self._peers_down[w] = time.time() + self._PEER_DOWN_TTL
        cache[tgt] = (time.time(), holders)
        return holders

    def _hbq_names_for_target(self, tgt_actor: int, tgt_ch: int):
        names = set(self.g.hbq.names_for_target(tgt_actor, tgt_ch))
        names.update(self._hbq_holders((tgt_actor, tgt_ch)))
        return sorted(names)

    def _hbq_contains(self, name):
        if self.g.hbq is not None and self.g.hbq.contains(name):
            return True
        return tuple(name) in self._hbq_holders((name[3], name[5]))

    def _hbq_fetch(self, name):
        table = self.g.hbq.get(name)
        if table is not None:
            return table
        w = self._hbq_holders((name[3], name[5])).get(tuple(name))
        if w is None:
            return None
        try:
            return self._peer(w).hbq_get(name)
        except (ConnectionError, OSError):
            self._peers.pop(w, None)
            self._peers_down[w] = time.time() + self._PEER_DOWN_TTL
            return None

    # -- recovery adoption ----------------------------------------------------
    def _adopt(self, actor: int, channel: int, choice=None):
        """Take over a failed peer's channel: the shared Engine recovery path
        (checkpoint + tape + HBQ replay) against this worker's local cache.
        `choice` is the coordinator's rewind-planner checkpoint selection."""
        obs.RECORDER.record("adopt", f"a{actor}c{channel}",
                            choice=repr(choice))
        # flush barrier: adoption replays from HBQ listings (ours included);
        # our own pending async spills must be durable first
        self._flush_spills()
        self.owned.setdefault(actor, set()).add(channel)
        self._recover_channel(actor, channel, choice=choice)

    # -- observability --------------------------------------------------------
    _FLIGHT_SHIP_EVERY = 0.5  # seconds between incremental event shipments

    def _worker_state(self, phase: str, now: float) -> WorkerState:
        return WorkerState(
            worker_id=self.worker_id,
            phase=phase,
            task=getattr(self, "_obs_task", None),
            last_progress=getattr(self, "_obs_last_progress", 0.0),
            queue_hint=self.cache.size(),
            events_seq=getattr(self, "_obs_shipped_seq", -1),
            dropped=obs.RECORDER.dropped,
            ts=now,
        )

    def _ship_flight(self) -> None:
        """Ship the flight-recorder events recorded since the last shipment
        (incremental: the full ring would be hundreds of KB at 2 Hz)."""
        since = getattr(self, "_obs_shipped_seq", -1)
        evs = obs.RECORDER.snapshot(since=since)
        if evs:
            self.store.flight_append(self.worker_id, evs)
            self._obs_shipped_seq = evs[-1][0]

    # -- main loop ------------------------------------------------------------
    def run_worker(self, heartbeat_every: float = 0.2):
        # QK_SANITIZE=1: the loop beats a watchdog; a dispatch that wedges
        # (lock/pipe deadlock) stops the beats, and the watchdog dumps every
        # thread's stack and kills this process — the coordinator then fails
        # the run in seconds instead of hanging to its timeout
        watchdog = getattr(self, "_watchdog", None)
        rec = obs.RECORDER
        rec.record("worker.start", f"worker-{self.worker_id}")
        # startup barrier: wait until every worker's data-plane address is
        # registered, or the first push to a late-starting peer would fail
        expected = self.store.get("expected_workers")
        t0 = time.time()
        while expected:
            addrs = self.store.get("worker_addrs") or {}
            if len(addrs) >= expected:
                self._peer_addrs = {int(k): tuple(v) for k, v in addrs.items()}
                rec.record("worker.barrier", f"{len(addrs)} peers registered")
                break
            if self.store.get("SHUTDOWN"):
                return
            if time.time() - t0 > 120:
                raise TimeoutError("peer workers never registered")
            self.store.heartbeat(self.worker_id,
                                 self._worker_state("barrier", time.time()))
            if watchdog is not None:
                watchdog.beat()
            time.sleep(0.05)
        last_hb = 0.0
        last_ship = 0.0
        dbg = os.environ.get("QUOKKA_DEBUG_WORKER")
        dbg_at = time.time()
        self._obs_last_progress = time.time()
        actors = sorted(self.g.actors.values(), key=lambda a: (a.stage, a.id))
        phase = "run"
        while True:
            now = time.time()
            if watchdog is not None:
                watchdog.beat()
            if now - last_hb >= heartbeat_every:
                self.store.heartbeat(self.worker_id,
                                     self._worker_state(phase, now))
                rec.record("hb", f"worker-{self.worker_id}")
                last_hb = now
            if now - last_ship >= self._FLIGHT_SHIP_EVERY:
                self._ship_flight()
                last_ship = now
            for msg in self.store.mailbox_drain(self.worker_id):
                if msg[0] == "adopt":
                    self._refresh_clt()
                    self._adopt(msg[1], msg[2],
                                choice=msg[3] if len(msg) > 3 else None)
            if self.store.get("SHUTDOWN"):
                rec.record("worker.shutdown", f"worker-{self.worker_id}")
                self._ship_flight()
                return
            stage = self.store.get("STAGE", 0)
            progress = False
            popped = []
            for info in actors:
                chans = self.owned.get(info.id)
                if not chans:
                    continue
                if info.kind == "input" and info.stage > stage:
                    continue
                task = self.store.ntt_pop(info.id, list(chans),
                                          self.worker_id)
                if task is None:
                    continue
                if dbg:
                    popped.append((info.id, task.name,
                                   getattr(task, "channel", None)))
                # remembered in the heartbeat payload so the coordinator
                # can name the in-flight task even mid-dispatch
                self._obs_task = (task.name, info.id,
                                  getattr(task, "channel", None))
                progress |= self.dispatch_task(task)
            if progress:
                dbg_at = now
                self._obs_last_progress = now
                phase = "run"
            else:
                phase = "idle"
                if dbg and now - dbg_at > 5.0:
                    dbg_at = now
                    obs.diag(
                        f"[worker {self.worker_id}] stalled: owned="
                        f"{ {a: sorted(c) for a, c in self.owned.items()} } "
                        f"popped={popped} "
                        f"cache={self.cache.size()} puttable={self.cache.puttable()}"
                    )
                time.sleep(0.01)


def _worker_hbq(spec: Dict, worker_id: int):
    """Each worker spills into its own PRIVATE subdir of the run's spill
    root — nothing assumes peers can read it from disk (multi-host safe);
    recovery fetches across workers over the data plane instead."""
    from quokka_tpu.runtime.hbq import HBQ

    return HBQ(os.path.join(spec["hbq_path"], f"worker-{worker_id}"))


def worker_main(spec_bytes: bytes, store_addr, worker_id: int, owned):
    """Spawn entry point (module-level for multiprocessing spawn)."""
    # honor a CPU platform request before any backend init (the axon
    # sitecustomize would otherwise force the TPU platform)
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import pickle

    # chaos plane: spawned children inherit QK_CHAOS through the environment;
    # the role keys this worker's seeded fault streams apart from (and as
    # reproducibly as) the coordinator's
    from quokka_tpu.chaos import CHAOS

    if CHAOS.enabled:
        CHAOS.set_role(f"worker-{worker_id}")
    spec = pickle.loads(spec_bytes)
    if spec.get("x64"):
        import jax

        jax.config.update("jax_enable_x64", True)
    store = ControlStoreClient(tuple(store_addr))
    w = None
    try:
        cache = BatchCache()
        hbq = _worker_hbq(spec, worker_id) if spec["hbq_path"] else None
        # advertise the address peers can actually reach: the local IP of the
        # socket we used to reach the coordinator (loopback stays loopback;
        # a cross-host connection yields this machine's routable IP, and the
        # cache binds all interfaces in that case)
        my_ip = store._rpc._sock.getsockname()[0]
        bind = "127.0.0.1" if my_ip.startswith("127.") else "0.0.0.0"
        server = serve_cache(cache, host=bind, hbq=hbq)
        store.set(f"worker_addr:{worker_id}", (my_ip, server.address[1]))
        # the coordinator merges individual keys into 'worker_addrs' itself
        store.heartbeat(worker_id)
        w = Worker(spec, store, cache, worker_id, owned, hbq=hbq)
        from quokka_tpu.analysis import sanitize

        w._watchdog = sanitize.start_watchdog(f"worker-{worker_id}")
        try:
            w.run_worker()
            w._flush_emits()
        finally:
            if w._watchdog is not None:
                w._watchdog.stop()
            try:
                w._flush_metrics()
            except Exception:
                pass  # a dead coordinator store must not block shutdown
            w._shutdown_prefetch()
            w._shutdown_emitter()
            w._shutdown_spill()
            server.close()
    except Exception:
        import traceback

        # ship the traceback to the coordinator — a spawned child's stderr is
        # otherwise invisible and the run would stall until timeout
        try:
            store.set(f"worker_error:{worker_id}", traceback.format_exc())
            # unshipped flight-recorder events too (only those PAST the
            # incremental shipper's high-water mark — re-shipping the tail
            # would duplicate slices in the merged timeline): the stall
            # dump then shows what this worker did right up to the crash
            since = getattr(w, "_obs_shipped_seq", -1) if w is not None else -1
            evs = obs.RECORDER.snapshot(since=since, last_n=256)
            if evs:
                store.flight_append(worker_id, evs)
        except Exception:
            pass
        raise
    finally:
        store.close()


def _connect_store(addr, deadline: Optional[float]):
    """Connect with retry: the coordinator's store may not be serving yet
    (daemons can be launched before the first query), or may be between
    query sessions in --persist mode.  deadline=None retries forever.
    A token mismatch is deterministic and fails fast, never retried."""
    from quokka_tpu.runtime.rpc import RpcAuthError

    while True:
        try:
            return ControlStoreClient(addr)
        except RpcAuthError:
            raise
        except (ConnectionRefusedError, ConnectionError, OSError, TimeoutError):
            if deadline is not None and time.time() > deadline:
                raise
            time.sleep(0.5)


def _serve_one_session(addr, worker_id: int, join_timeout: float,
                       served=None) -> bool:
    """Join the store at addr, fetch plan + ownership, run until SHUTDOWN.
    Returns False when no plan appeared within join_timeout (nothing ran).

    `served` (persist mode): set of session ids this daemon has already
    joined.  A session is joined AT MOST ONCE — if the daemon crashed out of
    it, the coordinator has declared it dead and adopted its channels on a
    survivor; rejoining with the original ownership map would split-brain
    (two workers taping the same channels)."""
    store = _connect_store(addr, time.time() + join_timeout)
    try:
        deadline = time.time() + join_timeout
        spec_bytes = None
        owned = None
        sid = None
        while time.time() < deadline:
            if store.get("SHUTDOWN"):
                return False  # tail of an already-finished session
            sid = store.get("session_id")
            if served is not None and sid is not None and sid in served:
                return False  # already joined (and possibly crashed out of)
            spec_bytes = store.get("spec")
            owned = store.get(("owned", worker_id))
            if spec_bytes is not None and owned is not None:
                if sid is None:
                    # session_id is published BEFORE spec (run_distributed),
                    # so it is guaranteed visible once spec is — this re-read
                    # closes the sid-then-spec interleave that would
                    # otherwise run a session without recording it in
                    # `served` (split-brain on crash-and-reconnect)
                    sid = store.get("session_id")
                    if served is not None and sid in served:
                        return False
                break
            time.sleep(0.2)
    finally:
        store.close()
    if spec_bytes is None or owned is None:
        return False
    if served is not None:
        if sid is None:
            return False  # store never published a session id: do not run
        served.add(sid)
    worker_main(spec_bytes, addr, worker_id, owned)
    return True


def main(argv=None):
    """Standalone worker for multi-host deployments: join a coordinator's
    served store, fetch the plan + channel ownership, and run.

        python -m quokka_tpu.runtime.worker --store HOST:PORT --worker-id K \
            [--persist]

    The coordinator must be started with external_workers > K so K's channels
    get assigned (runtime/distributed.run_distributed).  --persist keeps the
    daemon alive across queries: each QuokkaContext query serves a fresh
    store session on the same port; the daemon reconnects and serves each in
    turn until killed (the deployment mode QuokkaClusterManager.start_cluster
    launches).  The daemon authenticates with QUOKKA_RPC_TOKEN
    (runtime/rpc.py)."""
    import argparse

    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--store", required=True, help="coordinator HOST:PORT")
    p.add_argument("--worker-id", type=int, required=True)
    p.add_argument("--persist", action="store_true",
                   help="serve query sessions forever (daemon mode)")
    args = p.parse_args(argv)
    host, port = args.store.rsplit(":", 1)
    addr = (host, int(port))
    if not args.persist:
        if not _serve_one_session(addr, args.worker_id, join_timeout=120):
            raise TimeoutError(
                f"coordinator at {args.store} never published a plan for "
                f"worker {args.worker_id} (was it started with "
                "external_workers > this id?)"
            )
        return
    from quokka_tpu.runtime.rpc import RpcAuthError

    served: set = set()
    auth_failures = 0
    while True:
        try:
            if _serve_one_session(addr, args.worker_id, join_timeout=10,
                                  served=served):
                auth_failures = 0
        except RpcAuthError:
            # A server that closes mid-handshake is indistinguishable from a
            # token rejection (the server deliberately reveals nothing), and
            # a coordinator tearing down a finished session produces exactly
            # that close.  Retry a couple of times; a real token mismatch is
            # deterministic and still dies loudly.
            auth_failures += 1
            if auth_failures >= 3:
                raise
        except (ConnectionError, OSError, TimeoutError, EOFError):
            pass  # session ended mid-flight (coordinator closed); rejoin
        except Exception:
            import traceback

            traceback.print_exc()  # session crashed; daemon stays up
        time.sleep(0.3)


if __name__ == "__main__":
    main()
