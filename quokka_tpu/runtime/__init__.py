from quokka_tpu.runtime.engine import Engine, TaskGraph
from quokka_tpu.runtime.tables import ControlStore
