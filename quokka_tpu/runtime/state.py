"""Worker-visible state: heartbeat payloads + PersistentStateVariable.

``WorkerState`` is the structured payload a worker ships with every
heartbeat (runtime/worker.py -> store_service.CoordinatorStore.heartbeat)
so the coordinator can distinguish "busy" from "wedged": current task,
phase, queue depth hint, last-progress timestamp, and the flight-recorder
sequence number (how far this worker's shipped event stream reaches).

PersistentStateVariable — a spill-backed append-only batch list.

Reference parity: pyquokka/state.py:6 — operators that accumulate unbounded
batch state (join builds, custom stateful executors) append to this list; past
a memory cap the tail spills to disk as Arrow IPC files and is streamed back
on iteration.  The device analog of "memory" here is HOST memory: device
batches must be synced down before they count as persistent state (executors
with device-resident state use the spill tier in executors/sql_execs.py
instead — this class serves host-side custom executors, the role the
reference's PersistentStateVariable plays for its "old operators").
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as ipc

from quokka_tpu import config


@dataclass
class WorkerState:
    """One worker's self-reported liveness snapshot (heartbeat payload).

    Pickled across the control-store RPC: fields stay plain primitives.
    ``task`` is ``(kind, actor, channel)`` of the task being (or last)
    dispatched, ``last_progress`` the wall-clock time of the last dispatch
    that made progress, ``events_seq`` the flight-recorder sequence this
    worker has shipped through (a coordinator seeing ``events_seq`` stall
    while heartbeats continue knows the worker is idle, not wedged)."""

    worker_id: int = -1
    phase: str = "init"  # init | barrier | run | idle | adopt | shutdown
    task: Optional[Tuple[str, int, int]] = None
    last_progress: float = 0.0
    queue_hint: int = 0  # locally-known backlog (cached pending batches)
    events_seq: int = -1
    # flight-recorder events this worker's ring has overwritten (nonzero
    # means the coordinator's merged timeline is missing this worker's
    # earliest tail — surfaced as a warning in stall reports); ships as
    # the recorder's per-event-type dict, but 0/int from older states is
    # still understood downstream (merge._drop_total)
    dropped: object = 0
    ts: float = field(default=0.0)


class PersistentStateVariable:
    def __init__(self, mem_limit_bytes: int = 1 << 28,
                 spill_dir: Optional[str] = None):
        self.mem_limit = mem_limit_bytes
        self._mem: List[pa.Table] = []
        self._mem_bytes = 0
        self._spill_files: List[str] = []
        self._spilled_rows = 0
        self._dir = spill_dir or config.SPILL_DIR
        self._tmp: Optional[str] = None

    def __len__(self) -> int:
        return len(self._mem) + len(self._spill_files)

    def num_rows(self) -> int:
        return self._spilled_rows + sum(t.num_rows for t in self._mem)

    def append(self, table: pa.Table) -> None:
        nbytes = table.nbytes
        if self._mem_bytes + nbytes > self.mem_limit and self._mem:
            self._spill_all()
        if nbytes > self.mem_limit:
            self._spill_table(table)
            return
        self._mem.append(table)
        self._mem_bytes += nbytes

    def _ensure_dir(self) -> str:
        if self._tmp is None:
            os.makedirs(self._dir, exist_ok=True)
            self._tmp = tempfile.mkdtemp(prefix="psv-", dir=self._dir)
        return self._tmp

    def _spill_table(self, table: pa.Table) -> None:
        d = self._ensure_dir()
        p = os.path.join(d, f"part-{len(self._spill_files):06d}.arrow")
        with ipc.new_file(p, table.schema) as w:
            w.write_table(table)
        self._spill_files.append(p)
        self._spilled_rows += table.num_rows

    def _spill_all(self) -> None:
        for t in self._mem:
            self._spill_table(t)
        self._mem = []
        self._mem_bytes = 0

    def __iter__(self) -> Iterator[pa.Table]:
        for p in self._spill_files:
            with ipc.open_file(p) as r:
                yield r.read_all()
        yield from self._mem

    def to_table(self) -> Optional[pa.Table]:
        tables = list(self)
        if not tables:
            return None
        return pa.concat_tables(tables, promote_options="default")

    def clear(self) -> None:
        import shutil

        self._mem = []
        self._mem_bytes = 0
        self._spill_files = []
        self._spilled_rows = 0
        if self._tmp is not None:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None
