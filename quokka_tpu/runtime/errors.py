"""Runtime error taxonomy: transient vs fatal, and corruption-as-loss.

The recovery protocol (engine.py, distributed.py) and the chaos plane
(quokka_tpu/chaos) both need callers to tell three failure classes apart:

- **transient** (``TransientError`` mixin): the operation may succeed if
  simply retried — a dropped TCP connection, a flaky store call.  Retry
  with bounded exponential backoff (``retry_with_backoff``); the request
  either never left this process or is idempotent at the receiver
  (runtime/rpc.py dedups retried request ids server-side).
- **fatal**: retrying cannot help — an auth/protocol failure
  (``RpcAuthError``), a programming error.  Surface immediately.
- **corrupt artifact** (``CorruptArtifactError``): bytes came back but
  failed their integrity check (runtime/integrity.py).  NEVER retried in
  place and NEVER trusted: the artifact is quarantined and the loss falls
  through the normal recovery chain (cache -> live HBQ -> input-lineage
  re-read -> producer rewind), exactly as if the file had vanished.
  "Corrupt artifacts are loss, never data."
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type


class TransientError(Exception):
    """Mixin marking an error as retryable (the operation did not take
    effect, or taking effect twice is harmless)."""


class RpcTransportError(TransientError, ConnectionError):
    """The RPC transport died mid-call (socket reset, peer closed, timeout)
    and reconnect-with-backoff exhausted its attempts.  Distinct from
    ``RpcAuthError`` (fatal: wrong cluster token / not a quokka server),
    which subclasses ConnectionError but NOT TransientError."""


class TransientStoreError(TransientError, RuntimeError):
    """A control-store operation failed before it was applied (flaky
    backend, chaos injection).  Safe to retry: the request never reached
    the store's mutation path."""


class CorruptArtifactError(RuntimeError):
    """An on-disk/remote artifact (HBQ spill, checkpoint) failed its
    integrity check.  The reader quarantines the artifact and treats it as
    LOSS — recovery regenerates the data; the bytes are never used."""

    def __init__(self, source: str, reason: str):
        super().__init__(f"corrupt artifact {source}: {reason}")
        self.source = source
        self.reason = reason


def is_transient(exc: BaseException) -> bool:
    return isinstance(exc, TransientError)


def retry_with_backoff(
    fn: Callable,
    *,
    attempts: int = 5,
    base_delay: float = 0.02,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (TransientError,),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Call ``fn()``; on a retryable error sleep ``base_delay * 2**k``
    (capped) and try again, up to ``attempts`` total calls.  The backoff is
    deterministic (no jitter) so a seeded chaos run replays identically.
    The final failure re-raises the last error unchanged."""
    delay = base_delay
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            if attempt == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(delay)
            delay = min(delay * 2.0, max_delay)
