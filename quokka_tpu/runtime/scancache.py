"""Device-resident scan (buffer-pool) cache.

The role a buffer pool / page cache plays in a CPU database: hot table
segments stay resident so repeated scans skip IO.  Here the cached unit is
the POST-BRIDGE DeviceBatch — decoded, dictionary-encoded, packed and already
living in device HBM — so a warm re-scan skips parquet decode, host encode
AND the host->device transfer (the two dominant costs of a scan on a
single-core ingest host behind a thin accelerator link).

Correctness: entries are keyed by the reader-provided identity of the
underlying bytes (path, mtime_ns, size, row-group, projection), so a
rewritten file never serves stale data.  DeviceBatch columns are immutable
jax arrays; the cache hands out a shallow copy so callers can attach their
own nrows/sorted_by metadata.

Scope: readers opt in by exposing ``cache_key(channel, lineage)``; lineages
whose bytes are not reproducible (REST pages, ray objects) return None and
bypass the cache.  Capped by bytes with LRU eviction
(QUOKKA_SCAN_CACHE_BYTES, 0 disables).

Sharing: ``GLOBAL`` is PROCESS-global and thread-safe — one LRU serves every
concurrent query in the query service, so a second query scanning the same
parquet is a warm hit even while the first is still running.  Keys carry the
file's byte identity, never a query id; accounting is per-query
(``get(..., query=...)`` feeds ``stats()["by_query"]``) so the service can
attribute warmth without fragmenting the cache.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from quokka_tpu.ops.batch import DeviceBatch

def _default_bytes() -> int:
    env = os.environ.get("QUOKKA_SCAN_CACHE_BYTES")
    if env is not None:
        return int(env)
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    # TPU HBM is >= 16 GB; host-memory (CPU) runs get a modest default so
    # tests and small boxes are not pinned by cached scans
    return (2 << 30) if backend not in ("cpu",) else (256 << 20)


def _batch_nbytes(batch: DeviceBatch) -> int:
    from quokka_tpu.runtime.cache import _batch_nbytes as nb

    return nb(batch)


class ScanCache:
    def __init__(self, cap_bytes: Optional[int] = None):
        self.cap = _default_bytes() if cap_bytes is None else cap_bytes
        self._lock = threading.Lock()
        self._data: "OrderedDict[Tuple, Tuple[DeviceBatch, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        # query_id -> {"hits": n, "misses": n}: per-query attribution for
        # the service's shared cache (concurrent queries, one LRU)
        self._by_query: dict = {}

    @property
    def enabled(self) -> bool:
        return self.cap > 0

    def _account(self, query: Optional[str], field: str) -> None:
        if query is None:
            return
        q = self._by_query.get(query)
        if q is None:
            q = self._by_query[query] = {"hits": 0, "misses": 0}
        q[field] += 1

    def get(self, key: Tuple,
            query: Optional[str] = None) -> Optional[DeviceBatch]:
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                self.misses += 1
                self._account(query, "misses")
                return None
            self._data.move_to_end(key)
            self.hits += 1
            self._account(query, "hits")
            b, _ = ent
        return DeviceBatch(dict(b.columns), b.valid, b.nrows, b.sorted_by, b.nrows_dev)

    def put(self, key: Tuple, batch: DeviceBatch) -> None:
        if not self.enabled:
            return
        nb = _batch_nbytes(batch)
        if nb > self.cap:
            return
        snap = DeviceBatch(
            dict(batch.columns), batch.valid, batch.nrows, batch.sorted_by, batch.nrows_dev
        )
        evicted = []
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._data[key] = (snap, nb)
            self._bytes += nb
            while self._bytes > self.cap and self._data:
                k, (_, oldnb) = self._data.popitem(last=False)
                self._bytes -= oldnb
                evicted.append(k)
        # memory ledger outside the LRU lock.  query=None: entries are
        # keyed by FILE identity and deliberately outlive the query that
        # warmed them — process-global residency, never a per-query leak
        from quokka_tpu.obs import memplane

        memplane.LEDGER.track(("scan", id(self), key),
                              memplane.SITE_READER, nb)
        for k in evicted:
            if k != key:
                memplane.LEDGER.retire(("scan", id(self), k))

    def clear(self) -> None:
        with self._lock:
            keys = list(self._data.keys())
            self._data.clear()
            self._bytes = 0
        from quokka_tpu.obs import memplane

        for k in keys:
            memplane.LEDGER.retire(("scan", id(self), k))

    def drop_query(self, query: str) -> None:
        """Forget a finished query's ACCOUNTING.  Cached batches stay — they
        are keyed by file identity and are exactly the warmth the next query
        over the same files wants."""
        with self._lock:
            self._by_query.pop(query, None)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._data),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "by_query": {q: dict(c) for q, c in self._by_query.items()},
            }


GLOBAL = ScanCache()


def clear() -> None:
    GLOBAL.clear()
