"""Control-plane tables.

The reference keeps all scheduler state in 17 prefix-namespaced Redis tables
with MULTI/EXEC transactions (pyquokka/tables.py:8-339, fault-tolerance.md).
quokka-tpu keeps the same table taxonomy — it is the contract the recovery
protocol reasons over — behind a ControlStore interface.  The default
implementation is an embedded in-process store with a global lock providing the
same serialized-transaction discipline; a networked server can implement the
same interface later for multi-host deployments without touching the runtime.

Table map (name -> role, reference location in pyquokka/tables.py):
  CT   cemetery: objects safe to GC                      (103)
  NOT  node -> object names it must keep                  (121)
  PT   object name -> producing node                      (138)
  NTT  (node) -> pending task list                        (152)
  GIT  generated input seqs per (actor, channel)          (170)
  LT   lineage: (actor, channel, seq) -> lineage payload  (187)
  DST  done seqs per (actor, channel)                     (200)
  LCT  last checkpoint per (actor, channel)               (214)
  EST  executor state seq per (actor, channel)            (230)
  CLT  (actor, channel) -> worker/node location           (243)
  FOT  actor -> pickled reader/executor object            (257)
  IRT  input requirements at checkpoints                  (266)
  SAT  set of sorted (order-preserving) actors            (278)
  PFT  (source actor, target actor) -> partition spec     (292)
  AST  actor -> execution stage                           (305)
  LIT  last input seq per (actor, channel)                (318)
  EWT  consumption watermark per (actor, channel)         (332)
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

TABLE_NAMES = (
    "CT", "NOT", "PT", "NTT", "GIT", "LT", "DST", "LCT", "EST", "CLT",
    "FOT", "IRT", "SAT", "PFT", "AST", "LIT", "EWT", "CMT",
)


class ControlStore:
    """Embedded transactional KV/table store (single leader semantics)."""

    def __init__(self):
        # QK_SANITIZE=1 wraps the lock in the lock-order recorder
        # (analysis/sanitize.py); production gets the bare RLock
        from quokka_tpu.analysis import sanitize

        self._lock = sanitize.maybe_instrument(
            "controlstore", threading.RLock())
        self.kv: Dict[str, Any] = {}
        self.tables: Dict[str, Dict] = {name: {} for name in TABLE_NAMES}
        # NTT values are deques of tasks
        self.tables["NTT"] = defaultdict(deque)
        # set-valued tables
        self.tables["CT"] = set()
        self.tables["SAT"] = set()
        # CMT: channel-major actors (range-partitioned sorts) — consumers read
        # channel c fully before channel c+1; SAT's (seq, channel) interleave
        # would shuffle ranges once a channel emits more than one batch
        self.tables["CMT"] = set()
        self.tables["NOT"] = defaultdict(set)
        self.tables["DST"] = defaultdict(set)
        self.tables["GIT"] = defaultdict(set)

    @contextmanager
    def transaction(self):
        """All mutations inside happen atomically w.r.t. other transactions.
        (Serialized by a single lock — same guarantee Redis MULTI/EXEC gives
        the reference's commit paths, core.py:553,692.)"""
        with self._lock:
            yield self

    # -- generic kv ----------------------------------------------------------
    def set(self, key: str, value):
        with self._lock:
            self.kv[key] = value

    def get(self, key: str, default=None):
        with self._lock:
            return self.kv.get(key, default)

    # -- NTT: task queues ----------------------------------------------------
    def ntt_push(self, node: Tuple, task):
        with self._lock:
            self.tables["NTT"][node].append(task)

    def ntt_pop(self, node: Tuple, channels: Optional[List[int]] = None):
        """Pop the next task for `node`; with `channels`, only a task whose
        channel is in the set (multi-worker: each worker owns channels)."""
        with self._lock:
            q = self.tables["NTT"][node]
            if not q:
                return None
            if channels is None:
                return q.popleft()
            chans = set(channels)
            for i, t in enumerate(q):
                if t.channel in chans:
                    del q[i]
                    return t
            return None

    def ntt_remove_exec(self, node: Tuple, channel: int) -> None:
        """Drop queued exec tasks of one channel (failure recovery)."""
        with self._lock:
            q = self.tables["NTT"][node]
            keep = [t for t in q if not (t.name == "exec" and t.channel == channel)]
            q.clear()
            q.extend(keep)

    def ntt_remove_channel(self, node: Tuple, channel: int) -> None:
        """Drop EVERY queued task of one channel — adoption replaces them with
        rebuilt tasks; stale queued duplicates would double-execute."""
        with self._lock:
            q = self.tables["NTT"][node]
            keep = [t for t in q if t.channel != channel]
            q.clear()
            q.extend(keep)

    def ntt_peek_all(self, node: Tuple) -> List:
        with self._lock:
            return list(self.tables["NTT"][node])

    def ntt_len(self, node: Tuple) -> int:
        with self._lock:
            return len(self.tables["NTT"][node])

    def ntt_total(self) -> int:
        with self._lock:
            return sum(len(q) for q in self.tables["NTT"].values())

    # -- simple keyed tables -------------------------------------------------
    def tset(self, table: str, key, value):
        with self._lock:
            self.tables[table][key] = value

    def tget(self, table: str, key, default=None):
        with self._lock:
            return self.tables[table].get(key, default)

    def titems(self, table: str):
        with self._lock:
            return list(self.tables[table].items())

    def tappend(self, table: str, key, value):
        """Append to a list-valued entry (creating it) — replaces the
        read-modify-write pattern, which a served store cannot support."""
        with self._lock:
            t = self.tables[table]
            if key not in t:
                t[key] = []
            t[key].append(value)

    def tlen(self, table: str, key) -> int:
        with self._lock:
            v = self.tables[table].get(key)
            return 0 if v is None else len(v)

    def tdel(self, table: str, key) -> None:
        with self._lock:
            self.tables[table].pop(key, None)

    # -- lineage tape GC ------------------------------------------------------
    # Tapes grow per event for a run's whole life; checkpoints make the prefix
    # before the checkpoint position dead.  Positions stay LOGICAL (base +
    # list index) so LCT tape_pos values survive trimming.

    def tape_len(self, actor, ch) -> int:
        with self._lock:
            base = self.tables["LT"].get(("tape_base", actor, ch), 0)
            tape = self.tables["LT"].get(("tape", actor, ch))
            return base + (0 if tape is None else len(tape))

    def tape_slice(self, actor, ch, from_logical: int) -> List:
        with self._lock:
            base = self.tables["LT"].get(("tape_base", actor, ch), 0)
            tape = self.tables["LT"].get(("tape", actor, ch)) or []
            return list(tape[max(0, from_logical - base):])

    def tape_trim(self, actor, ch, upto_logical: int) -> None:
        with self._lock:
            base = self.tables["LT"].get(("tape_base", actor, ch), 0)
            tape = self.tables["LT"].get(("tape", actor, ch))
            if tape is None:
                return
            drop = max(0, min(upto_logical - base, len(tape)))
            if drop:
                del tape[:drop]
                self.tables["LT"][("tape_base", actor, ch)] = base + drop

    # -- set-valued tables ---------------------------------------------------
    def sadd(self, table: str, key, value=None):
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                t.add(key)
            else:
                t[key].add(value)

    def smembers(self, table: str, key=None):
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                return set(t)
            return set(t.get(key, ()))

    def scontains(self, table: str, key, value=None) -> bool:
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                return key in t
            return value in t.get(key, ())

    # -- debug ---------------------------------------------------------------
    def dump(self) -> Dict[str, Any]:
        """Snapshot of all control tables (the debugger.py:6-41 equivalent)."""
        with self._lock:
            out = {"kv": dict(self.kv)}
            for name, t in self.tables.items():
                if isinstance(t, set):
                    out[name] = set(t)
                elif name == "NTT":
                    out[name] = {k: list(v) for k, v in t.items()}
                else:
                    out[name] = dict(t)
            return out
