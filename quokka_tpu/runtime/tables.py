"""Control-plane tables.

The reference keeps all scheduler state in 17 prefix-namespaced Redis tables
with MULTI/EXEC transactions (pyquokka/tables.py:8-339, fault-tolerance.md).
quokka-tpu keeps the same table taxonomy — it is the contract the recovery
protocol reasons over — behind a ControlStore interface.  The default
implementation is an embedded in-process store with a global lock providing the
same serialized-transaction discipline; a networked server can implement the
same interface later for multi-host deployments without touching the runtime.

Namespacing (the query service): one store can host MANY concurrent queries.
``store.namespace(query_id)`` returns a ``NamespacedStore`` view that wraps
every table key as ``(query_id, key)`` (and set members as
``(query_id, member)``), so two TaskGraphs share one store without their
NTT/CT/DST/GIT rows colliding; ``drop_namespace(query_id)`` GCs everything a
finished query wrote.  The view only calls the public store surface, so it
wraps the embedded store and the RPC client alike.

Table map (name -> role, reference location in pyquokka/tables.py), annotated
with the writer/reader/GC matrix the protocol verifier
(``python -m quokka_tpu.analysis.protocol``) checks.  [W]=who writes,
[R]=who reads, [GC]=who reclaims; rows with no [GC] are bounded (overwrite
semantics per (actor, channel) key, or membership bounded by graph size).
Tables marked *parity* exist for taxonomy parity with the reference but have
no writers in this implementation (their reference roles are served by the
device cache / actor objects directly); writing one without adding a reader
trips protocol rule QK014 (dead write).

  CT   cemetery: objects safe to GC                      (103) *parity*
  NOT  node -> object names it must keep                  (121) *parity*
  PT   object name -> producing node                      (138) *parity*
  NTT  (node) -> pending task list                        (152)
       [W] ntt_push  [R/GC] ntt_pop / ntt_remove_*
  GIT  generated input seqs per (actor, channel)          (170)
       [W] engine commit  [R] recovery remaining-tape  [GC] manifest.gc
       (srem below the gc floor; recovery clamps its rebuild range there)
  LT   lineage: (actor, channel, seq) -> lineage payload  (187)
       plus sub-keyed rows: ("tape", a, ch) event list, ("tape_base", a, ch),
       ("ckpts", a, ch) checkpoint history, ("gc_floor*", a, ch) markers
       [W] engine commit/checkpoint  [R] replay + rewind planner
       [GC] manifest.gc (tdel below floor, tape_trim, history pruning)
  DST  done seqs per (actor, channel)                     (200)
       [W] engine finish  [R] scontains  [GC] tdel on recovery
  LCT  last checkpoint per (actor, channel)               (214)
       [W] checkpoint txn (QK017: atomic with ckpts+IRT)  [R] planner
  EST  executor state seq per (actor, channel)            (230) *parity*
  CLT  (actor, channel) -> worker/node location           (243)
       [W] coordinator placement  [R] worker adoption
  FOT  actor -> pickled reader/executor object            (257) *parity*
  IRT  input requirements at checkpoints                  (266)
       [W] checkpoint txn  [R] planner frontier walk  [GC] manifest.gc
  SAT  set of sorted (order-preserving) actors            (278)
       [W] graph build  [R] smembers (bounded by graph size)
  PFT  (source actor, target actor) -> partition spec     (292)
       [W] graph build  [R] push path (bounded by graph size)
  AST  actor -> execution stage                           (305)
       [W] graph build  [R] titems (bounded by graph size)
  LIT  last input seq per (actor, channel)                (318)
       [W] engine commit  [R] recovery/planner (overwrite, bounded)
  EWT  consumption watermark per (actor, channel)         (332)
       [W] exec consume  [R] producer throttle (overwrite, bounded)
  SWM/SWMC/SST stream watermarks + stop flags: SWM is per-seq
       [W] push  [R] replay  [GC] manifest.gc; SWMC/SST overwrite, bounded
  ADT  adaptive-exchange records (planner/adapt.py): (src actor, tgt
       actor) -> {mode, fat, from_seq} routing rewrite, written BEFORE the
       first rerouted batch ships so replay is deterministic
       [W] engine skew trigger  [R] partition fns + recovery refresh
       (overwrite, bounded by graph edge count)
  RMT  resume-manifest bookkeeping (runtime/resume.py, durable batch):
       ("sink", actor, ch) -> emitted result floor and ("hist",) ->
       manifest-generation journal
       [W] engine result append + resume.update  [R] resume.update
       manifest build + service /status manifest_writes column
       [GC] resume.update journal trim (drop-and-reappend at the cap);
       sink rows are overwrite-per-channel, bounded by sink width
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

TABLE_NAMES = (
    "CT", "NOT", "PT", "NTT", "GIT", "LT", "DST", "LCT", "EST", "CLT",
    "FOT", "IRT", "SAT", "PFT", "AST", "LIT", "EWT", "CMT",
    # streaming plane: SWM = per-(actor, ch, seq) watermark stamped at push
    # (recovery replay re-presents the exact watermark sequence); SWMC =
    # per-(actor, ch) watermark high-water mark; SST = stop flags of
    # standing-query source actors (StreamingHandle.stop)
    "SWM", "SWMC", "SST",
    # adaptive exchanges (planner/adapt.py): durable routing rewrites
    "ADT",
    # batch resume manifests (runtime/resume.py): sink emitted floors +
    # manifest-generation journal
    "RMT",
)


class ControlStore:
    """Embedded transactional KV/table store (single leader semantics)."""

    def __init__(self):
        # QK_SANITIZE=1 wraps the lock in the lock-order recorder
        # (analysis/sanitize.py); production gets the bare RLock
        from quokka_tpu.analysis import sanitize

        self._lock = sanitize.maybe_instrument(
            "controlstore", threading.RLock())
        self.kv: Dict[str, Any] = {}
        self.tables: Dict[str, Dict] = {name: {} for name in TABLE_NAMES}
        # NTT values are deques of tasks
        self.tables["NTT"] = defaultdict(deque)
        # set-valued tables
        self.tables["CT"] = set()
        self.tables["SAT"] = set()
        # CMT: channel-major actors (range-partitioned sorts) — consumers read
        # channel c fully before channel c+1; SAT's (seq, channel) interleave
        # would shuffle ranges once a channel emits more than one batch
        self.tables["CMT"] = set()
        self.tables["NOT"] = defaultdict(set)
        self.tables["DST"] = defaultdict(set)
        self.tables["GIT"] = defaultdict(set)

    @contextmanager
    def transaction(self):
        """All mutations inside happen atomically w.r.t. other transactions.
        (Serialized by a single lock — same guarantee Redis MULTI/EXEC gives
        the reference's commit paths, core.py:553,692.)"""
        with self._lock:
            yield self

    # -- generic kv ----------------------------------------------------------
    def set(self, key: str, value):
        with self._lock:
            self.kv[key] = value

    def get(self, key: str, default=None):
        with self._lock:
            return self.kv.get(key, default)

    # -- NTT: task queues ----------------------------------------------------
    def ntt_push(self, node: Tuple, task):
        with self._lock:
            self.tables["NTT"][node].append(task)

    def ntt_pop(self, node: Tuple, channels: Optional[List[int]] = None):
        """Pop the next task for `node`; with `channels`, only a task whose
        channel is in the set (multi-worker: each worker owns channels)."""
        with self._lock:
            q = self.tables["NTT"][node]
            if not q:
                return None
            if channels is None:
                return q.popleft()
            chans = set(channels)
            for i, t in enumerate(q):
                if t.channel in chans:
                    del q[i]
                    return t
            return None

    def ntt_remove_exec(self, node: Tuple, channel: int) -> None:
        """Drop queued exec tasks of one channel (failure recovery)."""
        with self._lock:
            q = self.tables["NTT"][node]
            keep = [t for t in q if not (t.name == "exec" and t.channel == channel)]
            q.clear()
            q.extend(keep)

    def ntt_remove_channel(self, node: Tuple, channel: int) -> None:
        """Drop EVERY queued task of one channel — adoption replaces them with
        rebuilt tasks; stale queued duplicates would double-execute."""
        with self._lock:
            q = self.tables["NTT"][node]
            keep = [t for t in q if t.channel != channel]
            q.clear()
            q.extend(keep)

    def ntt_peek_all(self, node: Tuple) -> List:
        with self._lock:
            return list(self.tables["NTT"][node])

    def ntt_len(self, node: Tuple) -> int:
        with self._lock:
            return len(self.tables["NTT"][node])

    def ntt_total(self, ns=None) -> int:
        """Total queued tasks; with ``ns``, only queues of that namespace
        (node keys wrapped ``(ns, node)`` by NamespacedStore)."""
        with self._lock:
            if ns is None:
                return sum(len(q) for q in self.tables["NTT"].values())
            return sum(
                len(q) for k, q in self.tables["NTT"].items()
                if isinstance(k, tuple) and len(k) == 2 and k[0] == ns
            )

    # -- simple keyed tables -------------------------------------------------
    def tset(self, table: str, key, value):
        with self._lock:
            self.tables[table][key] = value

    def tget(self, table: str, key, default=None):
        with self._lock:
            return self.tables[table].get(key, default)

    def titems(self, table: str):
        with self._lock:
            return list(self.tables[table].items())

    def tappend(self, table: str, key, value):
        """Append to a list-valued entry (creating it) — replaces the
        read-modify-write pattern, which a served store cannot support."""
        with self._lock:
            t = self.tables[table]
            if key not in t:
                t[key] = []
            t[key].append(value)

    def tlen(self, table: str, key) -> int:
        with self._lock:
            v = self.tables[table].get(key)
            return 0 if v is None else len(v)

    def tdel(self, table: str, key) -> None:
        with self._lock:
            self.tables[table].pop(key, None)

    # -- lineage tape GC ------------------------------------------------------
    # Tapes grow per event for a run's whole life; checkpoints make the prefix
    # before the checkpoint position dead.  Positions stay LOGICAL (base +
    # list index) so LCT tape_pos values survive trimming.

    def tape_append(self, actor, ch, event) -> None:
        """Append one event to a channel's lineage tape.  The single entry
        point for tape writes — NamespacedStore re-keys it consistently with
        tape_len/tape_slice."""
        self.tappend("LT", ("tape", actor, ch), event)

    def tape_len(self, actor, ch) -> int:
        with self._lock:
            base = self.tables["LT"].get(("tape_base", actor, ch), 0)
            tape = self.tables["LT"].get(("tape", actor, ch))
            return base + (0 if tape is None else len(tape))

    def tape_slice(self, actor, ch, from_logical: int) -> List:
        with self._lock:
            base = self.tables["LT"].get(("tape_base", actor, ch), 0)
            tape = self.tables["LT"].get(("tape", actor, ch)) or []
            return list(tape[max(0, from_logical - base):])

    def tape_trim(self, actor, ch, upto_logical: int) -> None:
        with self._lock:
            base = self.tables["LT"].get(("tape_base", actor, ch), 0)
            tape = self.tables["LT"].get(("tape", actor, ch))
            if tape is None:
                return
            drop = max(0, min(upto_logical - base, len(tape)))
            if drop:
                del tape[:drop]
                self.tables["LT"][("tape_base", actor, ch)] = base + drop

    # -- set-valued tables ---------------------------------------------------
    def sadd(self, table: str, key, value=None):
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                t.add(key)
            else:
                t[key].add(value)

    def smembers(self, table: str, key=None):
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                return set(t)
            return set(t.get(key, ()))

    def scontains(self, table: str, key, value=None) -> bool:
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                return key in t
            return value in t.get(key, ())

    def srem(self, table: str, key, value=None) -> None:
        """Discard one member (tolerant, like tdel) — the GC half of sadd
        for growing sets (GIT seq membership below the streaming gc floor)."""
        with self._lock:
            t = self.tables[table]
            if isinstance(t, set):
                t.discard(key)
            elif key in t:
                t[key].discard(value)

    # -- namespaces (multi-query) --------------------------------------------
    def namespace(self, query_id: str) -> "NamespacedStore":
        """A view of this store whose table keys are wrapped
        ``(query_id, key)`` — one store, many concurrent queries."""
        return NamespacedStore(self, query_id)

    def drop_namespace(self, query_id: str) -> int:
        """GC every table row, queue and set member a query namespace wrote;
        returns the number of entries dropped.  kv entries are keyed
        free-form, so only tuple kv keys carrying the query id anywhere
        (e.g. ``("metrics", query_id, worker)``) are swept."""
        dropped = 0
        with self._lock:
            for name, t in self.tables.items():
                if isinstance(t, set):
                    dead = {m for m in t
                            if isinstance(m, tuple) and len(m) == 2
                            and m[0] == query_id}
                    t -= dead
                    dropped += len(dead)
                else:
                    dead_keys = [k for k in t
                                 if isinstance(k, tuple) and len(k) == 2
                                 and k[0] == query_id]
                    for k in dead_keys:
                        del t[k]
                    dropped += len(dead_keys)
            dead_kv = [k for k in self.kv
                       if isinstance(k, tuple) and query_id in k]
            for k in dead_kv:
                del self.kv[k]
            dropped += len(dead_kv)
        return dropped

    # -- debug ---------------------------------------------------------------
    def dump(self) -> Dict[str, Any]:
        """Snapshot of all control tables (the debugger.py:6-41 equivalent)."""
        with self._lock:
            out = {"kv": dict(self.kv)}
            for name, t in self.tables.items():
                if isinstance(t, set):
                    out[name] = set(t)
                elif name == "NTT":
                    out[name] = {k: list(v) for k, v in t.items()}
                else:
                    out[name] = dict(t)
            return out


class NamespacedStore:
    """Per-query view of a shared store: every TABLE key goes through
    ``(query_id, key)`` (set members ``(query_id, member)``), so the engine's
    scheduling/recovery code runs unchanged against a store hosting many
    concurrent queries.  kv get/set, transactions and the coordinator extras
    (heartbeat, mailboxes, results, flight streams) pass through un-wrapped —
    they are worker/session-global, not per-query.

    Only the PUBLIC store surface is called, so the same view wraps the
    embedded ControlStore, a CoordinatorStore, or a ControlStoreClient."""

    def __init__(self, root, query_id: str):
        self._root = root
        self.query_id = query_id

    def __getattr__(self, name):
        # kv set/get, transaction, close, heartbeat, mailbox_*, result_append,
        # flight_append, drop_namespace, ... — namespace-independent surface
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(self._root, name)

    def _k(self, key):
        return (self.query_id, key)

    # -- NTT -----------------------------------------------------------------
    def ntt_push(self, node, task):
        return self._root.ntt_push(self._k(node), task)

    def ntt_pop(self, node, *args, **kwargs):
        return self._root.ntt_pop(self._k(node), *args, **kwargs)

    def ntt_remove_exec(self, node, channel):
        return self._root.ntt_remove_exec(self._k(node), channel)

    def ntt_remove_channel(self, node, channel):
        return self._root.ntt_remove_channel(self._k(node), channel)

    def ntt_peek_all(self, node):
        return self._root.ntt_peek_all(self._k(node))

    def ntt_len(self, node):
        return self._root.ntt_len(self._k(node))

    def ntt_total(self):
        return self._root.ntt_total(self.query_id)

    # -- keyed tables --------------------------------------------------------
    def tset(self, table, key, value):
        return self._root.tset(table, self._k(key), value)

    def tget(self, table, key, default=None):
        return self._root.tget(table, self._k(key), default)

    def titems(self, table):
        return [
            (k[1], v) for k, v in self._root.titems(table)
            if isinstance(k, tuple) and len(k) == 2 and k[0] == self.query_id
        ]

    def tappend(self, table, key, value):
        return self._root.tappend(table, self._k(key), value)

    def tlen(self, table, key):
        return self._root.tlen(table, self._k(key))

    def tdel(self, table, key):
        return self._root.tdel(table, self._k(key))

    # -- lineage tape --------------------------------------------------------
    # Reimplemented over the generic LT ops (not delegated to the root's
    # tape_* helpers) so the composed keys land under this namespace's
    # ``(query_id, ...)`` wrapping — one consistent prefix drop_namespace
    # can sweep.  Single-writer-per-channel discipline makes the non-atomic
    # base+list reads safe (the only appender is the channel's own task).
    def tape_append(self, actor, ch, event):
        self.tappend("LT", ("tape", actor, ch), event)

    def tape_len(self, actor, ch) -> int:
        base = self.tget("LT", ("tape_base", actor, ch), 0)
        return base + self.tlen("LT", ("tape", actor, ch))

    def tape_slice(self, actor, ch, from_logical: int) -> List:
        base = self.tget("LT", ("tape_base", actor, ch), 0)
        tape = self.tget("LT", ("tape", actor, ch)) or []
        return list(tape[max(0, from_logical - base):])

    def tape_trim(self, actor, ch, upto_logical: int) -> None:
        base = self.tget("LT", ("tape_base", actor, ch), 0)
        tape = self.tget("LT", ("tape", actor, ch))
        if tape is None:
            return
        drop = max(0, min(upto_logical - base, len(tape)))
        if drop:
            self.tset("LT", ("tape", actor, ch), list(tape[drop:]))
            self.tset("LT", ("tape_base", actor, ch), base + drop)

    # -- set-valued tables ---------------------------------------------------
    def sadd(self, table, key, value=None):
        return self._root.sadd(table, self._k(key), value)

    def smembers(self, table, key=None):
        if key is None:
            return {
                m[1] for m in self._root.smembers(table)
                if isinstance(m, tuple) and len(m) == 2
                and m[0] == self.query_id
            }
        return self._root.smembers(table, self._k(key))

    def scontains(self, table, key, value=None) -> bool:
        return self._root.scontains(table, self._k(key), value)

    def srem(self, table, key, value=None):
        return self._root.srem(table, self._k(key), value)

    def drop(self) -> int:
        """GC this namespace from the shared store."""
        return self._root.drop_namespace(self.query_id)
