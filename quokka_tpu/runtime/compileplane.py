"""Compile plane: AOT program acquisition, executable persistence, and
plan-driven pre-warm.

The engine's static-shape discipline means a query shape compiles a finite
program set and then reuses it forever — but BENCH_r05 showed warmup still
costing 3-6x steady state: every fused program paid trace + lower +
compile-or-cache-load serialized with its first dispatch.  This module makes
compilation a first-class, front-loaded concern with three layers:

- **AOT acquisition** (``acquire``): a program cache miss compiles the
  program EXPLICITLY (``jit(...).lower(args).compile()``) instead of
  letting the first dispatch pay an implicit trace, and wraps the compiled
  executable with a jit fallback so an aval drift can never error.
- **cross-restart persistence**: compiled executables are serialized
  (``jax.experimental.serialize_executable``) into
  ``<cache>/aot/<backend fingerprint>/`` with the same checksummed framing
  the spill/checkpoint tier uses (runtime/integrity.py).  A restarted
  replica deserializes the executable directly — no trace, no lower, no
  XLA cache lookup.  Corrupt or foreign artifacts are quarantined and fall
  back to a fresh compile, never an error.
- **plan ledger + pre-warm**: every program a query uses is recorded under
  the query's plan fingerprint (``plans/<fp>.json``).  ``prewarm_plan``
  replays that ledger on a background pool at submit time (QueryService)
  or query start (one-shot path), so executables load while admission/scan
  run instead of serializing with the first dispatch.

Counters (obs.REGISTRY, exported via /metrics): ``compile.cache_hit`` (a
persisted executable answered a miss), ``compile.miss`` (a fresh backend
compile), ``compile.prewarm_hit`` (a dispatch found its program already
installed by pre-warm), plus per-query twins GC'd with the query namespace.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import queue
import threading
from typing import Callable, Dict, List, Optional, Tuple

from quokka_tpu import config
from quokka_tpu.ops import sigkey
from quokka_tpu.runtime.errors import CorruptArtifactError
from quokka_tpu.runtime.integrity import frame, unframe

# process-wide program cache: key (a sigkey.make_key tuple) -> callable.
# Dispatch hot paths read this dict directly (one dict get per batch);
# acquire()/prewarm fill it.
PROGRAMS: Dict[Tuple, object] = {}

_ENTRY_VERSION = 1


def _enabled() -> bool:
    v = os.environ.get("QUOKKA_AOT_CACHE", "1").lower()
    return v not in ("0", "false", "no", "off")


# ---------------------------------------------------------------------------
# backend/topology fingerprint (lazy: reading device kind/count initializes
# the backend, which must not happen at import time)
# ---------------------------------------------------------------------------

_fp_lock = threading.Lock()
_fingerprint: Optional[str] = None


def backend_fingerprint() -> str:
    """Platform + device kind + device count + jax version + host uarch:
    serialized executables are valid only on the topology that compiled
    them, so the artifact directory is namespaced by this — a foreign
    host/backend/jax is a cache MISS instead of a load error."""
    global _fingerprint
    with _fp_lock:
        if _fingerprint is not None:
            return _fingerprint
        import jax

        try:
            devs = jax.devices()
            platform = jax.default_backend()
            kind = devs[0].device_kind if devs else "none"
            count = len(devs)
        except Exception:  # pragma: no cover - backend init failure
            platform, kind, count = "unknown", "unknown", 0
        raw = "|".join([
            platform, str(kind), str(count),
            getattr(jax, "__version__", ""), config._host_fingerprint(),
        ])
        h = hashlib.sha256(raw.encode()).hexdigest()[:12]
        _fingerprint = f"{platform}-{count}x-{h}"
        return _fingerprint


def _root_dir() -> Optional[str]:
    if not _enabled():
        return None
    base = os.environ.get("QUOKKA_AOT_CACHE_DIR", "")
    if not base:
        if not config.CACHE_ROOT:
            return None  # persistent caching opted out entirely
        base = os.path.join(config.CACHE_ROOT, "aot")
    return base


def _aot_dir(create: bool = False) -> Optional[str]:
    base = _root_dir()
    if base is None:
        return None
    d = os.path.join(base, backend_fingerprint())
    if create:
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return None
    return d


def _plans_dir(create: bool = False) -> Optional[str]:
    base = _root_dir()
    if base is None:
        return None
    d = os.path.join(base, "plans")
    if create:
        try:
            os.makedirs(d, exist_ok=True)
        except OSError:
            return None
    return d


def key_hash(key: Tuple) -> str:
    """Stable filename for a program key (keys are tuples of builtins, so
    repr is deterministic across processes)."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:24]


# ---------------------------------------------------------------------------
# per-query attribution scope (the engine enters it around dispatch, same
# once-resolved discipline as kernels.shuffle_sync_scope)
# ---------------------------------------------------------------------------

_SCOPE = threading.local()


@contextlib.contextmanager
def query_scope(counters: Optional[Dict[str, object]], plan_fp: Optional[str]):
    """counters: {"cache_hit"/"miss"/"prewarm_hit": Counter} per-query twins
    (or None); plan_fp: the plan fingerprint program uses are recorded
    under."""
    prev = (getattr(_SCOPE, "counters", None), getattr(_SCOPE, "fp", None))
    _SCOPE.counters, _SCOPE.fp = counters, plan_fp
    try:
        yield
    finally:
        _SCOPE.counters, _SCOPE.fp = prev


def _count(event: str) -> None:
    from quokka_tpu import obs

    obs.REGISTRY.counter(f"compile.{event}").inc()
    c = getattr(_SCOPE, "counters", None)
    if c is not None:
        qc = c.get(event)
        if qc is not None:
            qc.inc()


# ---------------------------------------------------------------------------
# plan ledger: plan fingerprint -> set of program key hashes
# ---------------------------------------------------------------------------

_plan_lock = threading.Lock()
_PLAN_SIGS: Dict[str, set] = {}
# key-hash -> pickled key + entry (kept so prewarm can install by hash)
_KEY_BY_HASH: Dict[str, Tuple] = {}
# key hashes whose program is already resident: prewarm filters on this
# BEFORE touching disk, so per-query prewarm of an already-warm plan is a
# set lookup, not a re-deserialization of the whole executable set
_INSTALLED_HASHES: set = set()


def _describe(obj, depth: int = 0) -> str:
    """Deterministic structural description of a plan component (executor
    factories are functools.partials over executor classes, expressions,
    and plain data — never described by object repr, which embeds
    addresses)."""
    import functools

    if depth > 6:
        return "..."
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return repr(obj)
    if isinstance(obj, functools.partial):
        inner = [_describe(obj.func, depth + 1)]
        inner += [_describe(a, depth + 1) for a in obj.args]
        inner += [f"{k}={_describe(v, depth + 1)}"
                  for k, v in sorted(obj.keywords.items())]
        return f"partial({', '.join(inner)})"
    if isinstance(obj, type):
        return obj.__name__
    if isinstance(obj, (list, tuple)):
        return "[" + ",".join(_describe(x, depth + 1) for x in obj) + "]"
    if isinstance(obj, dict):
        return "{" + ",".join(
            f"{_describe(k, depth + 1)}:{_describe(v, depth + 1)}"
            for k, v in sorted(obj.items(), key=lambda kv: str(kv[0]))
        ) + "}"
    sql = getattr(obj, "sql", None)
    if callable(sql):
        try:
            return f"sql:{sql()}"
        except Exception:  # noqa: BLE001 — partial exprs still fingerprint
            return f"sql?:{type(obj).__name__}"
    if callable(obj):
        return getattr(obj, "__name__", type(obj).__name__)
    # dataclass-ish plan objects (AggPlan): stable field dump
    d = getattr(obj, "__dict__", None)
    if d:
        return type(obj).__name__ + _describe(d, depth + 1)
    return type(obj).__name__


def plan_fingerprint(graph) -> str:
    """Structural fingerprint of a lowered TaskGraph: executor shapes,
    expression text, and reader size classes (``size_hint`` bucketed to the
    canonical ladder) — everything that decides which programs the query
    will request, nothing that varies per run (query ids, paths, object
    addresses)."""
    parts: List[str] = []
    for aid in sorted(graph.actors):
        info = graph.actors[aid]
        desc = [str(aid), info.kind, str(info.channels)]
        if info.reader is not None:
            desc.append(type(info.reader).__name__)
            hint_fn = getattr(info.reader, "size_hint", None)
            if hint_fn is not None:
                try:
                    # bucket the byte hint: plans over same-scale data share
                    # a fingerprint; a 4x data change is a different shape
                    desc.append(str(sigkey.pow2_dim(max(1, int(hint_fn())))))
                except Exception:  # noqa: BLE001 — hintless readers still
                    desc.append("hint?")  # fingerprint structurally
        if info.executor_factory is not None:
            desc.append(_describe(info.executor_factory))
        if info.predicate is not None:
            desc.append(_describe(getattr(info.predicate, "expr", None)))
        if info.projection:
            desc.append(",".join(info.projection))
        parts.append("|".join(desc))
    raw = ";".join(parts)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


# key tuple -> hash memo so the per-dispatch note costs a dict get, not a
# repr + sha256 (reads are GIL-atomic; writes take the plan lock)
_HASH_BY_KEY: Dict[Tuple, str] = {}


def note_program(key: Tuple, installed: bool = False) -> None:
    """Record a program use under the current query scope's plan.  Called
    on EVERY dispatch-path resolution — including in-memory hits, so a
    plan that reuses another plan's programs still records the full set —
    with a lock-free fast path once (key, plan) is known."""
    fp = getattr(_SCOPE, "fp", None)
    h = _HASH_BY_KEY.get(key)
    known = h is not None
    if known and not installed:
        s = _PLAN_SIGS.get(fp) if fp is not None else None
        if fp is None or (s is not None and h in s):
            return  # steady state: nothing new to record
    if not known:
        h = key_hash(key)
    with _plan_lock:
        _HASH_BY_KEY[key] = h
        _KEY_BY_HASH[h] = key
        if installed:
            _INSTALLED_HASHES.add(h)
        if fp is not None:
            _PLAN_SIGS.setdefault(fp, set()).add(h)


def _plan_path(fp: str, create: bool = False) -> Optional[str]:
    d = _plans_dir(create=create)
    return None if d is None else os.path.join(d, f"{fp}.json")


# a ledger merge takes milliseconds; a lock file older than this was left
# by a dead holder (chaos kill between O_EXCL create and unlink) and is
# broken, otherwise EVERY later flush of that plan would pay the full
# bounded wait on teardown forever
_LOCK_STALE_S = 5.0


@contextlib.contextmanager
def _merge_lock(path: str, attempts: int = 40, pause: float = 0.025):
    """Best-effort cross-process exclusion for the read-merge-replace on
    one ledger file: two replicas sharing a cache dir must not overwrite
    each other's merges (lost update = the 'shrink-never' promise broken).
    O_EXCL lock file with bounded wait and stale-lock takeover; on timeout
    the merge proceeds unlocked — a possible lost update beats a stuck
    teardown, and the loser's sigs return on its next flush."""
    import time

    lock = path + ".lock"
    held = False
    for _ in range(attempts):
        try:
            os.close(os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            held = True
            break
        except FileExistsError:
            try:
                stale = time.time() - os.path.getmtime(lock) > _LOCK_STALE_S
            except OSError:
                continue  # holder just released it: retry immediately
            if stale:
                with contextlib.suppress(OSError):
                    os.unlink(lock)
                continue
            time.sleep(pause)
        except OSError:
            break  # unwritable dir: the write below will say so loudly
    try:
        yield
    finally:
        if held:
            with contextlib.suppress(OSError):
                os.unlink(lock)


def flush_plan(fp: Optional[str]) -> None:
    """Merge this process's recorded program hashes for ``fp`` into the
    persistent plan ledger (cross-process merge lock + atomic tmp+rename;
    shrink-never)."""
    if fp is None:
        return
    with _plan_lock:
        sigs = set(_PLAN_SIGS.get(fp, ()))
    if not sigs:
        return
    path = _plan_path(fp, create=True)
    if path is None:
        return
    try:
        with _merge_lock(path):
            existing = []
            if os.path.exists(path):
                with open(path, "r", encoding="utf-8") as f:
                    existing = json.load(f).get("sigs", [])
            merged = sorted(set(existing) | sigs)
            if merged == sorted(existing):
                return  # nothing new: skip the write entirely
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"v": _ENTRY_VERSION, "sigs": merged}, f)
            os.replace(tmp, path)
    except (OSError, ValueError) as e:
        # the ledger is an optimization; never fail a query over it
        from quokka_tpu import obs

        obs.diag(f"[compileplane] plan ledger write failed for {fp}: {e!r}")


def plan_sig_hashes(fp: str) -> List[str]:
    path = _plan_path(fp)
    if path is None or not os.path.exists(path):
        return []
    try:
        with open(path, "r", encoding="utf-8") as f:
            return list(json.load(f).get("sigs", []))
    except (OSError, ValueError):
        return []


# ---------------------------------------------------------------------------
# AOT programs
# ---------------------------------------------------------------------------


# Compiled.__call__'s argument-mismatch class: TypeError for aval/pytree
# drift, ValueError for input-sharding drift (virtual multi-device CPU
# places arrays jit would silently re-place; a compiled executable
# refuses).  Both degrade to the jit fallback, never an error.
_MISMATCH_ERRORS = (TypeError, ValueError)


class AotProgram:
    """A compiled executable with a build-on-demand jit fallback.  The
    fallback fires when the caller's avals/shardings drift from the
    compiled ones — the program keeps answering, one
    ``compile.aot_mismatch`` counter richer."""

    __slots__ = ("compiled", "_builder", "_fallback", "prewarmed", "_counted")

    def __init__(self, compiled, builder: Optional[Callable[[], object]] = None,
                 prewarmed: bool = False):
        self.compiled = compiled
        self._builder = builder
        self._fallback = None
        self.prewarmed = prewarmed
        self._counted = False

    def __call__(self, *args):
        if self.prewarmed and not self._counted:
            self._counted = True
            _count("prewarm_hit")
        c = self.compiled
        if c is not None:
            try:
                return c(*args)
            except _MISMATCH_ERRORS:
                # aval/sharding drift: drop to the jitted fallback for good
                _count("aot_mismatch")
                self.compiled = None
        fb = self._fallback
        if fb is None:
            if self._builder is None:
                raise AotMismatch(
                    "pre-warmed executable does not match this call's "
                    "shapes and no builder is attached")
            fb = self._fallback = self._builder()
        return fb(*args)


class AotMismatch(TypeError):
    """A prewarm-loaded executable saw different shapes; the call site
    rebuilds from its own builder."""


def _entry_path(key: Tuple, create: bool = False) -> Optional[str]:
    d = _aot_dir(create=create)
    return None if d is None else os.path.join(d, key_hash(key) + ".aot")


def _quarantine(path: str) -> None:
    from quokka_tpu import obs

    obs.REGISTRY.counter("compile.aot_corrupt").inc()
    with contextlib.suppress(OSError):
        os.replace(path, path + ".corrupt")


def _load_entry(path: str):
    """(key, callable) from a persisted executable, or None (quarantining
    the file) on any corruption/mismatch."""
    from jax.experimental.serialize_executable import deserialize_and_load

    try:
        with open(path, "rb") as f:
            data = f.read()
        payload = unframe(data, source=path)
        entry = pickle.loads(payload)
        if entry.get("v") != _ENTRY_VERSION:
            raise CorruptArtifactError(f"{path}: unknown entry version")
        compiled = deserialize_and_load(
            entry["exe"], entry["in_tree"], entry["out_tree"])
        from quokka_tpu.obs import memplane

        # a loaded executable is host residency for the process lifetime
        # (same token as the persist path: load-after-persist replaces)
        memplane.LEDGER.track(("aot", path), memplane.SITE_EXEC,
                              len(payload), device=memplane.HOST)
        return entry["key"], compiled
    except Exception:  # noqa: BLE001 — any load failure means "not cached"
        _quarantine(path)
        return None


# persistence runs on ONE background writer thread: serialization costs
# milliseconds and must never sit on the dispatch path
_write_q: "queue.Queue[Tuple[Tuple, object]]" = queue.Queue()
_writer_started = False
_writer_lock = threading.Lock()


def _writer_loop() -> None:
    while True:
        key, compiled = _write_q.get()
        try:
            _persist_now(key, compiled)
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            from quokka_tpu import obs

            obs.diag(f"[compileplane] persist of {key[0]} failed: {e!r}")
        finally:
            _write_q.task_done()


def _ensure_writer() -> None:
    global _writer_started
    with _writer_lock:
        if not _writer_started:
            t = threading.Thread(target=_writer_loop, daemon=True,
                                 name="qk-aot-writer")
            t.start()
            _writer_started = True


def _persist_now(key: Tuple, compiled) -> None:
    from jax.experimental.serialize_executable import (
        deserialize_and_load,
        serialize,
    )

    path = _entry_path(key, create=True)
    if path is None or os.path.exists(path):
        return
    exe, in_tree, out_tree = serialize(compiled)
    # verify the round trip BEFORE writing: an executable that was itself
    # loaded from the XLA persistent cache can serialize with its jitted
    # symbols unresolved ("Symbols not found" on deserialize) — persisting
    # that would poison every future restart with a quarantine cycle
    try:
        deserialize_and_load(exe, in_tree, out_tree)
    except Exception:  # noqa: BLE001 — any load failure means "don't ship"
        from quokka_tpu import obs

        obs.REGISTRY.counter("compile.aot_unserializable").inc()
        return
    payload = pickle.dumps({
        "v": _ENTRY_VERSION, "key": key, "exe": exe,
        "in_tree": in_tree, "out_tree": out_tree,
    })
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(frame(payload))
    os.replace(tmp, path)
    from quokka_tpu.obs import memplane

    memplane.LEDGER.track(("aot", path), memplane.SITE_EXEC, len(payload),
                          device=memplane.HOST)


def drain_writes(timeout: float = 10.0) -> None:
    """Block until queued persists hit disk (tests / warmup-smoke).  Waits
    on the queue's task accounting (``put`` increments, ``task_done``
    decrements under ``all_tasks_done``), so a ``put`` racing the writer's
    last ``task_done`` can never report drained early — the failure mode
    an emptiness-probe idle flag had."""
    import time

    deadline = time.monotonic() + timeout
    with _write_q.all_tasks_done:
        while _write_q.unfinished_tasks:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            _write_q.all_tasks_done.wait(remaining)


def acquire(key: Tuple, builder: Callable[[], object], args,
            lowerer: Optional[Callable[[], object]] = None) -> object:
    """Resolve a program cache miss: persisted executable if one exists
    (``compile.cache_hit``), else an explicit AOT compile of ``builder()``
    at ``args``'s shapes (``compile.miss``), persisted in the background.
    Always returns a callable and installs it in PROGRAMS; on any AOT
    failure the plain jitted builder result stands in.  ``lowerer``
    overrides how the jitted function lowers (kernels with trailing static
    args lower with them but are CALLED without)."""
    note_program(key, installed=True)
    path = _entry_path(key)
    if path is not None and os.path.exists(path):
        loaded = _load_entry(path)
        if loaded is not None:
            _count("cache_hit")
            # deliberately NO builder: the caller's builder lambda closes
            # over the triggering batch (device arrays, for fuse programs)
            # and PROGRAMS never evicts — retaining it would pin that
            # batch's memory for the process lifetime.  Aval/sharding
            # drift raises AotMismatch instead, and every dispatch site
            # rebuilds from its own CURRENT builder.
            prog = AotProgram(loaded[1])
            PROGRAMS[key] = prog
            from quokka_tpu.obs import devprof

            # replay the persisted static-cost sidecar (no re-analysis)
            devprof.load_cost(key, path)
            return prog
    _count("miss")
    fn = builder()
    prog: object = fn
    if _enabled():
        try:
            lowered = lowerer() if lowerer is not None else fn.lower(*args)
            compiled = lowered.compile()
            prog = AotProgram(compiled, builder=lambda: fn)
            from quokka_tpu.obs import devprof

            # static flops/bytes from the fresh executable, persisted in a
            # sidecar next to the AOT artifact under the same key
            devprof.record_cost(key, compiled,
                                _entry_path(key, create=True))
            _ensure_writer()
            _write_q.put((key, compiled))
        except Exception:  # noqa: BLE001 — AOT is an optimization layer:
            prog = fn      # the jitted callable is always a valid program
    PROGRAMS[key] = prog
    return prog


def aot_kernel_call(kind: str, jit_fn, args: Tuple, statics: Tuple = ()):
    """Dispatch a module-level jitted kernel through the compile plane.

    ``args`` are the traced (array) positional arguments; ``statics`` are
    TRAILING static positional arguments.  The program key derives from the
    canonical aval signature (ops/sigkey) + statics, so one ladder bucket =
    one program.  Inside an active trace the jitted function is called
    directly (it inlines); a compiled executable cannot trace.  Any aval
    drift falls back to the plain jit call — never an error."""
    from quokka_tpu.analysis import compat

    if not compat.trace_state_clean():
        return jit_fn(*args, *statics)
    key = sigkey.make_key(kind, sigkey.aval_sig(args), *statics)
    prog = PROGRAMS.get(key)
    if prog is not None:
        # in-memory hits still record under the current plan: a plan that
        # REUSES another plan's programs must prewarm the full set
        note_program(key)
    else:
        if statics:
            def builder():
                return lambda *a: jit_fn(*a, *statics)
        else:
            def builder():
                return jit_fn
        prog = acquire(key, builder, args,
                       lowerer=lambda: jit_fn.lower(*args, *statics))
    from quokka_tpu.obs import devprof

    devprof.on_dispatch(key)
    try:
        return prog(*args)
    except AotMismatch:
        PROGRAMS[key] = builder2 = (lambda *a: jit_fn(*a, *statics))
        return builder2(*args)


# ---------------------------------------------------------------------------
# pre-warm
# ---------------------------------------------------------------------------


def _install_hash(h: str) -> bool:
    """Load one persisted executable by hash and install it (prewarm).
    The hash is CLAIMED in the installed set before the expensive
    deserialize (and released on failure), so two replays racing over the
    same plan — e.g. the lowering-fired background thread and an explicit
    ``prewarm_all`` — never both pay the load."""
    with _plan_lock:
        if h in _INSTALLED_HASHES:
            return False
        _INSTALLED_HASHES.add(h)
    ok = False
    try:
        d = _aot_dir()
        if d is None:
            return False
        path = os.path.join(d, h + ".aot")
        if not os.path.exists(path):
            return False
        loaded = _load_entry(path)
        if loaded is None:
            return False
        key, compiled = loaded
        with _plan_lock:
            _HASH_BY_KEY[key] = h
            _KEY_BY_HASH[h] = key
        if key not in PROGRAMS:
            PROGRAMS[key] = AotProgram(compiled, prewarmed=True)
        from quokka_tpu.obs import devprof

        devprof.load_cost(key, path)
        ok = True
        return True
    finally:
        if not ok:
            with _plan_lock:
                _INSTALLED_HASHES.discard(h)


# plan fingerprints already replayed by THIS process: the per-lowering
# prewarm of a steadily re-submitted plan must cost a set lookup, never a
# ledger open/parse (the programs a replay would find are resident — either
# installed by the first replay or compiled by the first run's dispatches).
# _REPLAY_THREADS keeps the live thread per fp so a caller that needs a
# SYNCHRONOUS warm (QueryService.prewarm) can join an in-flight replay it
# didn't start instead of silently returning before the loads finish.
_REPLAYED_FPS: set = set()
_REPLAY_THREADS: Dict[str, threading.Thread] = {}


def prewarm_plan(fp: Optional[str], wait: bool = False,
                 timeout: float = 60.0) -> Optional[threading.Thread]:
    """Load every persisted executable the plan ledger records for ``fp``
    on a background thread (daemon — a dying process must not wait on
    warmup).  ``wait=True`` blocks until done (startup prewarm API).
    One replay per plan per process: a warm plan's re-lowering is a set
    lookup, not a ledger read — but while that one replay is still in
    flight, its thread is returned (and joined under ``wait``) so every
    caller synchronizes with the real work."""
    if fp is None or not _enabled():
        return None
    with _plan_lock:
        claimed = fp not in _REPLAYED_FPS
        if claimed:
            _REPLAYED_FPS.add(fp)
            installed = set(_INSTALLED_HASHES)
        else:
            t = _REPLAY_THREADS.get(fp)
            if t is not None and not t.is_alive():
                del _REPLAY_THREADS[fp]
                t = None
    if not claimed:
        # the one replay already happened (t None: done, plan is as warm
        # as it gets) or is still in flight: synchronize with it
        if t is not None and wait:
            t.join(timeout)
        return t
    hashes = [h for h in plan_sig_hashes(fp) if h not in installed]
    if not hashes:
        return None

    def _run() -> None:
        n = 0
        from quokka_tpu import obs

        for h in hashes:
            try:
                n += bool(_install_hash(h))
            except Exception as e:  # noqa: BLE001 — warmup never kills
                obs.diag(f"[compileplane] prewarm of {h} failed: {e!r}")
        t.installed = n  # read by prewarm_all after join
        if n:
            obs.REGISTRY.counter("compile.prewarm_loaded").inc(n)
            obs.RECORDER.record("compile.prewarm", fp, n=n)

    t = threading.Thread(target=_run, daemon=True, name="qk-prewarm")
    t.installed = 0
    with _plan_lock:
        _REPLAY_THREADS[fp] = t
    t.start()
    if wait:
        t.join(timeout)
    return t


def prewarm_all(wait: bool = True, timeout: float = 120.0) -> int:
    """Service-startup prewarm: replay EVERY recorded plan ledger.
    ``wait=True`` returns the number of plans that actually loaded >= 1
    persisted executable (a ledger whose artifacts are missing — foreign
    fingerprint, wiped store — contributes 0, so a cold start reports as
    one); ``wait=False`` can only report the number of plan warmups
    dispatched.  ``timeout`` bounds the WHOLE wait (one deadline shared
    across plan threads, not one timeout per plan)."""
    import time

    d = _plans_dir()
    if d is None or not os.path.isdir(d):
        return 0
    threads = []
    for name in sorted(os.listdir(d)):
        if name.endswith(".json"):
            t = prewarm_plan(name[:-5])
            if t is not None:
                threads.append(t)
    if not wait:
        return len(threads)
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
    return sum(1 for t in threads if getattr(t, "installed", 0))


def stats() -> Dict[str, int]:
    from quokka_tpu import obs

    snap = obs.REGISTRY.snapshot()
    return {k.split(".", 1)[1]: int(v) for k, v in snap.items()
            if k.startswith("compile.") and k.count(".") == 1}
