"""Shuffle data-plane smoke: the push path must be sync-free at steady state.

    python -m quokka_tpu.runtime.shuffle_smoke      (or: make shuffle-smoke)

A seeded Q3-shaped pipeline (fact join dim on an integer key, then a grouped
aggregate — two hash-shuffle exchanges) runs twice; the second, fully-warm
run must show

1. ZERO blocking host readbacks on the partition/push path (the
   ``shuffle.host_syncs`` counter the split kernels increment on every
   blocking counts readback stays flat), and
2. ZERO real backend compiles (the sanitizer's recompile sentinel,
   ``analysis/sanitize.check_no_recompiles`` with force=True), and
3. nonzero ``shuffle.bytes`` — proof the run actually exercised a fan-out
   exchange rather than trivially passing on an empty path.

Exit nonzero on any violation, with the counter deltas printed.
"""

from __future__ import annotations

import os
import sys
import tempfile


def _make_tables(tmp: str, seed: int = 20260804):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    r = np.random.default_rng(seed)
    n_fact, n_dim = 400_000, 40_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
        "flag": r.integers(0, 4, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "grp": r.integers(0, 64, n_dim).astype(np.int64),
    })
    fp, dp = os.path.join(tmp, "fact.parquet"), os.path.join(tmp, "dim.parquet")
    pq.write_table(fact, fp, row_group_size=1 << 17)
    pq.write_table(dim, dp)
    return fp, dp


def _query(ctx, fp, dp):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fp)
    dim = ctx.read_parquet(dp)
    return (
        fact.filter(col("flag") < 3)
        .join(dim, left_on="fk", right_on="pk")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def main() -> int:
    from quokka_tpu import QuokkaContext, obs
    from quokka_tpu.analysis import sanitize
    from quokka_tpu.utils import compilestats

    with tempfile.TemporaryDirectory(prefix="qk-shuffle-smoke-") as tmp:
        fp, dp = _make_tables(tmp)
        ctx = QuokkaContext(io_channels=2, exec_channels=2)
        warm = _query(ctx, fp, dp).collect()  # compiles + fills scan cache
        assert len(warm) > 0, "smoke query returned no rows"

        c0 = compilestats.snapshot()
        snap0 = obs.REGISTRY.snapshot()
        steady = _query(ctx, fp, dp).collect()
        c1 = compilestats.snapshot()
        snap1 = obs.REGISTRY.snapshot()

        assert warm.equals(steady), "steady-state run changed the result"
        syncs = snap1.get("shuffle.host_syncs", 0) - snap0.get(
            "shuffle.host_syncs", 0)
        sbytes = snap1.get("shuffle.bytes", 0) - snap0.get("shuffle.bytes", 0)
        print(f"shuffle-smoke: steady-state shuffle.bytes={sbytes} "
              f"host_syncs={syncs} real_compiles="
              f"{c1['real_compiles'] - c0['real_compiles']}")
        if sbytes <= 0:
            print("shuffle-smoke: FAIL — no shuffle volume recorded; the "
                  "pipeline did not exercise a fan-out exchange",
                  file=sys.stderr)
            return 1
        if syncs > 0:
            print(f"shuffle-smoke: FAIL — {syncs} blocking host readback(s) "
                  "on the steady-state push path (shuffle.host_syncs)",
                  file=sys.stderr)
            return 1
        # recompile sentinel: a warmed shuffle pipeline must reuse its
        # executables (raises RecompileError on violation)
        sanitize.check_no_recompiles(c0, c1, context="shuffle-smoke steady run",
                                     force=True)
    print("shuffle-smoke: OK — zero steady-state host syncs, zero recompiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
