"""Compile-plane smoke: a RESTARTED process must serve its first query warm.

    python -m quokka_tpu.runtime.warmup_smoke      (or: make warmup-smoke)

Two child processes share one fresh cache directory:

1. **populate** — runs a seeded Q3-shaped join+aggregate (the shuffle-smoke
   pipeline) cold: real compiles happen here, executables persist via the
   XLA compilation cache AND the AOT executable store, the plan ledger
   records the program set.
2. **fresh replica** — a brand-new process runs the same query against the
   populated cache and must show

   - ZERO real backend compiles (``real_compiles`` from
     utils/compilestats: every program answered from a persisted artifact),
   - the compile plane engaged (``compile.prewarm_hit`` +
     ``compile.cache_hit`` > 0 — the warm start came from the AOT store,
     not luck), and
   - a warmup wall no slower than the populate run (sanity).

Exit nonzero on any violation with both children's stats printed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def _child(data_dir: str) -> int:
    from quokka_tpu import QuokkaContext
    from quokka_tpu.runtime.shuffle_smoke import _make_tables, _query
    from quokka_tpu.utils import compilestats

    fp, dp = _make_tables(data_dir)
    ctx = QuokkaContext(io_channels=2, exec_channels=2)
    c0 = compilestats.snapshot()
    t0 = time.time()
    df = _query(ctx, fp, dp).collect()
    wall = time.time() - t0
    c1 = compilestats.snapshot()
    assert len(df) > 0, "warmup smoke query returned no rows"
    from quokka_tpu.runtime import compileplane

    compileplane.drain_writes()
    stats = compileplane.stats()
    # stdout IS the child protocol here (the parent parses this line);
    # not a diagnostic, so it bypasses obs.diag deliberately
    sys.stdout.write(json.dumps({
        "wall_s": round(wall, 3),
        "real_compiles": c1["real_compiles"] - c0["real_compiles"],
        "cache_hits": c1["cache_hits"] - c0["cache_hits"],
        "aot_cache_hit": stats.get("cache_hit", 0),
        "aot_miss": stats.get("miss", 0),
        "prewarm_hit": stats.get("prewarm_hit", 0),
        "prewarm_loaded": stats.get("prewarm_loaded", 0),
    }) + "\n")
    return 0


def _run_child(data_dir: str, cache_dir: str) -> dict:
    env = dict(os.environ)
    env["QUOKKA_JAX_CACHE_DIR"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, "-m", "quokka_tpu.runtime.warmup_smoke",
         "--child", data_dir],
        capture_output=True, text=True, timeout=600, env=env,
    )
    if r.returncode != 0:
        raise RuntimeError(
            f"warmup-smoke child rc={r.returncode}:\n{r.stderr[-2000:]}")
    line = [ln for ln in r.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    return json.loads(line)


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="qk-warmup-smoke-") as tmp:
        data_dir = os.path.join(tmp, "data")
        cache_dir = os.path.join(tmp, "cache")
        os.makedirs(data_dir)
        cold = _run_child(data_dir, cache_dir)
        warm = _run_child(data_dir, cache_dir)
        print(f"warmup-smoke: cold {cold}")
        print(f"warmup-smoke: fresh-replica {warm}")
        if warm["real_compiles"] != 0:
            print(
                f"warmup-smoke: FAIL — a fresh process against the "
                f"populated cache paid {warm['real_compiles']} real "
                "backend compile(s); cross-restart persistence broke "
                "(nondeterministic program construction, a cache-key "
                "drift, or a fingerprint mismatch)", file=sys.stderr)
            return 1
        if warm["prewarm_hit"] + warm["aot_cache_hit"] == 0:
            print(
                "warmup-smoke: FAIL — zero AOT prewarm/cache hits in the "
                "fresh replica: the warm start came from the XLA cache "
                "alone, the compile plane's executable store never "
                "engaged", file=sys.stderr)
            return 1
        if warm["wall_s"] > cold["wall_s"]:
            print(
                f"warmup-smoke: FAIL — the fresh replica's first query "
                f"({warm['wall_s']}s) was SLOWER than the cold populate "
                f"run ({cold['wall_s']}s) despite paying zero compiles: "
                "warmup work (prewarm loads, ledger reads) is landing on "
                "the dispatch critical path", file=sys.stderr)
            return 1
    print("warmup-smoke: OK — fresh replica started warm "
          f"(0 real compiles, {warm['prewarm_hit']} prewarm hits, "
          f"{warm['aot_cache_hit']} AOT loads, "
          f"wall {cold['wall_s']}s -> {warm['wall_s']}s)")
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        sys.exit(_child(sys.argv[2]))
    sys.exit(main())
