"""HBQ — host buffer queue: disk spill of post-partition outputs.

Reference parity: pyquokka/hbq.py:30-95.  Every object pushed to the data
plane is also written (post-partition) as an Arrow IPC file named by its
6-tuple object name, so a ReplayTask can re-push it after a failure without
recomputing the producer.  GC follows the cemetery table.

Namespacing (the query service): many concurrent queries may share one spill
directory.  An HBQ constructed with ``namespace=query_id`` prefixes its
filenames ``hbq-<ns>-...`` and only ever lists/serves/wipes its own
namespace, so co-resident queries cannot replay each other's spill.

Integrity: every spill file is checksum-framed (runtime/integrity.py) and
verified on read.  A truncated, bit-flipped or otherwise unreadable spill
is QUARANTINED (moved aside, counted, recorded) and ``get`` returns None —
corruption is treated as loss, so recovery falls through the normal chain
(cache -> live-peer HBQ -> input-lineage re-read / producer replay) instead
of crashing on ``pa.ArrowInvalid`` or, worse, feeding bad bytes back into
the replay protocol.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.ipc as ipc

from quokka_tpu.runtime import integrity
from quokka_tpu.runtime.errors import CorruptArtifactError

# namespaces embed in filenames between dash-separated integer fields: keep
# them unambiguous to parse (and filesystem-safe)
_NS_RE = re.compile(r"^[A-Za-z0-9_]+$")


class HBQ:
    def __init__(self, path: str, namespace: Optional[str] = None):
        if namespace is not None and not _NS_RE.match(namespace):
            raise ValueError(
                f"HBQ namespace {namespace!r} must be alphanumeric/underscore "
                "(it embeds in dash-separated spill filenames)"
            )
        self.path = path
        self.namespace = namespace
        os.makedirs(path, exist_ok=True)

    def _fname(self, name: Tuple) -> str:
        src_actor, src_ch, seq, tgt_actor, pfn, tgt_ch = name
        ns = f"{self.namespace}-" if self.namespace is not None else ""
        return (f"hbq-{ns}{src_actor}-{src_ch}-{seq}-{tgt_actor}-{pfn}-"
                f"{tgt_ch}.arrow")

    def put(self, name: Tuple, table: pa.Table) -> None:
        p = os.path.join(self.path, self._fname(name))

        def _write(sink):
            with ipc.new_file(sink, table.schema) as w:
                w.write_table(table)

        # framed + STREAMED (checksum accumulates as pyarrow writes — no
        # 3x-the-spill buffering) + atomic rename: readers never see
        # partial or torn spills, and anything the disk mangles later
        # fails the checksum on read
        integrity.write_framed_stream(p, _write, site="spill")
        # spill residency: logical table bytes (the figure the
        # shuffle.spill_bytes counter reports), host-class, retired on
        # gc/wipe/quarantine
        from quokka_tpu.obs import memplane

        memplane.LEDGER.track(("hbq", self.path, self._fname(name)),
                              memplane.SITE_SPILL, table.nbytes,
                              query=self.namespace, device=memplane.HOST)

    def get(self, name: Tuple) -> Optional[pa.Table]:
        p = os.path.join(self.path, self._fname(name))
        if not os.path.exists(p):
            return None
        try:
            payload = integrity.read_framed(p)
            with ipc.open_file(pa.BufferReader(payload)) as r:
                return r.read_all()
        except (CorruptArtifactError, pa.ArrowInvalid) as e:
            # corrupt spill == lost spill: quarantine it so the next
            # existence probe says gone, and let recovery regenerate the
            # object (live peer HBQ / input lineage / producer replay)
            integrity.quarantine(p, e)
            from quokka_tpu.obs import memplane

            memplane.LEDGER.retire(("hbq", self.path, self._fname(name)))
            return None
        except OSError as e:
            # transient read failure (EMFILE, EINTR, raced GC) proves
            # nothing about the BYTES — report loss for this attempt but
            # leave the (possibly healthy) file in place for the next one
            from quokka_tpu import obs

            obs.diag(f"[hbq] transient read failure on {p}: {e}")
            return None

    def contains(self, name: Tuple) -> bool:
        return os.path.exists(os.path.join(self.path, self._fname(name)))

    def _own_files(self):
        """(filename, parsed 6-tuple name) for every spill file in THIS
        namespace; foreign-namespace and malformed files are skipped."""
        ns = self.namespace
        for f in os.listdir(self.path):
            if not (f.startswith("hbq-") and f.endswith(".arrow")):
                continue
            parts = f[4:-6].split("-")
            if ns is None:
                if len(parts) != 6:
                    continue
            else:
                if len(parts) != 7 or parts[0] != ns:
                    continue
                parts = parts[1:]
            try:
                yield f, tuple(int(x) for x in parts)
            except ValueError:
                continue

    def names_for_target(self, tgt_actor: int, tgt_ch: int):
        """Spilled object names destined to one consumer channel — the
        enumeration a ReplayTask re-pushes after that consumer is rebuilt."""
        out = []
        for _f, name in self._own_files():
            if name[3] == tgt_actor and name[5] == tgt_ch:
                out.append(name)
        return sorted(out)

    def gc(self, names: Sequence[Tuple]) -> None:
        from quokka_tpu.obs import memplane

        for name in names:
            p = os.path.join(self.path, self._fname(name))
            if os.path.exists(p):
                os.remove(p)
                memplane.LEDGER.retire(("hbq", self.path,
                                        self._fname(name)))

    def wipe(self) -> None:
        """Drop this HBQ's spill.  A namespaced HBQ shares its directory
        with other queries, so only its own files go; an un-namespaced one
        owns the directory outright.  Prefix (not suffix) matching so
        quarantined ``.corrupt`` and stale ``.tmp`` leftovers of this
        namespace go too — a long-lived service would otherwise leak them
        into the shared spill dir forever."""
        from quokka_tpu.obs import memplane

        if self.namespace is None:
            shutil.rmtree(self.path, ignore_errors=True)
            os.makedirs(self.path, exist_ok=True)
            memplane.LEDGER.retire_prefix(("hbq", self.path))
            return
        prefix = f"hbq-{self.namespace}-"
        for f in os.listdir(self.path):
            if f.startswith(prefix):
                try:
                    os.remove(os.path.join(self.path, f))
                except OSError:
                    continue
                memplane.LEDGER.retire(("hbq", self.path, f))
