"""HBQ — host buffer queue: disk spill of post-partition outputs.

Reference parity: pyquokka/hbq.py:30-95.  Every object pushed to the data
plane is also written (post-partition) as an Arrow IPC file named by its
6-tuple object name, so a ReplayTask can re-push it after a failure without
recomputing the producer.  GC follows the cemetery table.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional, Sequence, Tuple

import pyarrow as pa
import pyarrow.ipc as ipc


def _fname(name: Tuple) -> str:
    src_actor, src_ch, seq, tgt_actor, pfn, tgt_ch = name
    return f"hbq-{src_actor}-{src_ch}-{seq}-{tgt_actor}-{pfn}-{tgt_ch}.arrow"


class HBQ:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def put(self, name: Tuple, table: pa.Table) -> None:
        p = os.path.join(self.path, _fname(name))
        with ipc.new_file(p + ".tmp", table.schema) as w:
            w.write_table(table)
        os.replace(p + ".tmp", p)  # atomic: readers never see partial spills

    def get(self, name: Tuple) -> Optional[pa.Table]:
        p = os.path.join(self.path, _fname(name))
        if not os.path.exists(p):
            return None
        with ipc.open_file(p) as r:
            return r.read_all()

    def contains(self, name: Tuple) -> bool:
        return os.path.exists(os.path.join(self.path, _fname(name)))

    def names_for_target(self, tgt_actor: int, tgt_ch: int):
        """Spilled object names destined to one consumer channel — the
        enumeration a ReplayTask re-pushes after that consumer is rebuilt."""
        out = []
        for f in os.listdir(self.path):
            if not (f.startswith("hbq-") and f.endswith(".arrow")):
                continue
            parts = f[4:-6].split("-")
            if len(parts) != 6:
                continue
            sa, sch, seq, ta, pfn, tch = (int(x) for x in parts)
            if ta == tgt_actor and tch == tgt_ch:
                out.append((sa, sch, seq, ta, pfn, tch))
        return sorted(out)

    def gc(self, names: Sequence[Tuple]) -> None:
        for name in names:
            p = os.path.join(self.path, _fname(name))
            if os.path.exists(p):
                os.remove(p)

    def wipe(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)
        os.makedirs(self.path, exist_ok=True)
