"""Served ControlStore: the multi-process control plane.

The coordinator serves the SAME embedded ControlStore the single-process
engine uses (runtime/tables.py keeps the reference's 17-table taxonomy,
pyquokka/tables.py); workers talk to it through ControlStoreClient, which
implements the identical method surface over runtime/rpc.py — so
runtime/engine.py's scheduling/recovery logic runs unchanged on either side.

Coordinator extras carried on the same connection:
- result_append / results: blocking-node outputs ship to the coordinator as
  Arrow IPC bytes (the reference's Dataset actor, quokka_dataset.py:7)
- heartbeat / heartbeats: worker liveness for failure detection
  (coordinator.py:131-205)
- control messages: per-worker mailboxes (channel adoption on recovery)
"""

from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, List, Optional, Tuple

from quokka_tpu.runtime.errors import TransientStoreError, retry_with_backoff
from quokka_tpu.runtime.rpc import RpcClient, RpcServer
from quokka_tpu.runtime.tables import ControlStore

# per-worker flight-recorder history retained coordinator-side: enough to
# reconstruct seconds-to-minutes of each worker's recent activity without
# unbounded growth over a long run
FLIGHT_KEEP_EVENTS = 4096


def _task_summary(task) -> Optional[str]:
    """Compact one-line rendering of a task's arguments for the in-flight
    pop record: enough to replay "what was it chewing on" from a stall
    dump without shipping the whole object.  Never raises."""
    try:
        kind = getattr(task, "name", "?")
        if kind == "input":
            tape = getattr(task, "tape", None) or []
            head = ",".join(str(s) for s in tape[:3])
            more = f"+{len(tape) - 3}" if len(tape) > 3 else ""
            return f"tape=[{head}{more}]"
        if kind in ("exec", "exectape"):
            reqs = getattr(task, "input_reqs", None) or {}
            req_s = ";".join(
                f"a{a}:{{{','.join(f'{c}>={s}' for c, s in sorted(chs.items()))}}}"
                for a, chs in sorted(reqs.items()))
            out = (f"state_seq={getattr(task, 'state_seq', '?')} "
                   f"out_seq={getattr(task, 'out_seq', '?')} reqs={req_s}")
            if kind == "exectape":
                out += f" tape_pos={getattr(task, 'tape_pos', '?')}"
            return out
        if kind == "replay":
            specs = getattr(task, "replay_specs", None) or []
            return f"replays={len(specs)}"
        return None
    except Exception:  # noqa: BLE001 — diagnostics must not break pops
        return None


class CoordinatorStore(ControlStore):
    """ControlStore + coordinator-side mailboxes, heartbeat state, flight
    streams and in-flight pop records (served by RpcServer)."""

    def __init__(self):
        super().__init__()
        self.results: Dict[Tuple[int, int, int], bytes] = {}  # (actor,ch,seq)
        self.heartbeats: Dict[int, float] = {}
        # worker -> last shipped WorkerState (runtime/state.py)
        self.worker_states: Dict[int, object] = {}
        # worker -> deque of flight-recorder event tuples (obs/recorder.py)
        self.flights: Dict[int, Deque[tuple]] = {}
        # worker -> (actor, channel, task_kind, popped_at, args_summary):
        # what each worker took most recently — recorded AT POP TIME on the
        # coordinator, so a dispatch that wedges before its next heartbeat
        # is still named, WITH the task's arguments (seq positions / input
        # requests) so the dump says what the wedged dispatch was chewing on
        self.inflight: Dict[
            int, Tuple[int, Optional[int], str, float, Optional[str]]] = {}
        self.mailboxes: Dict[int, List] = {}
        # flight-recorder seq at this run's start: run_distributed stamps it
        # so dumps/exports exclude the process-global ring's earlier runs
        self.obs_since: int = -1

    def stall_snapshot(self):
        """(heartbeats, worker_states, inflight, ntt_depth) copied under the
        store lock — the stall detector's one-call view of worker liveness
        (RPC handler threads mutate all four concurrently)."""
        with self._lock:
            return (
                dict(self.heartbeats),
                dict(self.worker_states),
                dict(self.inflight),
                {k: len(v) for k, v in self.tables["NTT"].items() if v},
            )

    def result_append(self, actor: int, channel: int, seq: int, ipc: bytes):
        with self._lock:
            self.results[(actor, channel, seq)] = ipc

    def heartbeat(self, worker_id: int, state=None):
        with self._lock:
            self.heartbeats[worker_id] = time.time()
            if state is not None:
                self.worker_states[worker_id] = state

    def flight_append(self, worker_id: int, events: List[tuple]):
        """Ingest a worker's incremental flight-recorder snapshot."""
        with self._lock:
            d = self.flights.get(worker_id)
            if d is None:
                d = self.flights[worker_id] = deque(maxlen=FLIGHT_KEEP_EVENTS)
            d.extend(tuple(e) for e in events)

    def flight_streams(self) -> Dict[str, List[tuple]]:
        with self._lock:
            return {f"worker-{w}": list(evs)
                    for w, evs in self.flights.items()}

    def ntt_pop(self, node, channels=None, worker=None):
        task = super().ntt_pop(node, channels)
        if task is not None and worker is not None:
            with self._lock:
                self.inflight[worker] = (
                    node, getattr(task, "channel", None), task.name,
                    time.time(), _task_summary(task))
        return task

    def mailbox_push(self, worker_id: int, msg):
        with self._lock:
            self.mailboxes.setdefault(worker_id, []).append(msg)

    def mailbox_drain(self, worker_id: int) -> List:
        with self._lock:
            out = self.mailboxes.get(worker_id, [])
            self.mailboxes[worker_id] = []
            return out


def serve_store(
    store: CoordinatorStore, host: str = "127.0.0.1", port: int = 0
) -> RpcServer:
    """port=0 picks an ephemeral port; multi-host deployments pass a fixed
    port so worker daemons can be launched with a known address."""
    return RpcServer(store, host=host, port=port)


class ControlStoreClient:
    """ControlStore interface over RPC.  Reads pass through immediately;
    transaction() batches WRITES and flushes them atomically on exit — safe
    under the engine's single-writer-per-channel discipline (each channel's
    rows are only written by the worker that owns it)."""

    _WRITES = {
        "set", "ntt_push", "tset", "tappend", "tdel", "sadd",
        "ntt_remove_exec", "ntt_remove_channel", "tape_trim", "tape_append",
        "result_append", "heartbeat", "mailbox_push", "flight_append",
    }

    # transient store failures (a flaky backend, chaos "store" injection)
    # are retried with bounded backoff.  Safe because a TransientStoreError
    # is raised BEFORE the request is applied (errors.py taxonomy); loss of
    # an in-flight request/response is handled one layer down by the RPC
    # client's same-request-id retry + server dedup.
    _STORE_ATTEMPTS = 5

    def __init__(self, address: Tuple[str, int]):
        self._rpc = RpcClient(address)
        self._txn: Optional[List] = None

    @contextmanager
    def transaction(self):
        if self._txn is not None:  # nested: join the outer batch
            yield self
            return
        self._txn = []
        try:
            yield self
        finally:
            calls, self._txn = self._txn, None
            if calls:
                self._retry(
                    "__multi__", lambda: self._rpc.call_multi(calls))

    def _retry(self, method: str, fn):
        from quokka_tpu import obs
        from quokka_tpu.chaos import CHAOS

        def attempt():
            if CHAOS.enabled:
                CHAOS.store_fault(method)  # may raise TransientStoreError
            return fn()

        def on_retry(n, e):
            obs.REGISTRY.counter("store.retry").inc()
            obs.RECORDER.record("store.retry", method, attempt=n,
                                error=repr(e)[:120])

        return retry_with_backoff(
            attempt, attempts=self._STORE_ATTEMPTS,
            retry_on=(TransientStoreError,), on_retry=on_retry)

    def _call(self, method: str, *args):
        if self._txn is not None and method in self._WRITES:
            self._txn.append((method, args))
            return None
        return self._retry(method, lambda: self._rpc.call(method, *args))

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def method(*args):
            return self._call(name, *args)

        return method

    def close(self):
        self._rpc.close()
