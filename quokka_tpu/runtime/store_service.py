"""Served ControlStore: the multi-process control plane.

The coordinator serves the SAME embedded ControlStore the single-process
engine uses (runtime/tables.py keeps the reference's 17-table taxonomy,
pyquokka/tables.py); workers talk to it through ControlStoreClient, which
implements the identical method surface over runtime/rpc.py — so
runtime/engine.py's scheduling/recovery logic runs unchanged on either side.

Coordinator extras carried on the same connection:
- result_append / results: blocking-node outputs ship to the coordinator as
  Arrow IPC bytes (the reference's Dataset actor, quokka_dataset.py:7)
- heartbeat / heartbeats: worker liveness for failure detection
  (coordinator.py:131-205)
- control messages: per-worker mailboxes (channel adoption on recovery)
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from quokka_tpu.runtime.rpc import RpcClient, RpcServer
from quokka_tpu.runtime.tables import ControlStore


class CoordinatorStore(ControlStore):
    """ControlStore + coordinator-side mailboxes (served by RpcServer)."""

    def __init__(self):
        super().__init__()
        self.results: Dict[Tuple[int, int, int], bytes] = {}  # (actor,ch,seq)
        self.heartbeats: Dict[int, float] = {}
        self.mailboxes: Dict[int, List] = {}

    def result_append(self, actor: int, channel: int, seq: int, ipc: bytes):
        with self._lock:
            self.results[(actor, channel, seq)] = ipc

    def heartbeat(self, worker_id: int):
        with self._lock:
            self.heartbeats[worker_id] = time.time()

    def mailbox_push(self, worker_id: int, msg):
        with self._lock:
            self.mailboxes.setdefault(worker_id, []).append(msg)

    def mailbox_drain(self, worker_id: int) -> List:
        with self._lock:
            out = self.mailboxes.get(worker_id, [])
            self.mailboxes[worker_id] = []
            return out


def serve_store(
    store: CoordinatorStore, host: str = "127.0.0.1", port: int = 0
) -> RpcServer:
    """port=0 picks an ephemeral port; multi-host deployments pass a fixed
    port so worker daemons can be launched with a known address."""
    return RpcServer(store, host=host, port=port)


class ControlStoreClient:
    """ControlStore interface over RPC.  Reads pass through immediately;
    transaction() batches WRITES and flushes them atomically on exit — safe
    under the engine's single-writer-per-channel discipline (each channel's
    rows are only written by the worker that owns it)."""

    _WRITES = {
        "set", "ntt_push", "tset", "tappend", "tdel", "sadd",
        "ntt_remove_exec", "ntt_remove_channel", "tape_trim",
        "result_append", "heartbeat", "mailbox_push",
    }

    def __init__(self, address: Tuple[str, int]):
        self._rpc = RpcClient(address)
        self._txn: Optional[List] = None

    @contextmanager
    def transaction(self):
        if self._txn is not None:  # nested: join the outer batch
            yield self
            return
        self._txn = []
        try:
            yield self
        finally:
            calls, self._txn = self._txn, None
            if calls:
                self._rpc.call_multi(calls)

    def _call(self, method: str, *args):
        if self._txn is not None and method in self._WRITES:
            self._txn.append((method, args))
            return None
        return self._rpc.call(method, *args)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def method(*args):
            return self._call(name, *args)

        return method

    def close(self):
        self._rpc.close()
