"""Checkpoint store: executor-state snapshots that survive worker loss.

The reference writes checkpoints to an S3 bucket (pyquokka/core.py:678-685)
precisely because a node's local disk dies with the node; only the HBQ spill
is node-local (hbq.py).  Same discipline here: checkpoints go to a root that
all workers can reach — a shared directory, or any fsspec URL (s3://, gs://)
via exec_config["checkpoint_store"].  Writes are atomic (tmp + rename) on
local paths so a reader never sees a torn snapshot.
"""

from __future__ import annotations

import os
from typing import Optional


class CheckpointStore:
    """``namespace`` (the query service): checkpoints of concurrent queries
    may share one root; namespaced snapshot names keep a query from ever
    restoring a neighbor's executor state."""

    def __init__(self, root: str, namespace: Optional[str] = None):
        self.root = root.rstrip("/")
        self.namespace = namespace
        self._remote = "://" in root
        if not self._remote:
            os.makedirs(root, exist_ok=True)

    def _path(self, actor: int, ch: int, state_seq: int) -> str:
        ns = f"{self.namespace}-" if self.namespace is not None else ""
        return f"{self.root}/ckpt-{ns}{actor}-{ch}-{state_seq}.pkl"

    def wipe_namespace(self) -> None:
        """Drop every snapshot in this namespace (query teardown) — local
        dirs and fsspec roots alike; best-effort (GC, not correctness)."""
        if self.namespace is None:
            return
        prefix = f"ckpt-{self.namespace}-"
        if self._remote:
            try:
                import fsspec

                fs, _, paths = fsspec.get_fs_token_paths(self.root)
                base = paths[0].rstrip("/")
                for p in fs.glob(f"{base}/{prefix}*.pkl"):
                    fs.rm(p)
            except Exception as e:  # noqa: BLE001 — GC must not fail a query
                from quokka_tpu import obs

                obs.diag(f"[ckptstore] namespace wipe of {self.root} "
                         f"failed: {e!r}")
            return
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for f in names:
            if f.startswith(prefix) and f.endswith(".pkl"):
                try:
                    os.remove(os.path.join(self.root, f))
                except OSError:
                    continue

    def save(self, actor: int, ch: int, state_seq: int, data: bytes) -> None:
        p = self._path(actor, ch, state_seq)
        if self._remote:
            import fsspec

            with fsspec.open(p, "wb") as f:
                f.write(data)
            return
        with open(p + ".tmp", "wb") as f:
            f.write(data)
        os.replace(p + ".tmp", p)

    def load(self, actor: int, ch: int, state_seq: int) -> Optional[bytes]:
        p = self._path(actor, ch, state_seq)
        if self._remote:
            import fsspec

            fs, _, paths = fsspec.get_fs_token_paths(p)
            if not fs.exists(paths[0]):
                return None
            with fsspec.open(p, "rb") as f:
                return f.read()
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()
