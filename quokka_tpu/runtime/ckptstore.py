"""Checkpoint store: executor-state snapshots that survive worker loss.

The reference writes checkpoints to an S3 bucket (pyquokka/core.py:678-685)
precisely because a node's local disk dies with the node; only the HBQ spill
is node-local (hbq.py).  Same discipline here: checkpoints go to a root that
all workers can reach — a shared directory, or any fsspec URL (s3://, gs://)
via exec_config["checkpoint_store"].

Durability discipline (the chaos plane hardened this):

- **atomic everywhere**: local saves are tmp + rename (as before); REMOTE
  saves now write a tmp key then move it into place (copy+delete when the
  backend has no rename), so a writer that dies mid-upload leaves a stale
  tmp key — never a partial object under the final name that ``load``
  would happily return.
- **checksum-framed** (runtime/integrity.py): every snapshot is verified
  on read AND re-read after a remote upload (length + checksum).  A frame
  mismatch on load raises ``CorruptArtifactError`` after quarantining the
  object; the engine treats that as LOSS and rewinds to an older
  checkpoint (engine.handle_exectape_task) instead of trusting the bytes.
"""

from __future__ import annotations

import os
import secrets
from typing import Optional

from quokka_tpu.runtime import integrity
from quokka_tpu.runtime.errors import CorruptArtifactError


class CheckpointStore:
    """``namespace`` (the query service): checkpoints of concurrent queries
    may share one root; namespaced snapshot names keep a query from ever
    restoring a neighbor's executor state."""

    def __init__(self, root: str, namespace: Optional[str] = None):
        self.root = root.rstrip("/")
        self.namespace = namespace
        self._remote = "://" in root
        if not self._remote:
            os.makedirs(root, exist_ok=True)

    def _fs(self):
        """(filesystem, base path) for a remote root — resolved per call:
        fsspec filesystems cache connections internally, and a store object
        crosses process boundaries via pickle in worker specs."""
        import fsspec

        fs, _, paths = fsspec.get_fs_token_paths(self.root)
        return fs, paths[0].rstrip("/")

    def _path(self, actor: int, ch: int, state_seq: int) -> str:
        ns = f"{self.namespace}-" if self.namespace is not None else ""
        return f"{self.root}/ckpt-{ns}{actor}-{ch}-{state_seq}.pkl"

    def wipe_namespace(self) -> None:
        """Drop every snapshot in this namespace (query teardown) — local
        dirs and fsspec roots alike; best-effort (GC, not correctness).
        Stale tmp keys from crashed writers go with it."""
        if self.namespace is None:
            return
        from quokka_tpu.obs import memplane

        memplane.LEDGER.retire_prefix(("ckpt", self.root, self.namespace))
        prefix = f"ckpt-{self.namespace}-"
        if self._remote:
            try:
                fs, base = self._fs()
                for p in fs.glob(f"{base}/{prefix}*.pkl*"):
                    fs.rm(p)
            except Exception as e:  # noqa: BLE001 — GC must not fail a query
                from quokka_tpu import obs

                obs.diag(f"[ckptstore] namespace wipe of {self.root} "
                         f"failed: {e!r}")
            return
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for f in names:
            if f.startswith(prefix):
                try:
                    os.remove(os.path.join(self.root, f))
                except OSError:
                    continue

    def _track(self, actor: int, ch: int, state_seq: int,
               nbytes: int) -> None:
        from quokka_tpu.obs import memplane

        memplane.LEDGER.track(
            ("ckpt", self.root, self.namespace, actor, ch, state_seq),
            memplane.SITE_CKPT, nbytes, query=self.namespace,
            device=memplane.HOST)

    def save(self, actor: int, ch: int, state_seq: int, data: bytes) -> None:
        p = self._path(actor, ch, state_seq)
        if not self._remote:
            integrity.write_framed_atomic(p, data, site="ckpt")
            self._track(actor, ch, state_seq, len(data))
            return
        framed = integrity.maybe_corrupt(integrity.frame(data), "ckpt")
        # remote: never write the final key directly — a crash mid-write
        # would leave a partial object that load() trusts.  Write a unique
        # tmp key, move it into place, then verify what actually landed.
        fs, base = self._fs()
        rel = p[len(self.root) + 1:]
        tmp = f"{base}/{rel}.tmp-{secrets.token_hex(4)}"
        final = f"{base}/{rel}"
        try:
            with fs.open(tmp, "wb") as f:
                f.write(framed)
            try:
                fs.mv(tmp, final)
            except (NotImplementedError, OSError):
                fs.copy(tmp, final)
                fs.rm(tmp)
        except BaseException:
            try:
                if fs.exists(tmp):
                    fs.rm(tmp)
            except OSError as e:
                from quokka_tpu import obs

                obs.diag(f"[ckptstore] tmp-key cleanup of {tmp} failed: {e!r}")
            raise
        # read-after-write verification against the bytes we UPLOADED
        # (object stores can and do surface torn/duplicated uploads).
        # Deliberately NOT unframe(): chaos-injected corruption simulates
        # at-rest damage that a real read-after-write would not see — it
        # must surface at LOAD time as quarantine-and-rewind, not crash the
        # checkpointing query here
        landed = fs.cat_file(final)
        if landed != framed:
            fs.rm(final)
            raise CorruptArtifactError(
                final, f"read-after-write mismatch (uploaded {len(framed)}B,"
                       f" landed {len(landed)}B) — torn upload removed")
        self._track(actor, ch, state_seq, len(data))

    def load(self, actor: int, ch: int, state_seq: int) -> Optional[bytes]:
        """Verified snapshot bytes, None when absent.  Raises
        ``CorruptArtifactError`` (after quarantining the object) when the
        snapshot exists but fails its integrity check — the caller must
        treat that as loss, never as data."""
        p = self._path(actor, ch, state_seq)
        if self._remote:
            fs, base = self._fs()
            final = f"{base}/{p[len(self.root) + 1:]}"
            if not fs.exists(final):
                return None
            data = fs.cat_file(final)
            try:
                return integrity.unframe(data, source=final)
            except CorruptArtifactError as e:
                self._quarantine_remote(fs, final, e)
                raise
        if not os.path.exists(p):
            return None
        try:
            return integrity.read_framed(p)
        except CorruptArtifactError as e:
            integrity.quarantine(p, e)
            raise
        except OSError:
            return None  # raced a wipe: same as absent

    def _quarantine_remote(self, fs, path: str, err: BaseException) -> None:
        from quokka_tpu import obs

        obs.REGISTRY.counter("integrity.corrupt").inc()
        obs.RECORDER.record("integrity.corrupt", path.rsplit("/", 1)[-1],
                            reason=str(err)[:200])
        obs.diag(f"[ckptstore] quarantining corrupt checkpoint {path}: {err}")
        try:
            fs.mv(path, path + ".corrupt")
        except Exception:  # noqa: BLE001 — quarantine is best-effort
            try:
                fs.rm(path)
            except Exception:  # noqa: BLE001
                obs.diag(f"[ckptstore] could not quarantine or remove "
                         f"{path}; recovery proceeds treating it as lost")
