"""Checkpoint store: executor-state snapshots that survive worker loss.

The reference writes checkpoints to an S3 bucket (pyquokka/core.py:678-685)
precisely because a node's local disk dies with the node; only the HBQ spill
is node-local (hbq.py).  Same discipline here: checkpoints go to a root that
all workers can reach — a shared directory, or any fsspec URL (s3://, gs://)
via exec_config["checkpoint_store"].  Writes are atomic (tmp + rename) on
local paths so a reader never sees a torn snapshot.
"""

from __future__ import annotations

import os
from typing import Optional


class CheckpointStore:
    def __init__(self, root: str):
        self.root = root.rstrip("/")
        self._remote = "://" in root
        if not self._remote:
            os.makedirs(root, exist_ok=True)

    def _path(self, actor: int, ch: int, state_seq: int) -> str:
        return f"{self.root}/ckpt-{actor}-{ch}-{state_seq}.pkl"

    def save(self, actor: int, ch: int, state_seq: int, data: bytes) -> None:
        p = self._path(actor, ch, state_seq)
        if self._remote:
            import fsspec

            with fsspec.open(p, "wb") as f:
                f.write(data)
            return
        with open(p + ".tmp", "wb") as f:
            f.write(data)
        os.replace(p + ".tmp", p)

    def load(self, actor: int, ch: int, state_seq: int) -> Optional[bytes]:
        p = self._path(actor, ch, state_seq)
        if self._remote:
            import fsspec

            fs, _, paths = fsspec.get_fs_token_paths(p)
            if not fs.exists(paths[0]):
                return None
            with fsspec.open(p, "rb") as f:
                return f.read()
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()
