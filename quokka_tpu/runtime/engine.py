"""The embedded push-based runtime: TaskGraph + TaskManager + Coordinator.

This single-process engine carries the reference's full runtime semantics —
push-based pipelined execution, per-actor channels, partitioned shuffles,
stage-gated build-before-probe scheduling, consumption-watermark backpressure
(pyquokka/core.py exec/IO loops, coordinator.py stage advancement,
quokka_runtime.py TaskGraph) — against the embedded ControlStore and an
in-memory device BatchCache.  Multi-host deployment replaces the store with a
served ControlStore and the cache with the gRPC data plane, without changing
this scheduling logic.

Key invariants preserved from the reference:
- outputs of each (actor, channel) carry contiguous seq numbers; consumers
  request contiguous runs per source channel (flight.py do_get semantics);
- a source is exhausted for a consumer when its channel is in DST and the
  consumer's next needed seq exceeds the source's last produced seq (LIT);
- input generation throttles to at most `max_pipeline` batches ahead of the
  slowest consumer (EWT watermark, core.py:919-925);
- executors at stage s never run before every actor at stages < s is done
  (coordinator.py:106-128).
"""

from __future__ import annotations

import copy
import os
import pickle
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from quokka_tpu import config
from quokka_tpu.expression import Expr
from quokka_tpu.ops import bridge, kernels
from quokka_tpu.ops.batch import DeviceBatch
from quokka_tpu.ops.expr_compile import evaluate_predicate
from quokka_tpu.runtime.cache import BatchCache
from quokka_tpu.runtime.dataset import ResultDataset
from quokka_tpu.runtime.errors import CorruptArtifactError
from quokka_tpu.runtime.tables import ControlStore
from quokka_tpu.runtime.task import (
    ExecutorTask,
    ReplayTask,
    TapedExecutorTask,
    TapedInputTask,
)
from quokka_tpu import obs
from quokka_tpu.obs import memplane, opstats
from quokka_tpu.obs import spans as tracing
from quokka_tpu.planner import adapt as adapt_mod
from quokka_tpu.target_info import (
    BroadcastPartitioner,
    FunctionPartitioner,
    HashPartitioner,
    PassThroughPartitioner,
    RangePartitioner,
    TargetInfo,
)


def new_query_id() -> str:
    """Fresh query namespace id: short, unique per process lifetime, and
    alphanumeric (it embeds in HBQ spill and checkpoint filenames)."""
    import uuid

    return "q" + uuid.uuid4().hex[:10]


class LostObjectError(RuntimeError):
    """A tape input that probed available vanished before the replay reached
    it (e.g. the peer serving its HBQ copy died mid-replay).  Retryable: the
    caller requeues the TapedExecutorTask and the next attempt rebuilds from
    the checkpoint."""

    def __init__(self, name):
        super().__init__(f"lost object {name} vanished during replay")
        self.name = name


class ActorInfo:
    def __init__(self, actor_id, kind, channels, stage=0, sorted_actor=False,
                 channel_major=False):
        self.id = actor_id
        self.kind = kind  # 'input' | 'exec'
        self.channels = channels
        self.stage = stage
        self.sorted_actor = sorted_actor
        self.channel_major = channel_major  # range-partitioned sort output
        self.reader = None
        self.executor_factory = None
        self.targets: Dict[int, TargetInfo] = {}  # tgt_actor -> TargetInfo
        self.source_streams: Dict[int, int] = {}  # src_actor -> stream_id
        self.blocking_dataset: Optional[ResultDataset] = None
        self.sorted_by: Optional[List[str]] = None
        self.predicate = None  # pushed-down source filter (device mask post-read)
        self.projection: Optional[List[str]] = None
        # runtime/placement.py strategy pinning channels to workers (None ->
        # round-robin spread, the reference default)
        self.placement = None
        # plan-independent scan identity (planner/cost.source_signature),
        # stamped by SourceNode.lower on input actors: keys this scan's
        # measured rows/bytes in the persisted cardprofile
        self.src_sig: Optional[str] = None


class TaskGraph:
    """Physical plan builder (quokka_runtime.py:18-392 equivalent).

    ``query_id`` namespaces everything the graph writes — control-store
    tables (through a NamespacedStore view), HBQ spill filenames, checkpoint
    names, metrics keys — so many graphs can share one long-lived store and
    spill dir (the query service).  ``store``/``cache``/``spill_dir`` let
    the service hand in its shared, already-warm instances; a graph built
    without them owns fresh ones, exactly as before."""

    def __init__(self, exec_config: Optional[dict] = None, *,
                 store: Optional[ControlStore] = None,
                 cache: Optional[BatchCache] = None,
                 query_id: Optional[str] = None,
                 spill_dir: Optional[str] = None):
        self.query_id = query_id
        self.root_store = store if store is not None else ControlStore()
        self.store = (
            self.root_store.namespace(query_id) if query_id is not None
            else self.root_store
        )
        self.cache = cache if cache is not None else BatchCache(owner=query_id)
        self.exec_config = dict(config.DEFAULT_EXEC_CONFIG)
        if exec_config:
            self.exec_config.update(exec_config)
        self.actors: Dict[int, ActorInfo] = {}
        self._next_actor = 0
        # adaptive-exchange eligibility (planner/decide.py, registered by
        # JoinNode.lower / FusedStageNode.lower): (build_src_actor,
        # join_actor) -> {"probe_src": actor}.  The engine's skew trigger
        # only ever fires on edges listed here.
        self.adapt_edges: Dict[Tuple[int, int], dict] = {}
        # folded maps (optimizer.fold_maps): batch_funcs to prepend on every
        # edge whose source is this actor
        self._pending_batch_fns: Dict[int, List[Callable]] = {}
        self.hbq = None
        self.ckpt_dir = None
        self._private_spill = False  # True -> this graph owns its spill dirs
        if self.exec_config.get("fault_tolerance"):
            from quokka_tpu.runtime.hbq import HBQ

            if spill_dir is not None and query_id is not None:
                # service mode: one SHARED spill dir; filename namespaces
                # keep concurrent queries' spill + checkpoints apart
                os.makedirs(spill_dir, exist_ok=True)
                self.hbq = HBQ(spill_dir, namespace=query_id)
                self.ckpt_dir = os.path.join(spill_dir, "ckpt")
                os.makedirs(self.ckpt_dir, exist_ok=True)
            else:
                import tempfile

                base = self.exec_config.get("hbq_path",
                                            "/tmp/quokka_tpu_spill/")
                os.makedirs(base, exist_ok=True)
                # unique per run: id()-style keys repeat across (and within)
                # processes and would replay another run's spill files
                self.hbq = HBQ(tempfile.mkdtemp(prefix="run-", dir=base),
                               namespace=query_id)
                self.ckpt_dir = tempfile.mkdtemp(prefix="ckpt-", dir=base)
                self._private_spill = True

    def cleanup(self, preserve_durable: bool = False) -> None:
        """``preserve_durable``: keep the on-disk recovery trio (HBQ spill,
        checkpoint snapshots, stream resume manifest) while still GC'ing
        every in-memory namespace.  Set by the service for a standing query
        torn down by failure/shutdown, whose stream a restarted replica will
        resume from the manifest."""
        import shutil

        if self.hbq is not None and not preserve_durable:
            self.hbq.wipe()  # namespaced: only this query's files go
            if self._private_spill:
                shutil.rmtree(self.hbq.path, ignore_errors=True)
        if self.ckpt_dir is not None and self._private_spill \
                and not preserve_durable:
            shutil.rmtree(self.ckpt_dir, ignore_errors=True)
            # un-namespaced checkpoints die with the dir; their ledger
            # entries go with them (wipe_namespace covers namespaced ones)
            memplane.LEDGER.retire_prefix(("ckpt", self.ckpt_dir))
        if self.query_id is not None and not preserve_durable:
            # GC this query's checkpoints from wherever they actually went:
            # exec_config["checkpoint_store"] (an external/shared root that
            # outlives the graph) wins over the spill-dir default — a
            # persistent service would otherwise leak one ckpt-<qid> set
            # per query into the external store forever
            ckpt_root = self.exec_config.get("checkpoint_store")
            if ckpt_root is None and not self._private_spill:
                ckpt_root = self.ckpt_dir  # private dirs died in the rmtree
            if ckpt_root is not None:
                from quokka_tpu.runtime.ckptstore import CheckpointStore

                CheckpointStore(ckpt_root,
                                namespace=self.query_id).wipe_namespace()
            # a cleanly finished query is complete: no resume.  Both
            # manifest kinds (standing-query stream manifest, durable-batch
            # resume manifest) only survive via preserve_durable above.
            import contextlib

            for attr in ("stream_manifest", "resume_manifest"):
                manifest = getattr(self, attr, None)
                if manifest:
                    with contextlib.suppress(OSError):
                        os.remove(manifest)
        if self.query_id is not None:
            # the one-shot path and the service both land here: a finished
            # query's tables, queues, metrics and cache accounting all GC
            self.snapshot_metrics()  # metrics() keeps answering post-GC
            self.root_store.drop_namespace(self.query_id)
            from quokka_tpu import obs
            from quokka_tpu.runtime import scancache

            scancache.GLOBAL.drop_query(self.query_id)
            # memory plane: whatever the cache still holds is freed by this
            # teardown (retire, not leak), the measured peak persists under
            # the plan fingerprint for admission, and anything STILL in the
            # ledger after that is a named leak report.  A durably-preserved
            # standing query keeps its spill entries (the files survive for
            # resume) and only drops the per-query accounting.
            self.cache.release_ledger()
            if preserve_durable:
                memplane.LEDGER.drop_query(self.query_id)
            else:
                memplane.LEDGER.on_query_gc(
                    self.query_id, plan_fp=getattr(self, "plan_fp", None))
            # progress plane: final snapshot stashed, fraction gauges GC'd
            # (idempotent — the service path already finalized in finish();
            # must run BEFORE opstats GC while its ledger view still exists)
            from quokka_tpu.obs import progress

            progress.TRACKER.on_query_gc(self.query_id)
            # operator-stats plane: final snapshot, measured cardinalities
            # persisted under the plan fingerprint, per-query gauges GC'd
            opstats.OPSTATS.on_query_gc(
                self.query_id, plan_fp=getattr(self, "plan_fp", None))
            obs.REGISTRY.remove(f"cache.plan_hit.{self.query_id}",
                                f"cache.plan_miss.{self.query_id}",
                                f"task.latency_s.{self.query_id}",
                                f"shuffle.bytes.{self.query_id}",
                                f"shuffle.host_syncs.{self.query_id}",
                                f"compile.cache_hit.{self.query_id}",
                                f"compile.miss.{self.query_id}",
                                f"compile.prewarm_hit.{self.query_id}",
                                f"stream.panes.{self.query_id}",
                                f"stream.late_dropped.{self.query_id}",
                                f"stream.watermark_lag_s.{self.query_id}",
                                f"mem.live_bytes.{self.query_id}",
                                f"mem.peak_bytes.{self.query_id}",
                                f"mem.spill_resident_bytes.{self.query_id}")
        # persist this query's program set under its plan fingerprint so the
        # NEXT submit of the same plan shape pre-warms from disk
        fp = getattr(self, "plan_fp", None)
        if fp is not None:
            from quokka_tpu.runtime import compileplane

            compileplane.flush_plan(fp)

    def _new_actor(self, kind, channels, stage, sorted_actor=False) -> ActorInfo:
        info = ActorInfo(self._next_actor, kind, channels, stage, sorted_actor)
        self.actors[self._next_actor] = info
        self._next_actor += 1
        return info

    def new_input_reader_node(
        self,
        reader,
        channels: int,
        stage: int = 0,
        sorted_by: Optional[List[str]] = None,
        predicate=None,
        projection: Optional[List[str]] = None,
    ) -> int:
        info = self._new_actor("input", channels, stage, sorted_actor=sorted_by is not None)
        info.reader = reader
        info.sorted_by = sorted_by
        if predicate is not None:
            from quokka_tpu.ops.fuse import FusedPredicate

            info.predicate = FusedPredicate(predicate)
        info.projection = projection
        tapes = reader.get_own_state(channels)
        for ch in range(channels):
            lineages = tapes.get(ch, [])
            for seq, lineage in enumerate(lineages):
                self.store.tset("LT", (info.id, ch, seq), lineage)
            self.store.tset("LIT", (info.id, ch), len(lineages) - 1)
            self.store.ntt_push(info.id, TapedInputTask(info.id, ch, list(range(len(lineages)))))
        if info.sorted_actor:
            self.store.sadd("SAT", info.id)
        self.store.tset("AST", info.id, stage)
        return info.id

    def new_exec_node(
        self,
        executor_factory: Callable[[], object],
        sources: Dict[int, Tuple[int, TargetInfo]],  # stream_id -> (src_actor, edge spec)
        channels: int,
        stage: int = 0,
        blocking: bool = False,
        sorted_actor: bool = False,
        channel_major: bool = False,
    ) -> int:
        # per-source routing state is keyed by src_actor, so two streams from
        # the SAME actor (direct self-join / self-union) would collide; give
        # each extra stream its own pass-through relay actor
        seen_srcs = set()
        deduped = {}
        for stream_id in sorted(sources):
            src_actor, tinfo = sources[stream_id]
            if src_actor in seen_srcs:
                src_actor = self._relay_actor(src_actor, stage)
            seen_srcs.add(src_actor)
            deduped[stream_id] = (src_actor, tinfo)
        sources = deduped
        info = self._new_actor("exec", channels, stage, sorted_actor)
        info.channel_major = channel_major
        info.executor_factory = executor_factory
        self.store.tset("AST", info.id, stage)
        if sorted_actor:
            self.store.sadd("SAT", info.id)
        if channel_major:
            self.store.sadd("CMT", info.id)
        if blocking:
            info.blocking_dataset = ResultDataset(f"ds-{info.id}")
        for stream_id, (src_actor, tinfo) in sources.items():
            src = self.actors[src_actor]
            pending = self._pending_batch_fns.get(src_actor)
            if pending:
                tinfo = copy.copy(tinfo)
                tinfo.batch_funcs = list(pending) + list(tinfo.batch_funcs)
            src.targets[info.id] = tinfo
            info.source_streams[src_actor] = stream_id
            self.store.tset("PFT", (src_actor, info.id), tinfo)
        for ch in range(channels):
            reqs = {}
            for stream_id, (src_actor, tinfo) in sources.items():
                src = self.actors[src_actor]
                reqs[src_actor] = {
                    sch: 0
                    for sch in range(src.channels)
                    if _feeds(tinfo.partitioner, sch, ch, channels)
                }
            # IRT at state 0: the recovery planner's starting point
            self.store.tset("IRT", (info.id, ch, 0), copy.deepcopy(reqs))
            self.store.ntt_push(info.id, ExecutorTask(info.id, ch, 0, 0, reqs))
        return info.id

    def add_pending_batch_fn(self, src_actor: int, fn: Callable) -> None:
        self._pending_batch_fns.setdefault(src_actor, []).append(fn)

    def _relay_actor(self, src_actor: int, stage: int) -> int:
        from quokka_tpu.executors.sql_execs import StorageExecutor
        from quokka_tpu.target_info import PassThroughPartitioner

        return self.new_exec_node(
            StorageExecutor,
            {0: (src_actor, TargetInfo(PassThroughPartitioner()))},
            self.actors[src_actor].channels,
            stage,
        )

    def run(self, max_batches: Optional[int] = None):
        try:
            Engine(self).run(max_batches=max_batches)
        finally:
            self.cleanup()

    def result(self, actor_id: int) -> ResultDataset:
        return self.actors[actor_id].blocking_dataset

    def metrics(self) -> Dict:
        """Per-(actor, channel) progress counters flushed by engines/workers:
        {(actor, ch): {"tasks": n, "rows": n, "bytes": n}}, plus a "compile"
        entry (utils/compilestats.snapshot()) proving kernel reuse — actor
        keys are tuples, subsystem keys are strings."""
        saved = getattr(self, "_saved_metrics", None)
        out, workers = self._store_metrics() if saved is None else saved
        from quokka_tpu.utils import compilestats

        # kernel-reuse proof: real_compiles flat across runs == no churn;
        # worker processes report their own counters via the flush channel
        out = dict(out)
        out["compile"] = compilestats.snapshot()
        if workers:
            out["compile"]["workers"] = workers
        return out

    def _store_metrics(self) -> Tuple[Dict, Dict]:
        """Aggregate the flushed per-worker snapshots from the store.
        Namespaced graphs flush under ``("metrics", query_id, worker)``,
        plain graphs under ``("metrics", worker)``."""
        out: Dict = {}
        workers: Dict = {}
        want = 2 if self.query_id is None else 3
        for key, snap in list(self.root_store.kv.items()):
            if not (isinstance(key, tuple) and len(key) == want
                    and key[0] == "metrics"):
                continue
            if self.query_id is not None and key[1] != self.query_id:
                continue
            for k, v in snap.items():
                if k == "__compile__":
                    if key[-1] != "embedded":  # embedded == this process
                        workers[key[-1]] = v
                    continue
                agg = out.setdefault(k, {"tasks": 0, "rows": 0, "bytes": 0})
                for f in agg:
                    agg[f] += v[f]
        return out, workers

    def snapshot_metrics(self) -> None:
        """Capture the flushed metrics before drop_namespace sweeps them
        (metrics() keeps answering after cleanup)."""
        self._saved_metrics = self._store_metrics()


def ckpt_candidates(store, a: int, ch: int) -> List[Tuple[int, int, int]]:
    """A channel's recovery-point history: the recorded checkpoint triples
    ``(state_seq, out_seq, tape_pos)`` plus the always-available ``(0,0,0)``
    (state 0 + full tape replay needs no snapshot).  The single source for
    every covering-checkpoint selection (plan_rewinds, corrupt-checkpoint
    fallback, forced producer rewind) — the covering rule is correctness-
    critical and must not fork."""
    return [(0, 0, 0)] + [
        tuple(h) for h in (store.tget("LT", ("ckpts", a, ch)) or [])
    ]


def plan_rewinds(store, dead_exec: List[Tuple[int, int]]) -> Dict[Tuple[int, int], Tuple[int, int, int]]:
    """Need-driven checkpoint selection for a set of simultaneously lost exec
    channels (the reference's rewind requests, coordinator.py:221-229,274-334).

    Default = each channel's latest checkpoint.  But when channel X's replay
    tape consumes an object produced by co-dead channel Y at an output seq
    BELOW Y's chosen checkpoint out_seq, no surviving copy of that object may
    exist (HBQ spill is producer-local and died with Y's worker) — Y must
    rewind to a checkpoint old enough to regenerate it.

    The same covering rule applies PAST the tape: once X's tape is exhausted
    its live execution resumes consuming at its post-replay input frontier
    (IRT at the chosen state, advanced through the tape slice).  A co-dead
    producer restored past that frontier leaves a seq gap no surviving copy
    fills — the consumer-side cache copies died with X's worker and the
    producer-side async spill died with Y's — so X's exec task spins on
    plan_get forever while the stall report blames the dead worker's stale
    heartbeat (the TestKill9Recovery wedge; reproduce with
    `python -m quokka_tpu.analysis.schedex`).  Covering the frontier too
    closes it: over-rewinding is idempotent (re-emissions are seq-keyed,
    consumers ignore seqs below their frontier) and a finished producer is
    never rewound past its end (its checkpoint out_seqs never exceed the
    frontier a consumer could still need).  Iterate to fixpoint; choices
    only move backward, bounded by (0, 0, 0), so this terminates."""
    dead = set(dead_exec)
    choice: Dict[Tuple[int, int], Tuple[int, int, int]] = {}
    for (a, ch) in dead:
        lct = store.tget("LCT", (a, ch))
        choice[(a, ch)] = tuple(lct) if lct is not None else (0, 0, 0)

    def _rewind_to_cover(key: Tuple[int, int], seq: int) -> bool:
        if choice[key][1] <= seq:
            return False  # producer's replay regenerates it
        hist = ckpt_candidates(store, *key)
        best = tuple(
            max((h for h in hist if h[1] <= seq), key=lambda h: h[0])
        )
        if best == choice[key]:
            return False
        choice[key] = best
        return True

    changed = True
    while changed:
        changed = False
        for (a, ch) in dead:
            state_seq, _out_seq, tape_pos = choice[(a, ch)]
            irt = store.tget("IRT", (a, ch, state_seq)) or {}
            frontier = {s: dict(c) for s, c in irt.items()}
            for ev in store.tape_slice(a, ch, tape_pos):
                if ev[0] != "exec":
                    continue
                for name in ev[2]:
                    key = (name[0], name[1])
                    seq = name[2]
                    chans = frontier.setdefault(name[0], {})
                    if chans.get(name[1], 0) <= seq:
                        chans[name[1]] = seq + 1
                    if key not in dead:
                        continue  # producer alive: its HBQ still serves it
                    if _rewind_to_cover(key, seq):
                        changed = True
            # live-phase needs: the first seq consumed after the tape ends
            # must also be regenerated by any co-dead producer
            for sa, chans in frontier.items():
                for sch, nxt in chans.items():
                    key = (sa, sch)
                    if key not in dead:
                        continue
                    if _rewind_to_cover(key, nxt):
                        changed = True
    return choice


def _feeds(partitioner, src_ch: int, tgt_ch: int, n_tgt: int) -> bool:
    if isinstance(partitioner, PassThroughPartitioner):
        return src_ch % n_tgt == tgt_ch
    return True  # hash/broadcast/range/function: every source channel


# ---------------------------------------------------------------------------

# Guards lazily-created per-engine state (emit pool, prefetch pool, metrics,
# service scheduling state) against double-init when the query service drives
# one Engine from several dispatch threads.  Module-level so the distributed
# Worker (which bypasses Engine.__init__) is covered too.  Reentrant:
# _service_prepare holds it across _warm_prefetch -> _ensure_prefetch_pool.
_LAZY_INIT_LOCK = threading.RLock()

# Per-dispatch observability note (thread-local: service pools dispatch one
# engine from many threads).  dispatch_task opens a dict, handlers annotate
# the task's causal identity through it (seqs consumed/produced), and the
# finished dict rides the task's flight-recorder event — what the
# critical-path profiler (obs/critpath.py) rebuilds the DAG from.
_OBS_NOTE = threading.local()


def _note(**kw) -> None:
    d = getattr(_OBS_NOTE, "d", None)
    if d is not None:
        d.update(kw)


def _note_out(seq: int) -> None:
    d = getattr(_OBS_NOTE, "d", None)
    if d is not None:
        d.setdefault("outs", []).append(seq)


class Engine:
    """TaskManager + Coordinator for the embedded runtime."""

    def __init__(self, graph: TaskGraph):
        self.g = graph
        self.store = graph.store
        self.cache = graph.cache
        self._init_latency_hists(graph)
        self.max_batches = graph.exec_config.get("max_pipeline_batches", 8)
        self.execs: Dict[Tuple[int, int], object] = {}
        self._partition_fns: Dict[Tuple[int, int], Callable] = {}
        # adaptive-exchange state (planner/adapt.py): the edge->record map
        # mirrors the durable ADT table (re-read on every recovery path);
        # the row histograms and last-pushed sequences feed the trigger
        self._adapt: Dict[Tuple[int, int], dict] = dict(
            self.store.titems("ADT"))
        self._adapt_rows: Dict[Tuple[int, int], Dict[int, int]] = {}
        self._push_seqs: Dict[Tuple[int, int], int] = {}
        for info in graph.actors.values():
            if info.kind == "exec":
                for ch in range(info.channels):
                    self.execs[(info.id, ch)] = self._bind_executor(
                        info.executor_factory())
        # upgrade the plan's exec labels to the bound executor class names
        # (register_plan already ran in _init_latency_hists); executors may
        # carry an OP_NAME override — a fused stage labels itself with its
        # member chain so opstats rows stay legible per logical operator
        opstats.OPSTATS.register_plan(
            graph, op_names={aid: getattr(ex, "OP_NAME", type(ex).__name__)
                             for (aid, ch), ex in self.execs.items()})

    def _bind_executor(self, executor):
        """Streaming executors resolve their pane/late counters (global +
        per-query twins) against the live registry here — after the
        per-channel factory copy, so instruments are never deep-copied and
        never ride a checkpoint."""
        if hasattr(executor, "bind_query"):
            executor.bind_query(getattr(self.g, "query_id", None))
        return executor

    # -- partition function lowering (quokka_runtime.py:215-312) ------------
    def _partition_fn(self, src_actor: int, tgt_actor: int) -> Callable:
        key = (src_actor, tgt_actor)
        if key in self._partition_fns:
            return self._partition_fns[key]
        tinfo: TargetInfo = self.store.tget("PFT", key)
        n_tgt = self.g.actors[tgt_actor].channels
        part = tinfo.partitioner

        fused_pred = None
        if tinfo.predicate is not None:
            from quokka_tpu.ops.fuse import FusedPredicate

            fused_pred = FusedPredicate(tinfo.predicate)

        range_state = None
        if isinstance(part, RangePartitioner):
            # boundaries land on device ONCE per edge, not once per batch
            # (the per-batch jnp.asarray upload used to sit on the push hot
            # path).  The device copy is built lazily on the first narrow-
            # column batch: wide (int64-limb) columns never upload — their
            # boundaries exceed int32 without x64 — and use the host ints.
            range_state = {"host": [int(b) for b in part.boundaries],
                           "dev": None}

        def fn(batch: DeviceBatch, src_ch: int,
               seq: int = 0) -> Dict[int, DeviceBatch]:
            if fused_pred is not None:
                batch = fused_pred(batch)
            for f in tinfo.batch_funcs:
                batch = f(batch)
                if batch is None:
                    return {}
            if isinstance(part, PassThroughPartitioner):
                out = {src_ch % n_tgt: batch}
            elif isinstance(part, BroadcastPartitioner):
                out = {ch: batch for ch in range(n_tgt)}
            elif isinstance(part, HashPartitioner):
                if n_tgt == 1:
                    out = {0: batch}
                else:
                    # mid-query adaptation (planner/adapt.py): an ADT
                    # record rewrites this edge's routing — salt the fat
                    # build partition from its recorded sequence on, or
                    # replicate the fat probe partition to every channel.
                    # Looked up per call: the record can appear mid-run.
                    ad = self._adapt_map().get(key)
                    pids = kernels.partition_ids(batch, part.keys, n_tgt)
                    if ad is not None and ad["mode"] == "replicate":
                        out = dict(enumerate(adapt_mod.replicate_parts(
                            batch, pids, ad["fat"], n_tgt)))
                    else:
                        if (ad is not None and ad["mode"] == "salt"
                                and seq >= ad["from_seq"].get(src_ch, 0)):
                            pids = adapt_mod.salt_pids(pids, ad["fat"],
                                                       n_tgt)
                        out = dict(enumerate(kernels.split_by_partition(
                            batch, pids, n_tgt)))
            elif isinstance(part, RangePartitioner):
                out = self._range_split(batch, part, n_tgt, range_state)
            elif isinstance(part, FunctionPartitioner):
                out = part.fn(batch, src_ch, n_tgt)
            else:
                raise NotImplementedError(type(part))
            if tinfo.projection is not None:
                out = {ch: b.select(list(tinfo.projection)) for ch, b in out.items()}
            return out

        self._partition_fns[key] = fn
        return fn

    def _range_split(self, batch, part: RangePartitioner, n_tgt: int,
                     range_state=None):
        import jax.numpy as jnp

        if range_state is None:  # direct callers (tests): uncached
            range_state = {"host": [int(b) for b in part.boundaries],
                           "dev": None}
        col = batch.columns[part.key]
        if getattr(col, "hi", None) is not None:
            from quokka_tpu.ops import timewide

            pids = timewide.limb_le_scalar_count(col, range_state["host"])
        else:
            if range_state["dev"] is None:
                range_state["dev"] = jnp.asarray(part.boundaries)
            pids = jnp.searchsorted(
                range_state["dev"], col.data, side="right").astype(jnp.int32)
        if part.descending:
            pids = (n_tgt - 1) - pids  # channel 0 owns the highest range
        return dict(enumerate(kernels.split_by_partition(batch, pids, n_tgt)))

    # -- adaptive exchanges (planner/adapt.py) -------------------------------
    def _adapt_map(self) -> Dict[Tuple[int, int], dict]:
        """Edge -> adaptation record.  Lazy because the distributed Worker
        bypasses Engine.__init__ (it never TRIGGERS adaptations, but its
        partition fns must honor records a coordinator run persisted)."""
        m = getattr(self, "_adapt", None)
        if m is None:
            m = self._adapt = {}
            self._adapt_refresh()
        return m

    def _adapt_refresh(self) -> None:
        """Re-read the durable ADT table into the local map — recovery
        paths call this so replayed pushes route exactly as the adapted
        run did (an engine-local map alone would forget records written
        before a simulated kill)."""
        m = self._adapt_map()
        try:
            m.update(dict(self.store.titems("ADT")))
        except Exception as e:  # a served store mid-failover: keep local
            # view; the next recovery path re-reads, so note, don't wedge
            obs.RECORDER.record("adapt", "refresh-deferred", err=repr(e))

    def _adapt_consider(self, edge: Tuple[int, int], src_channels: int,
                        n_tgt: int) -> None:
        """Evaluate the skew trigger for one eligible build edge; on fire,
        persist the (build, probe) ADT records BEFORE any batch ships under
        the new routing, then install them locally."""
        hist = self._adapt_rows.get(edge, {})
        fat = adapt_mod.skewed_channel(hist, n_tgt,
                                       opstats.skew_ratio_threshold())
        if fat is None:
            return
        src, tgt = edge
        probe = self.g.adapt_edges[edge]["probe_src"]
        probe_edge = (probe, tgt)
        # safety net on top of build-before-probe stage gating: replicating
        # the fat probe partition is only exactly-once if NO probe batch
        # shipped under the old routing
        if any(a == probe for (a, _ch) in self._push_seqs):
            del self.g.adapt_edges[edge]  # too late for this run
            return
        tinfo = self.store.tget("PFT", probe_edge)
        if tinfo is None or not isinstance(tinfo.partitioner,
                                           HashPartitioner):
            del self.g.adapt_edges[edge]
            return
        from_seq = {ch: self._push_seqs.get((src, ch), -1) + 1
                    for ch in range(src_channels)}
        build_rec, probe_rec = adapt_mod.build_records(fat, from_seq)
        with self.store.transaction():
            self.store.tset("ADT", edge, build_rec)
            self.store.tset("ADT", probe_edge, probe_rec)
        m = self._adapt_map()
        m[edge] = build_rec
        m[probe_edge] = probe_rec
        total = sum(hist.values())
        mean = total / max(n_tgt, 1)
        opstats.OPSTATS.note_adaptation(
            getattr(self.g, "query_id", None),
            {"kind": "adapt_runtime", "edge": f"a{src}->a{tgt}",
             "fat_channel": int(fat), "fat_rows": int(hist.get(fat, 0)),
             "mean_rows": round(mean), "total_rows": int(total),
             "ratio": round(hist.get(fat, 0) / mean, 2) if mean else None,
             "action": f"salt build partition {fat} across {n_tgt} "
                       f"channels, replicate probe partition {fat}"})
        obs.RECORDER.record("adapt", f"a{src}->a{tgt}", fat=int(fat),
                            total_rows=int(total))
        obs.REGISTRY.counter("adapt.fired").inc()

    # -- push (core.py:276-376) ---------------------------------------------
    def push(self, actor: int, channel: int, seq: int, batch: DeviceBatch) -> None:
        _note_out(seq)  # producer side of a critical-path data edge
        info = self.g.actors[actor]
        from quokka_tpu.runtime.cache import _batch_nbytes

        # streaming plane: persist the batch's watermark under its seq (SWM)
        # so recovery replay re-presents the same watermark trail, and stamp
        # every partition (splits build new DeviceBatch objects)
        stream_wm = getattr(batch, "_stream_wm", None)
        if stream_wm is not None:
            self.store.tset("SWM", (actor, channel, seq), stream_wm)
        # the sync scope carries this engine's once-resolved per-query
        # counter, so a split blocking inside the partition fn attributes to
        # THIS query even when neighbors dispatch concurrently
        adapt_edges = getattr(self.g, "adapt_edges", None) or {}
        with kernels.shuffle_sync_scope(self._shuffle_syncs_q):
            for tgt_actor in info.targets:
                fn = self._partition_fn(actor, tgt_actor)
                parts = fn(batch, channel, seq)
                if stream_wm is not None:
                    for part in parts.values():
                        part._stream_wm = stream_wm
                        part._stream_ch = channel
                if len(parts) > 1:
                    # shuffle volume: bytes entering a real exchange
                    # (fan-out > 1), counted once per edge from the parent
                    nb = _batch_nbytes(batch)
                    self._shuffle_bytes.inc(nb)
                    if self._shuffle_bytes_q is not None:
                        self._shuffle_bytes_q.inc(nb)
                # skew-trigger accounting, only while an eligible build
                # edge is still unadapted (and only on the embedded engine
                # — the distributed Worker lacks the serial-order guarantee
                # the trigger's determinism rides on)
                edge = (actor, tgt_actor)
                track = None
                if (edge in adapt_edges and config.adapt_enabled()
                        and hasattr(self, "_adapt_rows")
                        and edge not in self._adapt_map()):
                    track = self._adapt_rows.setdefault(edge, {})
                qid = getattr(self.g, "query_id", None)
                for tgt_ch, part in parts.items():
                    # delivered rows per (edge, target channel): the skew
                    # histogram.  Host count when known; else the part's
                    # async nrows_dev scalar (resolved at flush) — never a
                    # fresh device sync
                    opstats.OPSTATS.edge(
                        qid, actor, tgt_actor, tgt_ch,
                        part.nrows if part.nrows is not None
                        else part.nrows_dev)
                    if track is not None:
                        # the trigger's histogram may block on the tiny
                        # count scalar — a kernel-queue wait on an already-
                        # dispatched reduction, not a shuffle host sync
                        n = (part.nrows if part.nrows is not None
                             else int(part.nrows_dev)
                             if part.nrows_dev is not None else 0)
                        track[tgt_ch] = track.get(tgt_ch, 0) + int(n)
                    name = (actor, channel, seq, tgt_actor, actor, tgt_ch)
                    if self.g.hbq is not None:
                        # spill post-partition (core.py:311-313): replayable
                        # without recomputing the producer.  The d2h copy +
                        # checksummed write run on the background spill
                        # pool, overlapped with compute; recovery/checkpoint
                        # boundaries flush it (_flush_spills).
                        self._spill_submit(name, part)
                    self._cache_put(name, part)
                if track is not None:
                    self._push_seqs[(actor, channel)] = seq
                    self._adapt_consider(
                        edge, info.channels,
                        self.g.actors[tgt_actor].channels)
        if hasattr(self, "_push_seqs"):
            self._push_seqs[(actor, channel)] = seq

    # -- async HBQ spill ------------------------------------------------------
    # The HBQ write used to sit synchronously inside push: a full d2h sync +
    # framed disk write per partition per batch, serializing the producer
    # behind the disk.  It now runs on a bounded background pool; the
    # fault-tolerance contract is preserved by flush barriers at every point
    # recovery consults the spill (checkpoint record, failure simulation,
    # tape replay, object replay) and at engine teardown.  QK_SPILL_ASYNC=0
    # restores the synchronous path.

    def _spill_submit(self, name: Tuple, part: DeviceBatch) -> None:
        if not config.SPILL_ASYNC:
            self._spill_one(name, part)
            return
        pool = getattr(self, "_spill_pool", None)
        if pool is None:
            with _LAZY_INIT_LOCK:
                pool = getattr(self, "_spill_pool", None)
                if pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._spill_futs = []
                    self._spill_lock = threading.Lock()
                    pool = self._spill_pool = ThreadPoolExecutor(
                        max_workers=max(1, config.SPILL_POOL),
                        thread_name_prefix="quokka-spill",
                    )
        with self._spill_lock:
            self._spill_futs.append(pool.submit(self._spill_one, name, part))
        while True:
            with self._spill_lock:
                if len(self._spill_futs) <= config.SPILL_INFLIGHT:
                    break
                f = self._spill_futs.pop(0)
            f.result()  # bound device memory pinned by pending spills

    def _spill_one(self, name: Tuple, part: DeviceBatch) -> None:
        with tracing.span("spill.hbq"):
            # masked-view parts compact here (counts have landed by spill
            # time) so the d2h copy and the disk bytes stay proportional to
            # the partition, not the parent batch
            if part.padded_len > (1 << 16):
                part = kernels.compact(part)
            table = bridge.device_to_arrow(part)
            self.g.hbq.put(name, table)
        obs.REGISTRY.counter("shuffle.spill_bytes").inc(table.nbytes)

    def _flush_spills(self) -> None:
        futs = getattr(self, "_spill_futs", None)
        if futs:
            with self._spill_lock:
                futs, self._spill_futs = self._spill_futs, []
            for f in futs:
                f.result()  # propagate the first spill error loudly

    def _shutdown_spill(self) -> None:
        pool = getattr(self, "_spill_pool", None)
        if pool is not None:
            try:
                self._flush_spills()
            finally:
                self._spill_pool = None
                pool.shutdown(wait=True)

    def _cache_put(self, name: Tuple, part: DeviceBatch) -> None:
        """Deliver a partition to its consumer channel's cache.  The embedded
        engine has one cache; the distributed worker overrides this to route
        by the channel-location table (CLT) over the socket data plane."""
        self.cache.put(name, part)

    # -- input task (core.py:824-965) ----------------------------------------
    # Reader IO overlaps device compute: while the engine executes other
    # tasks, a one-slot background thread per input channel pre-reads the
    # NEXT lineage (VERDICT r1: the serial loop left IO, h2d and compute
    # strictly sequential).  reader.execute is pure per lineage, so the
    # prefetched table is byte-identical to a synchronous read — replay
    # determinism is unaffected.
    def _read_and_bridge(self, info, channel: int, lineage) -> DeviceBatch:
        """Read one lineage and land it on device: decode -> (projection) ->
        dictionary-encode/pack -> one device_put.  Runs on the prefetch
        threads so host decode + the h2d transfer overlap device compute
        (reader.execute is pure per lineage, so a prefetched batch is
        byte-identical to a synchronous read — replay determinism holds).

        Hot segments come from the device scan cache (buffer-pool role,
        runtime/scancache.py): a warm re-scan of an unchanged file skips
        decode, encode and the h2d transfer entirely."""
        from quokka_tpu.runtime import scancache

        ckey = None
        key_fn = getattr(info.reader, "cache_key", None)
        if key_fn is not None and scancache.GLOBAL.enabled:
            base = key_fn(channel, lineage)
            if base is not None:
                ckey = (
                    base,
                    tuple(info.projection or ()),
                    tuple(info.sorted_by or ()),
                    config.x64_enabled(),  # dtype regime changes device layout
                )
                cached = scancache.GLOBAL.get(
                    ckey, query=getattr(self.g, "query_id", None))
                if cached is not None:
                    return cached
        with tracing.span("reader.execute"):
            table = info.reader.execute(channel, lineage)
        if info.projection is not None:
            keep = [c for c in info.projection if c in table.column_names]
            table = table.select(keep)
        with tracing.span("bridge.to_device"):
            # an h2d transfer is where HBM exhaustion actually surfaces:
            # capture the ledger state in a forensics bundle before the
            # allocator error propagates
            with memplane.alloc_guard(memplane.SITE_READER):
                batch = bridge.arrow_to_device(table,
                                               sorted_by=info.sorted_by)
        if ckey is not None:
            scancache.GLOBAL.put(ckey, batch)
        return batch

    def _ensure_prefetch_pool(self):
        if getattr(self, "_prefetch", None) is None:
            with _LAZY_INIT_LOCK:
                if getattr(self, "_prefetch", None) is None:
                    import concurrent.futures

                    self._prefetch_pool = (
                        concurrent.futures.ThreadPoolExecutor(
                            max_workers=self._io_threads(),
                            thread_name_prefix="quokka-io"))
                    self._prefetch = {}
        return self._prefetch

    def _take_prefetched(self, info, task, seq):
        pf = self._ensure_prefetch_pool()
        key = (task.actor, task.channel)
        fut = pf.pop(key, None)
        batch = None
        if fut is not None:
            want, f = fut
            if want == seq:
                with tracing.span("prefetch.wait"):
                    batch = f.result()
            else:
                f.cancel()
        if batch is None:
            lineage = self.store.tget("LT", (task.actor, task.channel, seq))
            batch = self._read_and_bridge(info, task.channel, lineage)
        # schedule the next seq while this batch computes
        nxt = task.peek_next_seq() if hasattr(task, "peek_next_seq") else None
        if nxt is not None:
            lineage_n = self.store.tget("LT", (task.actor, task.channel, nxt))
            if lineage_n is not None:
                pf[key] = (
                    nxt,
                    self._prefetch_pool.submit(
                        self._read_and_bridge, info, task.channel, lineage_n
                    ),
                )
        return batch

    def handle_input_task(self, task: TapedInputTask) -> bool:
        info = self.g.actors[task.actor]
        seq = task.current_seq()
        if seq is None:
            # unbounded sources never exhaust their tape: poll for appended
            # segments until a stop flag turns the channel finite
            streamed = self._stream_advance(info, task)
            if streamed is not None:
                return streamed
            self.store.sadd("DST", (task.actor, task.channel), "done")
            return True
        if self._throttled(info, task.channel, seq):
            self.store.ntt_push(task.actor, task)
            return False
        batch = self._take_prefetched(info, task, seq)
        rows_raw = self._rows_of(batch)  # pre-predicate: what the reader read
        if info.predicate is not None:
            with tracing.span("source.predicate"):
                batch = info.predicate(batch)
        if getattr(info.reader, "UNBOUNDED", False):
            batch = self._stamp_input_wm(info, task.actor, task.channel,
                                         seq, batch)
        with tracing.span("push.input"):
            self.push(task.actor, task.channel, seq, batch)
        from quokka_tpu.runtime.cache import _batch_nbytes

        # counters use the host-known row count only: count_valid() would add
        # a device sync per batch when a source predicate filtered device-side
        rows = batch.nrows if batch.nrows is not None else 0
        self._metric(task.actor, task.channel, rows, _batch_nbytes(batch))
        opstats.OPSTATS.scan(
            getattr(self.g, "query_id", None), task.actor, task.channel,
            rows_raw, self._rows_of(batch), _batch_nbytes(batch),
            batch.padded_len)
        with self.store.transaction():
            self.store.sadd("GIT", (task.actor, task.channel), seq)
        nxt = task.advance()
        if nxt.tape:
            self.store.ntt_push(task.actor, nxt)
        elif (getattr(info.reader, "UNBOUNDED", False)
              and not self.store.tget("SST", task.actor)):
            # exhausted tape on an un-stopped standing source: requeue so
            # the next dispatch polls for appended segments
            self.store.ntt_push(task.actor, nxt)
        else:
            self.store.sadd("DST", (task.actor, task.channel), "done")
        return True

    def _throttled(self, info: ActorInfo, src_ch: int, seq: int) -> bool:
        max_pipeline = self.g.exec_config["max_pipeline"]
        if not info.targets:
            return False
        if not self.cache.puttable():
            return True
        watermark = None
        for tgt_actor, tinfo in info.targets.items():
            tgt = self.g.actors[tgt_actor]
            for tgt_ch in range(tgt.channels):
                if not _feeds(tinfo.partitioner, src_ch, tgt_ch, tgt.channels):
                    continue
                w = self.store.tget("EWT", (info.id, src_ch, tgt_actor, tgt_ch), -1)
                watermark = w if watermark is None else min(watermark, w)
        return watermark is not None and seq > watermark + max_pipeline

    # -- streaming plane (quokka_tpu/streaming/) ------------------------------
    # An input actor whose reader declares UNBOUNDED never finishes on its
    # own: when its tape runs dry the engine polls the reader for appended
    # segments (recording each discovery in the control store, so recovery
    # and the resume manifest see the same frozen lineage) until a stop flag
    # (SST, set by StreamingHandle.stop) turns the channel finite and the
    # normal end-of-input finalization drains every open pane.

    def _stream_advance(self, info: ActorInfo, task: TapedInputTask):
        """Returns None (not streaming / stopped -> finite end-of-input),
        True (new segments discovered and queued: progress), or False
        (nothing new: requeued, idle)."""
        reader = info.reader
        if info.kind != "input" or not getattr(reader, "UNBOUNDED", False):
            return None
        a, ch = task.actor, task.channel
        if self.store.tget("SST", a):
            return None
        polls = getattr(self, "_stream_poll_at", None)
        if polls is None:
            with _LAZY_INIT_LOCK:
                polls = getattr(self, "_stream_poll_at", None)
                if polls is None:
                    polls = self._stream_poll_at = {}
        now = time.time()
        if now - polls.get((a, ch), 0.0) < config.STREAM_POLL_S:
            self.store.ntt_push(a, task)
            return False
        polls[(a, ch)] = now
        new = reader.poll(ch)  # StreamTruncatedError propagates LOUDLY
        if not new:
            self._stream_lag_update(a, ch, advanced=False)
            self.store.ntt_push(a, task)
            return False
        last = self.store.tget("LIT", (a, ch), -1)
        with self.store.transaction():
            for i, lineage in enumerate(new):
                self.store.tset("LT", (a, ch, last + 1 + i), lineage)
            self.store.tset("LIT", (a, ch), last + len(new))
        self.store.ntt_push(
            a, TapedInputTask(a, ch,
                              list(range(last + 1, last + 1 + len(new)))))
        obs.RECORDER.record("stream.segments", f"a{a}c{ch}", a=a, c=ch,
                            n=len(new), **(
                                {"q": self.g.query_id}
                                if getattr(self.g, "query_id", None) else {}))
        return True

    def _stamp_input_wm(self, info: ActorInfo, a: int, ch: int, seq: int,
                        batch: DeviceBatch) -> DeviceBatch:
        """Attach the channel's event-time watermark to an unbounded
        source's batch.  Derived host-side from the lineage's recorded max
        event time (never a device sync), persisted per seq (SWM) so
        recovery replay re-presents the identical watermark sequence, and
        monotone per channel (SWMC high-water)."""
        wm = self.store.tget("SWM", (a, ch, seq))
        if wm is None:
            lineage = self.store.tget("LT", (a, ch, seq))
            delay = float(getattr(info.reader, "watermark_delay", 0.0))
            wm = float(info.reader.lineage_time_max(lineage)) - delay
            prev = self.store.tget("SWMC", (a, ch))
            if prev is not None:
                wm = max(wm, prev)
            with self.store.transaction():
                self.store.tset("SWM", (a, ch, seq), wm)
                self.store.tset("SWMC", (a, ch), wm)
            self._stream_lag_update(a, ch, advanced=True)
        batch._stream_wm = wm
        batch._stream_ch = ch
        return batch

    def _stream_lag_update(self, a: int, ch: int, advanced: bool) -> None:
        """stream.watermark_lag_s gauge: wall seconds since the source
        watermark last ADVANCED (0 while it moves) — the standing query's
        staleness signal.  Instruments resolved once per engine, same
        no-resurrection discipline as the latency histograms."""
        gauges = getattr(self, "_stream_lag_gauges", None)
        if gauges is None:
            with _LAZY_INIT_LOCK:
                gauges = getattr(self, "_stream_lag_gauges", None)
                if gauges is None:
                    qid = getattr(self.g, "query_id", None)
                    insts = [obs.REGISTRY.gauge("stream.watermark_lag_s")]
                    if qid is not None:
                        insts.append(obs.REGISTRY.gauge(
                            f"stream.watermark_lag_s.{qid}"))
                    self._stream_wm_advanced_at = {}
                    gauges = self._stream_lag_gauges = insts
        now = time.time()
        if advanced or (a, ch) not in self._stream_wm_advanced_at:
            self._stream_wm_advanced_at[(a, ch)] = now
        lag = now - min(self._stream_wm_advanced_at.values())
        for g in gauges:
            g.set(lag)

    def _stamp_exec_wm(self, executor, out, channel: int) -> None:
        """Streaming executors' emissions carry the operator watermark so
        chained streaming stages clock off their upstream."""
        if out is None:
            return
        fn = getattr(executor, "current_watermark", None)
        if fn is None:
            return
        wm = fn(channel)
        if wm is not None and wm != float("-inf"):
            out._stream_wm = wm
            out._stream_ch = channel

    def _attach_stream_wm(self, name: Tuple, b):
        """Replay/recovery resolution path: re-attach the watermark recorded
        for this object's producing seq (batch attrs do not survive the
        arrow round trip through the HBQ spill)."""
        if b is None:
            return b
        wm = self.store.tget("SWM", (name[0], name[1], name[2]))
        if wm is not None:
            b._stream_wm = wm
            b._stream_ch = name[1]
        return b

    # -- exec task (core.py:484-700) -----------------------------------------
    def handle_exec_task(self, task: ExecutorTask) -> bool:
        info = self.g.actors[task.actor]
        executor = self.execs[(task.actor, task.channel)]
        qid = getattr(self.g, "query_id", None)
        # prune exhausted sources against DST/LIT; notify the executor so
        # multi-stream operators can finalize a side (build completion)
        out_seq = task.out_seq
        for src in list(task.input_reqs):
            chans = task.input_reqs[src]
            for ch in list(chans):
                if self.store.scontains("DST", (src, ch), "done"):
                    last = self.store.tget("LIT", (src, ch), -1)
                    if chans[ch] > last:
                        del chans[ch]
            if not chans:
                del task.input_reqs[src]
                with opstats.OPSTATS.current_op(qid, task.actor,
                                                task.channel):
                    extra = executor.source_done(
                        info.source_streams[src], task.channel)
                # emit decisions never inspect device data (a live-row count is
                # a full host round trip); empty batches flow and are harmless
                emitted = extra is not None
                if emitted:
                    self._stamp_exec_wm(executor, extra, task.channel)
                    self._emit(info, task.channel, out_seq, extra)
                    self._metric(task.actor, task.channel, self._rows_of(extra), 0)
                    opstats.OPSTATS.exec_out(qid, task.actor, task.channel,
                                             self._rows_of(extra))
                    out_seq += 1
                self._tape(task.actor, task.channel,
                           ("srcdone", info.source_streams[src], emitted))
        task.out_seq = out_seq
        if not task.input_reqs:
            with tracing.span(f"done.{type(executor).__name__}"), \
                    opstats.OPSTATS.current_op(qid, task.actor, task.channel):
                out = executor.done(task.channel)
            # spill-tier executors (external sort, grace join) emit their
            # result as a lazy SEQUENCE of bounded batches — a generator keeps
            # only one merged batch on device at a time
            if out is None or isinstance(out, DeviceBatch):
                outs = [out]
            else:
                outs = out  # list or generator
            for o in outs:
                if o is not None:
                    self._stamp_exec_wm(executor, o, task.channel)
                    self._emit(info, task.channel, out_seq, o)
                    self._metric(task.actor, task.channel, self._rows_of(o), 0)
                    opstats.OPSTATS.exec_out(qid, task.actor, task.channel,
                                             self._rows_of(o))
                    out_seq += 1
            # all sink emissions must land before DST says done: a consumer
            # (collect, coordinator result read) may act on "done" immediately
            self._flush_emits()
            with self.store.transaction():
                self.store.tset("LIT", (task.actor, task.channel), out_seq - 1)
                self.store.sadd("DST", (task.actor, task.channel), "done")
            return True
        plan = self.cache.plan_get(
            task.actor,
            task.channel,
            task.input_reqs,
            self._actor_stages(),
            self._sorted_actors(),
            # a fused stage amortizes its whole member chain over one
            # dispatch — let it drain a wider slice of the ready queue than
            # the per-operator default (still deterministic: the cap is a
            # static executor attribute, so tape replay sees the same sets)
            max_batches=getattr(executor, "MAX_PIPELINE_BATCHES", None)
            or self.max_batches,
            channel_major=self._channel_major_actors(),
        )
        if plan is None:
            self.store.ntt_push(task.actor, task)
            return False
        src_actor, names = plan
        # consumer side of the critical-path data edges: which (channel,
        # seq) batches of src_actor this dispatch consumed
        _note(src=src_actor, **{"in": [[n[1], n[2]] for n in names]})
        batches = [self.cache.get(n) for n in names]
        stream_id = info.source_streams[src_actor]
        opstats.OPSTATS.exec_in(qid, task.actor, task.channel, batches)
        with tracing.span(f"exec.{type(executor).__name__}"), \
                opstats.OPSTATS.current_op(qid, task.actor, task.channel):
            out = executor.execute(batches, stream_id, task.channel)
        out_seq = task.out_seq
        emitted = out is not None
        if emitted:
            self._stamp_exec_wm(executor, out, task.channel)
            with tracing.span("push.exec"):
                self._emit(info, task.channel, out_seq, out)
            out_seq += 1
        self._metric(task.actor, task.channel, self._rows_of(out), 0)
        opstats.OPSTATS.exec_out(qid, task.actor, task.channel,
                                 self._rows_of(out))
        self._tape(task.actor, task.channel, ("exec", src_actor, tuple(names), emitted))
        consumed: Dict[int, Dict[int, int]] = {src_actor: {}}
        for (sa, sch, seq, *_rest) in names:
            consumed[sa][sch] = max(consumed[sa].get(sch, 0), seq + 1)
        with self.store.transaction():
            for sch, nxt in consumed[src_actor].items():
                self.store.tset("EWT", (src_actor, sch, task.actor, task.channel), nxt - 1)
        self.cache.gc(names)
        new_task = task.advance(consumed, out_seq)
        interval = self.g.exec_config.get("checkpoint_interval")
        if interval and self.g.ckpt_dir is not None and new_task.state_seq % interval == 0:
            self._checkpoint(executor, new_task)
        self.store.ntt_push(task.actor, new_task)
        return True

    # -- metrics --------------------------------------------------------------
    # typed per-channel accounting lives in obs/metrics.py (EngineMetrics);
    # the flush cadence and the ("metrics", worker_id) store contract are
    # unchanged from the inline dict this replaced
    _METRICS_FLUSH_EVERY = 64

    def _metrics_guard(self):
        """Per-ENGINE lock for the EngineMetrics read-modify-write (the
        query service dispatches one engine's tasks from several threads).
        Per-engine so concurrent queries never contend on each other's
        counters; the global lock only guards the lazy creation."""
        lock = getattr(self, "_metrics_lock", None)
        if lock is None:
            with _LAZY_INIT_LOCK:
                lock = getattr(self, "_metrics_lock", None)
                if lock is None:
                    lock = self._metrics_lock = threading.Lock()
        return lock

    def _metric(self, actor: int, channel: int, rows, nbytes: int) -> None:
        """rows: an int, or a device count scalar (resolved lazily at flush
        time, when its async host copy has long landed — emit paths must not
        block on a device round trip for a counter)."""
        with self._metrics_guard():
            m = getattr(self, "_metrics", None)
            if m is None:
                m = self._metrics = obs.EngineMetrics()
            m.task(actor, channel, rows, nbytes)
            dirty = m.dirty >= self._METRICS_FLUSH_EVERY
        if dirty:
            self._flush_metrics()

    def _rows_of(self, batch):
        """Host count if known, else the batch's async device count (for
        deferred metric resolution), else None."""
        if batch is None:
            return 0
        if batch.nrows is not None:
            return batch.nrows
        return batch.nrows_dev

    def _flush_metrics(self) -> None:
        m = getattr(self, "_metrics", None)
        if m:
            wid = getattr(self, "worker_id", "embedded")
            qid = getattr(self.g, "query_id", None)
            key = ("metrics", wid) if qid is None else ("metrics", qid, wid)
            with self._metrics_guard():
                snap = m.snapshot()
            self.store.set(key, snap)
            # same cadence for the operator-stats plane: queued nrows_dev
            # scalars (async copies long landed) fold into the ledger here
            opstats.OPSTATS.resolve_pending()

    def _shutdown_prefetch(self) -> None:
        """Cancel speculative reads and release the IO threads — without this
        every Engine leaks its pool, and interpreter exit can block on a read
        stuck in a wedged filesystem/tunnel."""
        pool = getattr(self, "_prefetch_pool", None)
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
            self._prefetch_pool = None
            self._prefetch = None

    def _actor_stages(self) -> Dict[int, int]:
        """AST is write-once at graph build; workers cache it locally instead
        of a per-task RPC (distributed hot loop)."""
        return dict(self.store.titems("AST"))

    def _sorted_actors(self):
        return self.store.smembers("SAT")

    def _channel_major_actors(self):
        return self.store.smembers("CMT")

    # -- fault tolerance ------------------------------------------------------
    def _tape(self, actor: int, ch: int, event) -> None:
        """Record the exec channel's event history (the lineage 'tape'): which
        exact batch sets were consumed and which steps emitted.  Replaying the
        tape after a failure reproduces byte-identical output seqs, which is
        what lets already-consumed outputs stay valid downstream (the
        TapedExecutorTask discipline, pyquokka/task.py:139, fault-tolerance.md)."""
        if self.g.hbq is None:
            return
        self.store.tape_append(actor, ch, event)

    def _ckpt_store(self):
        """Checkpoints outlive their writer (reference: S3, core.py:678-685):
        exec_config["checkpoint_store"] may point anywhere fsspec can reach;
        default = the run's checkpoint dir (shared on one machine)."""
        store = getattr(self, "_ckpt_store_obj", None)
        if store is None:
            from quokka_tpu.runtime.ckptstore import CheckpointStore

            root = self.g.exec_config.get("checkpoint_store") or self.g.ckpt_dir
            # query-service graphs share one checkpoint root: snapshot names
            # carry the query namespace so neighbors never restore each other
            store = self._ckpt_store_obj = CheckpointStore(
                root, namespace=getattr(self.g, "query_id", None))
        return store

    def _checkpoint(self, executor, task: ExecutorTask) -> None:
        """Snapshot executor state + input frontier + tape position
        (core.py:678-685)."""
        if not getattr(executor, "SUPPORTS_CHECKPOINT", False):
            # no snapshot support: recovery rewinds to state 0 + full tape
            # replay; recording an LCT here would silently drop state
            return
        # flush barrier: every spill the tape references up to this point
        # must be durable before the checkpoint triple is recorded —
        # recovery that restores here may immediately replay from the HBQ
        self._flush_spills()
        state = executor.checkpoint()
        try:
            self._ckpt_store().save(
                task.actor, task.channel, task.state_seq, pickle.dumps(state)
            )
        except (CorruptArtifactError, OSError) as e:
            # a failed snapshot is a SKIPPED snapshot, never a dead query:
            # checkpointing only shortens recovery (older checkpoints and
            # the full tape remain valid recovery points), so a flaky
            # store/torn upload must not kill a healthy run.  LCT is not
            # recorded — recovery never points at the failed save.
            obs.REGISTRY.counter("recover.ckpt_save_skipped").inc()
            obs.RECORDER.record("recover.ckpt_save_skipped",
                                f"a{task.actor}c{task.channel}",
                                state=task.state_seq, error=repr(e)[:160])
            obs.diag(f"[ckpt] snapshot ({task.actor},{task.channel}) state "
                     f"{task.state_seq} skipped: {e!r}")
            return
        tape_len = self.store.tape_len(task.actor, task.channel)
        with self.store.transaction():
            self.store.tset(
                "LCT",
                (task.actor, task.channel),
                (task.state_seq, task.out_seq, tape_len),
            )
            # full checkpoint HISTORY, not just the latest: recovery may have
            # to rewind a producer PAST its latest checkpoint when a co-dead
            # consumer's tape needs outputs the latest checkpoint postdates
            # (the reference's rewind requests, coordinator.py:221-229)
            self.store.tappend(
                "LT", ("ckpts", task.actor, task.channel),
                (task.state_seq, task.out_seq, tape_len),
            )
            self.store.tset(
                "IRT",
                (task.actor, task.channel, task.state_seq),
                {a: dict(c) for a, c in task.input_reqs.items()},
            )
        # The tape is NOT trimmed at checkpoints: pre-checkpoint events must
        # stay replayable because a failure can lose both a producer and a
        # consumer, and regenerating the consumer's lost inputs may require
        # replaying the producer from an older state than its latest
        # checkpoint (no shared spill disk is assumed).  Tape entries are
        # small host tuples — the reference similarly keeps full lineage in
        # Redis for the run's lifetime.
        #
        # Standing queries additionally persist a resume manifest (source
        # segment log + watermark trail + this recovery point) so a FULL
        # process restart — not just an in-process kill — resumes from here
        # instead of offset zero (quokka_tpu/streaming/manifest.py).
        if getattr(self.g, "stream_manifest", None):
            from quokka_tpu.streaming import manifest as _smanifest

            _smanifest.update(self.g)
        # Durable BATCH queries persist the analogous batch resume manifest
        # at the same cadence (quokka_tpu/runtime/resume.py): the service
        # supervisor re-admits orphans from it after a process death.
        elif getattr(self.g, "resume_manifest", None):
            from quokka_tpu.runtime import resume as _bresume

            _bresume.update(self.g)

    def simulate_failure_and_recover(self, failed: List[Tuple[int, int]]) -> None:
        """Kill the given exec (actor, channel) workers — losing executor
        state, their queued tasks, and cached inputs destined to them — then
        run the recovery protocol (coordinator.py:219-552): restore from a
        checkpoint chosen by the rewind planner, rebuild the input frontier
        from IRT, and replay already-produced inputs from the HBQ spill."""
        assert self.g.hbq is not None, "fault tolerance is not enabled"
        # flush barrier: the rewind planner and the replay tasks it queues
        # consult HBQ listings — pending async spills must land first
        self._flush_spills()
        dead_exec = []
        for (a, ch) in failed:
            info = self.g.actors[a]
            assert info.kind == "exec", "simulated failures target exec workers"
            for name in list(self.cache.flights_info()):
                if name[3] == a and name[5] == ch:
                    self.cache.gc([name])
            dead_exec.append((a, ch))
        choices = plan_rewinds(self.store, dead_exec)
        for (a, ch) in failed:
            self._recover_channel(a, ch, choice=choices.get((a, ch)))

    def _recover_channel(self, a: int, ch: int, choice=None) -> None:
        """Rebuild one lost channel by QUEUEING recovery tasks into NTT (the
        reference pushes TapedInputTask/TapedExecutorTask/ReplayTask from the
        coordinator, pyquokka/coordinator.py:424-552): whichever worker owns
        the channel after reassignment pops and executes them through its
        normal task loop.  Shared by the embedded failure simulation and the
        distributed worker's channel adoption (runtime/worker.py).
        `choice` = (state_seq, out_seq, tape_pos) from the rewind planner;
        None restores the latest checkpoint."""
        info = self.g.actors[a]
        # replayed pushes must honor adaptations recorded before the loss
        self._adapt_refresh()
        self.store.tdel("DST", (a, ch))
        self.store.ntt_remove_channel(a, ch)
        if info.kind == "input":
            # inputs carry no state: re-derive the remaining tape from GIT.
            # Seqs below the streaming GC floor were committed AND consumed
            # past every recorded checkpoint frontier before manifest.gc
            # dropped their GIT/LT rows, so the rebuild starts at the floor.
            last = self.store.tget("LIT", (a, ch), -1)
            floor = self.store.tget("LT", ("gc_floor", a, ch), 0)
            done = self.store.smembers("GIT", (a, ch))
            remaining = [s for s in range(floor, last + 1) if s not in done]
            if remaining:
                self.store.ntt_push(a, TapedInputTask(a, ch, remaining))
            elif (getattr(info.reader, "UNBOUNDED", False)
                  and not self.store.tget("SST", a)):
                # a fully committed UNBOUNDED channel is idle, not done:
                # requeue an empty tape so the poll loop keeps tailing
                self.store.ntt_push(a, TapedInputTask(a, ch, []))
            else:
                self.store.sadd("DST", (a, ch), "done")
            return
        if choice is None:
            choice = self.store.tget("LCT", (a, ch)) or (0, 0, 0)
        state_seq, out_seq, tape_pos = choice
        tape_base = self.store.tget("LT", ("tape_base", a, ch), 0)
        if tape_pos < tape_base:
            # streaming GC trimmed the tape below this recovery point
            # (manifest.gc trims only below the covering checkpoint of the
            # retained floor, so a planner choice landing here means the
            # floor discipline was violated) — fail loudly rather than
            # replay a silently truncated tape as if it were complete
            raise RuntimeError(
                f"recovery of channel ({a}, {ch}) needs tape history from "
                f"position {tape_pos}, but the tape was trimmed to "
                f"{tape_base} (streaming GC floor violation)"
            )
        reqs = {
            s: dict(c)
            for s, c in self.store.tget("IRT", (a, ch, state_seq)).items()
        }
        n_exec_events = sum(
            1 for ev in self.store.tape_slice(a, ch, tape_pos) if ev[0] == "exec"
        )
        self.store.ntt_push(
            a,
            TapedExecutorTask(
                a, ch, state_seq, out_seq, state_seq + n_exec_events, reqs,
                tape_pos,
            ),
        )

    # -- HBQ resolution hooks -------------------------------------------------
    # The embedded engine owns the run's only HBQ; the distributed Worker
    # overrides these to aggregate its OWN spill dir with every live peer's
    # (served over the data plane) — the reference's ReplayTask-co-located-
    # with-an-HBQ-copy discipline (coordinator.py:424-552) with the transfer
    # direction inverted: the adopter pulls instead of the holder pushing.
    def _hbq_names_for_target(self, tgt_actor: int, tgt_ch: int):
        return self.g.hbq.names_for_target(tgt_actor, tgt_ch)

    def _hbq_fetch(self, name: Tuple):
        return self.g.hbq.get(name)

    def _recompute_object(self, name: Tuple):
        """Last-resort recovery of a lost object (no live HBQ holds it):
        when its producer is an INPUT actor, the read is pure per lineage —
        re-read the lineage and re-partition for exactly the lost consumer
        channel (the reference's 'new input requests', coordinator.py:274-334).
        Exec-produced objects are regenerated by the producer's own tape
        replay instead; returns None for those."""
        src_a, src_ch, seq, tgt_a, _pfn, tgt_ch = name
        info = self.g.actors.get(src_a)
        if info is None or info.kind != "input":
            return None
        lineage = self.store.tget("LT", (src_a, src_ch, seq))
        if lineage is None:
            return None
        batch = self._read_and_bridge(info, src_ch, lineage)
        if info.predicate is not None:
            # exactly the live input path: source predicate BEFORE push
            # (handle_input_task), else the recomputed object gains rows
            batch = info.predicate(batch)
        # seq-aware: an adapted edge (ADT) routes this historical sequence
        # exactly as the original push did
        self._adapt_refresh()
        parts = self._partition_fn(src_a, tgt_a)(batch, src_ch, seq)
        return parts.get(tgt_ch)

    def _resolve_lost_object(self, name: Tuple):
        """cache -> any live HBQ -> input re-read; None if irrecoverable
        right now (the producer's tape replay may still regenerate it).
        Watermarks re-attach from the SWM trail: batch attrs do not survive
        the arrow round trip, and replay determinism needs the exact
        original watermark sequence."""
        b = self.cache.get(name)
        if b is not None:
            return self._attach_stream_wm(name, b)
        table = self._hbq_fetch(name)
        if table is not None:
            return self._attach_stream_wm(name, bridge.arrow_to_device(table))
        return self._attach_stream_wm(name, self._recompute_object(name))

    def _hbq_contains(self, name: Tuple) -> bool:
        """Listing-level probe; the distributed Worker overrides this to also
        consult peer HBQ listings (no bytes move either way)."""
        return self.g.hbq is not None and self.g.hbq.contains(name)

    def _object_available(self, name: Tuple) -> bool:
        """Existence probe WITHOUT materializing bytes: local cache hit, an
        HBQ listing (local or a peer's), or an input-lineage recompute is
        possible.  handle_exectape_task pre-flights the whole tape with this
        so a rewind to (0,0,0) on a long-running channel doesn't hold the
        channel's entire consumed history in device memory at once."""
        if self.cache.get(name) is not None:
            return True
        if self._hbq_contains(name):
            return True
        src_a, src_ch, seq = name[0], name[1], name[2]
        info = self.g.actors.get(src_a)
        return (
            info is not None
            and info.kind == "input"
            and self.store.tget("LT", (src_a, src_ch, seq)) is not None
        )

    def handle_exectape_task(self, task: TapedExecutorTask) -> bool:
        """Run a queued tape replay: recreate the executor, restore the
        checkpoint named by task.state_seq, re-run the recorded event history,
        then requeue the channel as a live ExecutorTask plus a ReplayTask that
        refills its input cache from the HBQ spill.

        Tape inputs are pre-flighted with EXISTENCE PROBES before any event
        executes (a missing one — its producer's own adoption/replay may not
        have re-pushed it yet — requeues this task untouched), then resolved
        one event at a time inside _replay_tape so a rewind to (0,0,0) never
        holds the channel's full consumed history in memory simultaneously.
        A probe-then-vanish race (peer dies mid-replay) surfaces as
        LostObjectError and requeues the same way: replay emissions are
        seq-keyed and deterministic, so the retried replay overwrites its own
        partial output rather than duplicating it."""
        a, ch = task.actor, task.channel
        self._flush_spills()  # tape inputs probe the HBQ listing below
        self._adapt_refresh()  # replay emissions route per recorded ADT
        reqs = {s: dict(c) for s, c in task.input_reqs.items()}
        tape = self.store.tape_slice(a, ch, task.tape_pos)

        def _requeue_waiting(name):
            # a vanished input whose producer is ALIVE will never reappear
            # on its own (e.g. its only spill copy was quarantined as
            # corrupt): force the producer to rewind far enough to re-emit
            # it (no-op outside the embedded single-threaded loop).  A
            # rewind queued now counts as progress — recovery work exists.
            rewound = self._maybe_force_producer_rewind(name)
            # time-based, not attempt-based: the co-dead producer's own
            # replay (possibly from state 0 with a long tape) can
            # legitimately take minutes to regenerate this object.  The
            # bound is QK_REPLAY_DEADLINE: a genuinely irrecoverable loss
            # used to wedge the full 600s under load (the ROADMAP
            # test_distributed note) with no way to shorten the verdict
            deadline = getattr(task, "retry_deadline", None)
            if deadline is None:
                deadline = task.retry_deadline = (
                    time.time() + config.replay_retry_deadline_s())
            if os.environ.get("QUOKKA_DEBUG_REPLAY"):
                now = time.time()
                if now - getattr(task, "_dbg_at", 0) > 3.0:
                    task._dbg_at = now
                    obs.diag(f"[replay-wait] ({a},{ch}) waiting on {name} "
                             f"cache={self.cache.get(name) is not None} "
                             f"hbq={self._hbq_contains(name)}")
            if time.time() > deadline:
                raise RuntimeError(
                    f"tape input {name} for channel ({a},{ch}) is in "
                    "no live HBQ and its producer never regenerated it "
                    f"within QK_REPLAY_DEADLINE="
                    f"{config.replay_retry_deadline_s():g}s — "
                    "irrecoverable loss"
                )
            self.store.ntt_push(a, task)
            time.sleep(0.05)
            return rewound

        probed = set()
        for ev in tape:
            if ev[0] != "exec":
                continue
            for name in ev[2]:
                if name in probed:
                    continue
                if not self._object_available(name):
                    return _requeue_waiting(name)
                probed.add(name)
        self.execs[(a, ch)] = self._bind_executor(
            self.g.actors[a].executor_factory())
        try:
            blob = self._ckpt_store().load(a, ch, task.state_seq)
        except CorruptArtifactError:
            # corrupt checkpoint == LOST checkpoint (the store already
            # quarantined it): rewind this channel to an older checkpoint —
            # ultimately (0,0,0) + full tape replay — instead of crashing
            # or restoring from untrusted bytes.  True: the queued fallback
            # IS progress (the embedded loop's no-progress stall check
            # would otherwise fire when this was the only pending task)
            self._ckpt_fallback(task)
            return True
        if blob is not None:
            self.execs[(a, ch)].restore(pickle.loads(blob))
        elif task.state_seq > 0:
            raise FileNotFoundError(
                f"checkpoint for ({a},{ch}) state {task.state_seq} named by "
                "LCT is missing from the checkpoint store — cannot rebuild"
            )
        try:
            state_seq, out_seq = self._replay_tape(
                a, ch, tape, reqs, task.state_seq, task.out_seq
            )
        except LostObjectError as e:
            self.execs.pop((a, ch), None)  # discard the partial rebuild
            return _requeue_waiting(e.name)
        # replay-complete check: the tape must advance the state exactly to
        # where the coordinator said the channel was when it queued this task
        assert state_seq == task.last_state_seq, (
            f"tape replay of ({a},{ch}) reached state {state_seq}, "
            f"expected {task.last_state_seq} — lineage tape diverged"
        )
        if self.g.hbq is not None:
            hbq_names = self._hbq_names_for_target(a, ch)
            specs = {
                name
                for name in hbq_names
                if name[0] in reqs
                and name[1] in reqs[name[0]]
                and name[2] >= reqs[name[0]][name[1]]
            }
            # ... plus every input-produced object the producer already
            # COMMITTED (GIT) past the restored frontier, whether or not a
            # live HBQ lists it: a partition that lived only in the dead
            # worker's cache/private HBQ is in nobody's listing, and without
            # a spec nobody regenerates it — the consumer then waits forever
            # while the recovered input task skips the seq as already-done
            # (the deadlock this closes).  These names re-read from lineage
            # in handle_replay_task (_recompute_object — the reference's
            # 'new input requests', coordinator.py:274-334).  Bounded to
            # GIT'd seqs: uncommitted seqs arrive from the live/recovered
            # producer normally, and exec-produced inputs re-push via their
            # producer's own tape replay.
            for src_a, chans in reqs.items():
                src_info = self.g.actors.get(src_a)
                if src_info is None or src_info.kind != "input":
                    continue
                for sch, nxt in chans.items():
                    for s in self.store.smembers("GIT", (src_a, sch)):
                        if s >= nxt:
                            specs.add((src_a, sch, s, a, src_a, ch))
            if specs:
                self.store.ntt_push(a, ReplayTask(a, ch, sorted(specs)))
        self.store.ntt_push(a, ExecutorTask(a, ch, state_seq, out_seq, reqs))
        return True

    def _ckpt_fallback(self, task: TapedExecutorTask) -> None:
        """Requeue a tape replay whose checkpoint failed its integrity
        check, rebuilt at the deepest available OLDER checkpoint (the
        ``ckpts`` history recorded at checkpoint time; (0,0,0) is always
        available — state 0 + full tape replay needs no snapshot).  The
        target ``last_state_seq`` is unchanged, so the replay still proves
        it reached exactly the state the channel died at."""
        a, ch = task.actor, task.channel
        hist = ckpt_candidates(self.store, a, ch)
        choice = max((h for h in hist if h[0] < task.state_seq),
                     key=lambda h: h[0])
        obs.REGISTRY.counter("recover.ckpt_fallback").inc()
        obs.RECORDER.record("recover.ckpt_fallback", f"a{a}c{ch}",
                            bad_state=task.state_seq, to=repr(choice))
        state_seq, out_seq, tape_pos = choice
        reqs = {
            s: dict(c)
            for s, c in self.store.tget("IRT", (a, ch, state_seq)).items()
        }
        self.store.ntt_push(
            a,
            TapedExecutorTask(a, ch, state_seq, out_seq,
                              task.last_state_seq, reqs, tape_pos),
        )

    # Escalation for an unrecoverable-by-waiting tape/replay input: the
    # object is in no cache and no HBQ (e.g. its spill was quarantined as
    # corrupt), and its producer is a LIVE exec channel — nothing in the
    # basic chain will ever regenerate it, so the producer itself must
    # rewind to a checkpoint old enough to re-emit it (corruption is
    # treated as loss OF THE PRODUCER'S OUTPUT, the same judgment
    # plan_rewinds makes for co-dead producers).  Embedded-engine only:
    # its dispatch loop is single-threaded, so rewinding a live channel
    # cannot race an in-flight dispatch of that channel.  The distributed
    # worker and the multi-threaded query service keep the wait-with-
    # deadline behavior (loud failure, never silent corruption).
    _allow_forced_rewind = True

    def _maybe_force_producer_rewind(self, name) -> bool:
        """Returns True when a rewind was queued NOW — that is real
        scheduling progress (new recovery work exists), which keeps the
        embedded loop's no-progress stall check honest while the waiting
        consumer requeues itself."""
        if not self._allow_forced_rewind or getattr(self, "_svc_ready", False):
            return False
        src_a, src_ch, seq = name[0], name[1], name[2]
        info = self.g.actors.get(src_a)
        if info is None or info.kind != "exec":
            return False
        forced = getattr(self, "_forced_rewinds", None)
        if forced is None:
            forced = self._forced_rewinds = set()
        key = (src_a, src_ch, seq)
        if key in forced:
            return False
        forced.add(key)
        # a LATER rewind of the same channel replaces any queued earlier one
        # (_recover_channel drops the channel's queued tasks), so every
        # rewind must cover the MINIMUM seq ever lost from this channel —
        # rewinding only far enough for the newest loss would cancel the
        # pending replay that was going to regenerate an older one
        floors = getattr(self, "_rewind_floor", None)
        if floors is None:
            floors = self._rewind_floor = {}
        floor = min(seq, floors.get((src_a, src_ch), seq))
        floors[(src_a, src_ch)] = floor
        hist = ckpt_candidates(self.store, src_a, src_ch)
        # the checkpoint must PREDATE the lost output seq or the replay
        # never re-emits it (same covering rule as plan_rewinds)
        choice = max((h for h in hist if h[1] <= floor), key=lambda h: h[0])
        obs.REGISTRY.counter("recover.producer_rewind").inc()
        obs.RECORDER.record("recover.producer_rewind", f"a{src_a}c{src_ch}",
                            for_seq=seq, to=repr(choice))
        self._recover_channel(src_a, src_ch, choice=choice)
        return True

    def dispatch_task(self, task) -> bool:
        """Route a popped NTT task to its handler by task kind, recording
        the dispatch in the flight recorder: completed dispatches as
        duration events, could-not-progress requeues coalesced to one
        ``task.wait`` instant per (actor, channel) stall episode (the retry
        loop would otherwise flood the ring and evict the history a stall
        dump needs)."""
        rec = obs.RECORDER
        qid = getattr(self.g, "query_id", None)
        if not rec.enabled:
            t0 = time.perf_counter()
            ok = self._dispatch(task)
            if ok:
                dt = time.perf_counter() - t0
                self._observe_latency(dt)
                opstats.OPSTATS.dispatch_time(qid, task.actor, task.channel,
                                              dt)
            return ok
        qargs = {"a": task.actor, "c": task.channel, "k": task.name}
        if qid is not None:
            qargs["q"] = qid
        label = f"{task.name}:a{task.actor}c{task.channel}"
        if qid is not None:
            label = f"{qid}:{label}"
        idle = getattr(self, "_obs_idle", None)
        if idle is None:
            idle = self._obs_idle = set()
        key = (task.actor, task.channel, task.name)
        _OBS_NOTE.d = {}
        t0 = time.perf_counter()
        try:
            with rec.activity("task:" + label):
                ok = self._dispatch(task)
        finally:
            note = getattr(_OBS_NOTE, "d", None) or {}
            _OBS_NOTE.d = None
        if ok:
            dt = time.perf_counter() - t0
            rec.record("task", label, dur=dt, **qargs, **note)
            self._observe_latency(dt)
            opstats.OPSTATS.dispatch_time(qid, task.actor, task.channel, dt)
            idle.discard(key)
        elif key not in idle:
            idle.add(key)
            rec.record("task.wait", label, **qargs)
        return ok

    def _init_latency_hists(self, graph) -> None:
        """Latency histograms resolved ONCE, while the graph is alive: the
        observe path must never use a creating registry lookup, or a
        dispatch quantum completing after TaskGraph.cleanup would resurrect
        the GC'd per-query instrument as a permanent /metrics leak
        (observing into the orphaned object instead is harmless).  Shared
        with the distributed Worker, whose __init__ bypasses Engine's."""
        self._lat_hist = obs.REGISTRY.histogram("task.latency_s")
        qid = getattr(graph, "query_id", None)
        self._qlat_hist = (
            obs.REGISTRY.histogram(f"task.latency_s.{qid}")
            if qid is not None else None)
        # shuffle instruments, same once-resolved discipline (push runs on
        # the dispatch path; per-query twins are GC'd in TaskGraph.cleanup)
        self._shuffle_bytes = obs.REGISTRY.counter("shuffle.bytes")
        self._shuffle_bytes_q = (
            obs.REGISTRY.counter(f"shuffle.bytes.{qid}")
            if qid is not None else None)
        self._shuffle_syncs_q = (
            obs.REGISTRY.counter(f"shuffle.host_syncs.{qid}")
            if qid is not None else None)
        # compile-plane attribution: per-query twins of the compile.* event
        # counters (GC'd in TaskGraph.cleanup) plus the plan fingerprint the
        # query's program uses are recorded under (runtime/compileplane.py)
        self._compile_counters = (
            {ev: obs.REGISTRY.counter(f"compile.{ev}.{qid}")
             for ev in ("cache_hit", "miss", "prewarm_hit")}
            if qid is not None else None)
        self._plan_fp = getattr(graph, "plan_fp", None)
        # operator-statistics plane: topology registered once while the
        # graph is alive (covers the distributed Worker too, whose __init__
        # bypasses Engine's); recording for an unregistered query is a no-op
        opstats.OPSTATS.register_plan(graph)

    def _observe_latency(self, dt: float) -> None:
        """Dispatch latency into the typed histograms (resolved once in
        __init__): one process-wide family plus a per-query one (GC'd with
        the query in TaskGraph.cleanup) that service stats() reads p50/p95
        from."""
        self._lat_hist.observe(dt)
        if self._qlat_hist is not None:
            self._qlat_hist.observe(dt)

    def _dispatch(self, task) -> bool:
        from quokka_tpu.runtime import compileplane

        # every program this dispatch compiles/loads is attributed to this
        # query (per-query compile.* counters) and recorded under its plan
        # fingerprint for the next submit's pre-warm
        with compileplane.query_scope(self._compile_counters, self._plan_fp):
            if task.name == "input":
                return self.handle_input_task(task)
            if task.name == "exec":
                return self.handle_exec_task(task)
            if task.name == "exectape":
                return self.handle_exectape_task(task)
            return self.handle_replay_task(task)

    def handle_replay_task(self, task: ReplayTask) -> bool:
        """Re-push spilled post-partition objects to the (rebuilt) consumer's
        cache — the reference's ReplayTask (pyquokka/core.py:967-1025), the
        objects coming off this worker's own HBQ or a live peer's (or an
        input re-read when no copy survives).

        Unresolvable names (every surviving copy corrupt/quarantined, the
        producer's regeneration not landed yet) requeue with the remaining
        specs instead of being silently dropped — a dropped spec would
        starve the rebuilt consumer forever.  A live exec producer of such
        a name is force-rewound (embedded engine) so regeneration actually
        happens; after the deadline the loss is surfaced loudly."""
        self._flush_spills()  # _resolve_lost_object reads the HBQ below
        missing = []
        resolved = 0
        for name in task.replay_specs:
            b = self._resolve_lost_object(name)
            if b is not None:
                self._cache_put(name, b)
                resolved += 1
            else:
                missing.append(name)
        if not missing:
            return True
        rewound = False
        for name in missing:
            rewound |= self._maybe_force_producer_rewind(name)
        deadline = getattr(task, "retry_deadline", None)
        if deadline is None:
            deadline = task.retry_deadline = (
                time.time() + config.replay_retry_deadline_s())
        if time.time() > deadline:
            raise RuntimeError(
                f"replay objects {missing[:3]}{'...' if len(missing) > 3 else ''} "
                f"for channel ({task.actor},{task.channel}) survive in no "
                "cache or HBQ and were never regenerated within "
                f"QK_REPLAY_DEADLINE={config.replay_retry_deadline_s():g}s "
                "— irrecoverable loss"
            )
        task.replay_specs = missing
        self.store.ntt_push(task.actor, task)
        time.sleep(0.05)
        # resolved objects ARE progress (they may unblock the consumer this
        # pass); so is a freshly queued producer rewind — only a fully
        # fruitless pass reads as no-progress to the stall check
        return rewound or resolved > 0

    def _replay_tape(self, actor: int, ch: int, events, reqs,
                     state_seq: int, out_seq: int):
        """Re-run the recorded event history: identical inputs in identical
        order reproduce identical outputs at identical seqs (so downstream
        consumers — which may already hold some of them — stay consistent).
        Inputs resolve LAZILY, one event at a time — probed available by the
        caller, but never all materialized at once."""
        info = self.g.actors[actor]
        executor = self.execs[(actor, ch)]
        for ev in events:
            if ev[0] == "exec":
                _, src_actor, names, emitted = ev
                batches = []
                for name in names:
                    b = self._resolve_lost_object(name)
                    if b is None:
                        raise LostObjectError(name)
                    batches.append(b)
                out = executor.execute(batches, info.source_streams[src_actor], ch)
                re_emitted = out is not None
                assert re_emitted == emitted, "non-deterministic replay"
                if re_emitted:
                    self._stamp_exec_wm(executor, out, ch)
                    self._emit(info, ch, out_seq, out)
                    out_seq += 1
                for name in names:
                    sa, sch, seq = name[0], name[1], name[2]
                    reqs[sa][sch] = max(reqs[sa].get(sch, 0), seq + 1)
                state_seq += 1
            else:
                # exhausted sources stay in reqs here; the first live prune
                # re-drops them (executors guard repeated source_done calls)
                _, stream_id, emitted = ev
                extra = executor.source_done(stream_id, ch)
                re_emitted = extra is not None
                assert re_emitted == emitted, "non-deterministic replay"
                if re_emitted:
                    self._stamp_exec_wm(executor, extra, ch)
                    self._emit(info, ch, out_seq, extra)
                    out_seq += 1
        return state_seq, out_seq

    # at most this many sink batches may be in flight on the emitter thread
    # (bounds device memory held by un-converted DeviceBatches)
    _EMIT_INFLIGHT = 8

    def _emit(self, info: ActorInfo, channel: int, seq: int, out: DeviceBatch) -> None:
        if getattr(info, "blocking", False) or info.blocking_dataset is not None:
            # sink emission is the engine's big blocking host segment (a full
            # device->host sync per output batch): run it on a single emitter
            # thread so the task loop keeps dispatching device work — the
            # reference gets this overlap from concurrent Ray actors
            # (pyquokka/core.py:276-376).  One thread => FIFO order; appends
            # are seq-keyed so replay re-emissions stay idempotent.  The
            # emitter is FLUSHED before a channel is marked done (DST) so no
            # consumer can observe a partially-shipped result set.
            self._emit_submit(
                lambda: self._convert_and_append(info, channel, seq, out)
            )
        else:
            self.push(info.id, channel, seq, out)

    def _convert_and_append(self, info, channel, seq, out):
        with tracing.span("emit.result_d2h"):
            table = bridge.device_to_arrow(out)
        self._result_append(info, channel, seq, table)

    def _emit_submit(self, fn) -> None:
        pool = getattr(self, "_emit_pool", None)
        if pool is None:
            with _LAZY_INIT_LOCK:
                pool = getattr(self, "_emit_pool", None)
                if pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._emit_futs = []
                    self._emit_lock = threading.Lock()
                    pool = self._emit_pool = ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="quokka-emit"
                    )
        with self._emit_lock:
            self._emit_futs.append(pool.submit(fn))
        while True:
            with self._emit_lock:
                if len(self._emit_futs) <= self._EMIT_INFLIGHT:
                    break
                f = self._emit_futs.pop(0)
            f.result()  # wait OUTSIDE the lock: conversion is a d2h sync

    def _flush_emits(self) -> None:
        futs = getattr(self, "_emit_futs", None)
        if futs:
            with self._emit_lock:
                futs, self._emit_futs = self._emit_futs, []
            for f in futs:
                f.result()  # propagate the first conversion/append error

    def _shutdown_emitter(self) -> None:
        pool = getattr(self, "_emit_pool", None)
        if pool is not None:
            self._emit_pool = None
            pool.shutdown(wait=True)

    def _result_append(self, info: ActorInfo, channel: int, seq: int, table) -> None:
        """Blocking-node output sink; the distributed worker overrides this to
        ship result tables to the coordinator.  seq-keyed so fault-tolerant
        replay overwrites, never duplicates."""
        info.blocking_dataset.append(channel, table, seq=seq)
        if getattr(self.g, "resume_manifest", None):
            # durable-batch sink floor (monotone: replay re-appends must not
            # rewind it) — the resume manifest records how far the
            # client-visible result had materialized
            cur = self.store.tget("RMT", ("sink", info.id, channel), 0)
            if seq + 1 > cur:
                self.store.tset("RMT", ("sink", info.id, channel), seq + 1)

    # -- coordinator loop (coordinator.py:106-165) ----------------------------
    # Stage discipline follows the reference exactly: INPUT tasks only run when
    # their actor's stage <= the current execution stage; EXEC tasks always run
    # (their input requirements + the input gating enforce ordering,
    # core.py:504 comment); the stage advances when no undone actor remains at
    # the current stage.
    def run(self, max_batches: Optional[int] = None, timeout: float = 3600.0) -> None:
        try:
            self._run(max_batches, timeout)
            self._flush_emits()
        finally:
            try:
                self._flush_metrics()
            except Exception:
                pass  # a dead store must not block thread shutdown below
            self._shutdown_prefetch()
            self._shutdown_emitter()
            self._shutdown_spill()
            self._export_trace()

    def _export_trace(self) -> None:
        """QK_TRACE_EVENTS=<path>: write this process's flight events as
        Chrome trace JSON at run end (embedded engine only — distributed
        runs export the MERGED multi-worker timeline from the coordinator,
        runtime/distributed.py)."""
        path = obs.trace_export_path()
        if path is None or getattr(self, "worker_id", None) is not None:
            return
        try:
            obs.write_chrome_trace(
                path, obs.merge_streams({"engine": obs.RECORDER.snapshot()}))
        except OSError as e:
            obs.diag(f"[flight-recorder] trace export to {path} failed: {e}")

    def _io_threads(self) -> int:
        n = sum(a.channels for a in self.g.actors.values() if a.kind == "input")
        return max(2, min(4, n))

    def _warm_prefetch(self, actors) -> None:
        """Kick off the first read of every stage-0 input channel before the
        task loop starts, so initial decode+h2d runs in parallel across
        channels instead of serially on first touch."""
        if getattr(self, "_warmed", False):
            return  # re-entrant run(): finished channels must not re-read
        self._warmed = True
        self._ensure_prefetch_pool()
        for info in actors:
            if info.kind != "input" or info.stage != 0:
                continue
            for ch in range(info.channels):
                key = (info.id, ch)
                if key in self._prefetch or self.store.scontains(
                    "DST", (info.id, ch), "done"
                ):
                    continue
                lineage = self.store.tget("LT", (info.id, ch, 0))
                if lineage is None:
                    continue
                self._prefetch[key] = (
                    0,
                    self._prefetch_pool.submit(self._read_and_bridge, info, ch, lineage),
                )

    def _run(self, max_batches: Optional[int], timeout: float) -> None:
        if max_batches is not None:
            self.max_batches = max_batches
        actors = sorted(self.g.actors.values(), key=lambda a: (a.stage, a.id))
        self._warm_prefetch(actors)
        stages = sorted({a.stage for a in actors})
        stage_idx = 0
        t0 = time.time()
        inject = self.g.exec_config.get("inject_failure")
        handled = 0
        # chaos plane (QK_CHAOS kill=N): lose seeded-random exec channels at
        # seeded-random task boundaries, on top of any scripted injection
        from quokka_tpu.chaos import CHAOS

        chaos_kills = []
        if CHAOS.enabled and self.g.hbq is not None:
            exec_channels = sorted(
                (a.id, ch) for a in actors if a.kind == "exec"
                for ch in range(a.channels))
            chaos_kills = list(CHAOS.plan_embedded_failures(exec_channels))
        while True:
            if time.time() - t0 > timeout:
                _, report, _ = obs.dump_flight(
                    f"embedded engine run exceeded {timeout:.0f}s timeout",
                    {"engine": obs.RECORDER.snapshot()})
                raise TimeoutError(
                    "engine run exceeded timeout; pending tasks: "
                    f"{self.store.ntt_total()}"
                    + (f"; flight report: {report}" if report else "")
                )
            current = stages[stage_idx]
            progress = False
            for info in actors:
                if info.kind == "input" and info.stage > current:
                    continue
                task = self.store.ntt_pop(info.id)
                if task is None:
                    continue
                ok = self.dispatch_task(task)
                progress |= ok
                if ok:
                    handled += 1
                    if inject is not None and handled >= inject["after_tasks"]:
                        self.simulate_failure_and_recover(inject["channels"])
                        inject = None
                        progress = True
                    while chaos_kills and handled >= chaos_kills[0][0]:
                        _, chans = chaos_kills.pop(0)
                        CHAOS.record_kill(f"embedded {chans}")
                        self.simulate_failure_and_recover(chans)
                        progress = True
            if self._all_done(actors):
                return
            # advance when nothing undone remains at the current stage
            while stage_idx < len(stages) - 1 and not self._stage_undone(
                actors, stages[stage_idx]
            ):
                stage_idx += 1
                progress = True
            if not progress:
                _, report, _ = obs.dump_flight(
                    "embedded engine stalled: no task progressed",
                    {"engine": obs.RECORDER.snapshot()})
                raise RuntimeError(
                    "engine stalled: no task progressed and the stage cannot "
                    f"advance (stage={stages[stage_idx]}, "
                    f"pending={self.store.ntt_total()})"
                    + (f"; flight report: {report}" if report else "")
                )

    # -- service stepping (query service, service/server.py) ------------------
    # The multi-query scheduler round-robins NTT pops ACROSS live query
    # namespaces; within one query, each call to service_step is one
    # fair-scheduling quantum: pop and dispatch AT MOST ONE task, honoring
    # the same stage discipline as run().  Task-granular quanta are what
    # keep a large query from starving a small one sharing the pool.

    def _service_prepare(self) -> None:
        if getattr(self, "_svc_ready", False):
            return
        with _LAZY_INIT_LOCK:
            if getattr(self, "_svc_ready", False):
                return
            self._svc_actors = sorted(
                self.g.actors.values(), key=lambda a: (a.stage, a.id))
            self._svc_stages = sorted({a.stage for a in self._svc_actors})
            self._svc_stage_idx = 0
            self._svc_cursor = 0
            # serializes the stage barrier: a racy `_svc_stage_idx += 1`
            # from two dispatch threads could advance PAST an unchecked
            # stage (skipping its _stage_undone barrier)
            self._svc_stage_lock = threading.Lock()
            self._warm_prefetch(self._svc_actors)
            self._svc_ready = True

    def service_step(self) -> str:
        """Returns 'done' (query complete), 'progress' (a task ran),
        'wait' (a task popped but could not progress and requeued itself),
        or 'idle' (nothing poppable at the current stage)."""
        self._service_prepare()
        actors = self._svc_actors
        stages = self._svc_stages
        # stage barrier: advance when nothing undone remains at the current
        # stage.  Under the lock so each increment is preceded by its own
        # _stage_undone check — an unsynchronized += from two dispatch
        # threads could hop over an unchecked stage.
        with self._svc_stage_lock:
            while (self._svc_stage_idx < len(stages) - 1
                   and not self._stage_undone(actors,
                                              stages[self._svc_stage_idx])):
                self._svc_stage_idx += 1
        if self._all_done(actors):
            return "done"
        current = stages[self._svc_stage_idx]
        n = len(actors)
        start = self._svc_cursor
        for i in range(n):
            info = actors[(start + i) % n]
            if info.kind == "input" and info.stage > current:
                continue
            task = self.store.ntt_pop(info.id)
            if task is None:
                continue
            self._svc_cursor = (start + i + 1) % n
            ok = self.dispatch_task(task)
            return "progress" if ok else "wait"
        return "idle"

    def service_finalize(self) -> None:
        """Run-end teardown for a service-driven engine: ship pending sink
        emissions, flush counters, release the IO/emit threads (the
        shared store and caches stay — they belong to the service)."""
        try:
            self._flush_emits()
        finally:
            try:
                self._flush_metrics()
            except Exception as e:  # torn-down store must not block teardown
                obs.diag(f"[service] final metrics flush failed: {e!r}")
            self._shutdown_prefetch()
            self._shutdown_emitter()
            self._shutdown_spill()

    def _stage_undone(self, actors, stage) -> bool:
        for info in actors:
            if info.stage != stage:
                continue
            for ch in range(info.channels):
                if not self.store.scontains("DST", (info.id, ch), "done"):
                    return True
        return False

    def _all_done(self, actors) -> bool:
        for info in actors:
            for ch in range(info.channels):
                if not self.store.scontains("DST", (info.id, ch), "done"):
                    return False
        return True
