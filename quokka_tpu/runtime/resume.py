"""Shared resume-manifest layer: what survives a full process death.

The in-process recovery protocol (chaos kills) replays from the control
store's tapes — but the control store is memory.  Queries survive a
PROCESS death through the durable trio:

- executor snapshots (CheckpointStore — durable, checksummed, atomic),
- the HBQ spill (durable when the service runs on a stable ``spill_dir``),
- and a resume manifest: the plan's structural fingerprint, every
  checkpointed exec channel's recovery point ``(state_seq, out_seq)`` +
  checkpoint history + IRT frontier rows, and the sink's emitted floor.

This module is the layer both manifest kinds share (structural
fingerprinting, integrity-framed load, the exec-channel collect/seed
surgery) plus the BATCH manifest itself: ``streaming/manifest.py``
delegates here and adds the stream-only parts (source segment log,
watermark trail, delivered-floor rewind, lineage GC).

Batch semantics differ from streams in two load-bearing ways:

- the HBQ spill is NOT wiped at resume: batch seq assignment is
  deterministic (re-lowering re-seeds identical frozen lineages), so the
  dead incarnation's spill files replay byte-identically — they are the
  bounded-replay substrate that lets sinks rebuild without recomputing
  upstream operators;
- every needed spill is read-VERIFIED at resume time: service-mode
  engines never force live producer rewinds, so a corrupt/missing
  exec-produced spill discovered mid-run would wedge the consumer.
  ``apply_resume`` instead probes the needed ranges up front and rewinds
  each damaged producer's recovery point to the newest checkpoint that
  COVERS the first broken output (ultimately ``(0, 0, 0)``), so its live
  re-execution re-emits the gap.  Corrupt artifacts are loss, never data.

The engine rewrites the manifest atomically (tmp + integrity frame +
rename) after EVERY successful checkpoint; clean finishes (success,
cancel, deadline, failure) delete it — only a process death leaves an
orphan for ``QueryService.recover_orphans()`` to re-admit.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Dict, List, Optional, Tuple

from quokka_tpu import obs
from quokka_tpu.runtime import integrity
from quokka_tpu.runtime.task import ReplayTask, TapedExecutorTask, TapedInputTask

MANIFEST_VERSION = 1
# manifest-generation journal entries retained in the RMT store table
# (trimmed drop-and-reappend at the cap: the QK015 GC site for the class)
_JOURNAL_KEEP = 64


class ManifestMismatch(RuntimeError):
    """The manifest cannot resume this plan (fingerprint mismatch, missing
    actors, version drift, or an unreadable/incomplete manifest) — loud,
    never a silent fresh start."""


def _exec_desc(factory) -> str:
    """Stable description of an executor factory: streaming executors expose
    ``plan_signature()`` (operator config, no object addresses); everything
    else describes by type."""
    import functools

    fn = factory
    parts = []
    while isinstance(fn, functools.partial):
        parts.extend(type(a).__name__ for a in fn.args
                     if not callable(a) or hasattr(a, "plan_signature"))
        for a in fn.args:
            sig = getattr(a, "plan_signature", None)
            if sig is not None:
                return repr(sig())
        fn = fn.func
    name = getattr(fn, "__name__", type(fn).__name__)
    return "/".join([name] + parts)


def structural_parts(graph) -> List[str]:
    """The fingerprint preimage, one part per actor: topology + operator
    configuration only — no reader size buckets (a source file may grow
    between restarts), no object reprs or addresses.  Exposed separately so
    the plan-invariant verifier (analysis/planck.py QK025) can assert the
    preimage stays restart-stable and address-free."""
    parts = []
    for aid in sorted(graph.actors):
        info = graph.actors[aid]
        desc = [str(aid), info.kind, str(info.channels), str(info.stage)]
        if info.reader is not None:
            desc.append(type(info.reader).__name__)
        if info.executor_factory is not None:
            desc.append(_exec_desc(info.executor_factory))
        desc.append(",".join(
            f"{stream}:{src}"
            for src, stream in sorted(info.source_streams.items())))
        parts.append("|".join(desc))
    return parts


def structural_fingerprint(graph) -> str:
    """Structural fingerprint for resume verification.  Unlike the compile
    plane's ``plan_fingerprint`` it must be stable across process restarts
    of the SAME query — just topology + operator configuration."""
    import hashlib

    return hashlib.sha256(
        ";".join(structural_parts(graph)).encode()).hexdigest()[:16]


def manifest_root(graph) -> str:
    """Where this graph's manifest lives: the checkpoint root, falling back
    to the spill-side checkpoint dir for remote (``://``) stores."""
    root = graph.exec_config.get("checkpoint_store") or graph.ckpt_dir
    if root is None or "://" in str(root):
        root = graph.ckpt_dir or "."
    return root


def default_path(graph) -> str:
    return os.path.join(manifest_root(graph),
                        f"batch-{graph.query_id}.manifest")


def load_framed(path: str, err=None) -> Dict:
    """Read and verify an integrity-framed manifest; loud on corruption or
    version drift — resume is an explicit operator request, never a
    best-effort guess."""
    err = err or ManifestMismatch
    try:
        m = pickle.loads(integrity.read_framed(path))
    except (OSError, pickle.UnpicklingError,
            integrity.CorruptArtifactError) as e:
        raise err(f"resume manifest {path} unreadable: {e!r}") from e
    if m.get("version") != MANIFEST_VERSION:
        raise err(
            f"resume manifest {path} has version {m.get('version')}, "
            f"this build expects {MANIFEST_VERSION}")
    return m


def load(path: str) -> Dict:
    m = load_framed(path)
    if m.get("kind", "stream") != "batch":
        raise ManifestMismatch(
            f"{path} is a {m.get('kind', 'stream')!r} manifest — batch "
            "resume needs a batch manifest (streams resume through "
            "submit_continuous)")
    return m


def collect_exec_channels(graph, with_tape: bool = False
                          ) -> Dict[Tuple[int, int], Dict]:
    """Every checkpointed exec channel's durable recovery state: the LCT
    recovery point, the full checkpoint history, and the IRT frontier rows
    for each recorded state (plus state 0, the full-replay fallback).
    ``with_tape`` additionally captures the channel's event tape (small
    host tuples) so a BATCH resume can fall back from a corrupt snapshot
    to an older checkpoint + tape replay, exactly like in-process
    recovery; streams skip it (their manifest carries source segments and
    re-bases instead).  Shared by the stream and batch manifest writers —
    call inside the caller's store transaction."""
    store = graph.store
    execs: Dict[Tuple[int, int], Dict] = {}
    for info in graph.actors.values():
        if info.kind != "exec":
            continue
        for ch in range(info.channels):
            lct = store.tget("LCT", (info.id, ch))
            if lct is None:
                continue
            irts = {}
            for hist in [(0, 0, 0)] + [
                    tuple(h) for h in
                    (store.tget("LT", ("ckpts", info.id, ch)) or [])]:
                reqs = store.tget("IRT", (info.id, ch, hist[0]))
                if reqs is not None:
                    irts[hist[0]] = {a: dict(c) for a, c in reqs.items()}
            execs[(info.id, ch)] = {
                "lct": tuple(lct),
                "ckpts": [tuple(h) for h in
                          (store.tget("LT", ("ckpts", info.id, ch))
                           or [])],
                "irts": irts,
            }
            if with_tape:
                execs[(info.id, ch)]["tape"] = list(
                    store.tget("LT", ("tape", info.id, ch)) or [])
                execs[(info.id, ch)]["tape_base"] = store.tget(
                    "LT", ("tape_base", info.id, ch), 0)
    return execs


def seed_exec_channel(store, a: int, ch: int, e: Dict,
                      ckpts: Optional[List[Tuple]] = None) -> Tuple[int, int]:
    """Restart surgery for one checkpointed exec channel on a fresh store:
    re-base the recovery point and checkpoint history to tape position 0
    (the dead process's tape is gone), restore the IRT frontier rows, seed
    the producer-throttle watermarks (EWT = consumed-1: a fresh store's -1
    would deadlock any source whose checkpointed frontier is past the
    pipeline cap), and queue the empty-tape replay task that restores the
    snapshot then goes live.  Returns the restored (state_seq, out_seq)."""
    state_seq, out_seq, _old_tape = e["lct"]
    reqs = {s: dict(c)
            for s, c in e["irts"].get(state_seq, {}).items()}
    hist = e["ckpts"] if ckpts is None else ckpts
    with store.transaction():
        store.tset("LCT", (a, ch), (state_seq, out_seq, 0))
        for h in hist:
            store.tappend("LT", ("ckpts", a, ch), (h[0], h[1], 0))
        for s, r in e["irts"].items():
            store.tset("IRT", (a, ch, s),
                       {src: dict(c) for src, c in r.items()})
        for src, chans in reqs.items():
            for sch, nxt in chans.items():
                store.tset("EWT", (src, sch, a, ch), nxt - 1)
    store.ntt_push(a, TapedExecutorTask(
        a, ch, state_seq, out_seq, state_seq, copy.deepcopy(reqs), 0))
    return state_seq, out_seq


def seed_exec_channel_taped(store, a: int, ch: int, e: Dict,
                            lct: Optional[Tuple] = None,
                            ckpts: Optional[List[Tuple]] = None
                            ) -> Tuple[int, int]:
    """Batch restart surgery for one checkpointed exec channel: the batch
    manifest carries the channel's event tape, so everything keeps its
    ORIGINAL tape coordinates — a corrupt snapshot discovered at restore
    time can then fall back through the seeded checkpoint history
    (``_ckpt_fallback``, ultimately state 0 + full tape replay) exactly
    like in-process recovery.  The queued replay targets the END of the
    recorded tape: events past the chosen checkpoint re-run from replayed
    inputs, recovering progress made between the checkpoint and the
    manifest write.  Returns the chosen (state_seq, out_seq)."""
    state_seq, out_seq, tape_pos = tuple(lct if lct is not None
                                         else e["lct"])
    reqs = {s: dict(c)
            for s, c in e["irts"].get(state_seq, {}).items()}
    hist = e["ckpts"] if ckpts is None else ckpts
    with store.transaction():
        store.tset("LCT", (a, ch), (state_seq, out_seq, tape_pos))
        store.tset("LT", ("tape", a, ch), list(e.get("tape") or []))
        store.tset("LT", ("tape_base", a, ch), e.get("tape_base", 0))
        for h in hist:
            store.tappend("LT", ("ckpts", a, ch), tuple(h))
        for s, r in e["irts"].items():
            store.tset("IRT", (a, ch, s),
                       {src: dict(c) for src, c in r.items()})
        for src, chans in reqs.items():
            for sch, nxt in chans.items():
                store.tset("EWT", (src, sch, a, ch), nxt - 1)
    n_exec = sum(1 for ev in store.tape_slice(a, ch, tape_pos)
                 if ev[0] == "exec")
    store.ntt_push(a, TapedExecutorTask(
        a, ch, state_seq, out_seq, state_seq + n_exec,
        copy.deepcopy(reqs), tape_pos))
    return state_seq, out_seq


# -- batch manifest writer -----------------------------------------------------

def update(graph) -> None:
    """Write the current batch resume point; called by the engine after each
    successful checkpoint (and once at durable submit, so a crash before the
    first checkpoint still re-admits as a fresh run).  A failed write is a
    SKIPPED manifest (the previous one stays valid), never a dead query."""
    path = getattr(graph, "resume_manifest", None)
    if not path:
        return
    store = graph.store
    m: Dict = {
        "version": MANIFEST_VERSION,
        "kind": "batch",
        "query_id": graph.query_id,
        "plan_fp": structural_fingerprint(graph),
        "written_at": time.time(),
        "execs": {},
        "sinks": {},
        "est_bytes": getattr(graph, "resume_est_bytes", None),
        "plan_blob": getattr(graph, "resume_plan_blob", None),
    }
    with store.transaction():
        m["execs"] = collect_exec_channels(graph, with_tape=True)
        for info in graph.actors.values():
            if info.blocking_dataset is None:
                continue
            for ch in range(info.channels):
                floor = store.tget("RMT", ("sink", info.id, ch))
                if floor is not None:
                    m["sinks"][(info.id, ch)] = floor
    # manifest-generation journal (RMT("hist",)): /status surfaces the write
    # count per durable query; trimmed drop-and-reappend at the cap so the
    # row class has its in-run GC site (protocol rule QK015)
    top = max((e["lct"][0] for e in m["execs"].values()), default=0)
    journal = list(store.tget("RMT", ("hist",)) or [])
    if len(journal) >= _JOURNAL_KEEP:
        with store.transaction():
            store.tdel("RMT", ("hist",))
            for entry in journal[-(_JOURNAL_KEEP // 2):]:
                store.tappend("RMT", ("hist",), entry)
            store.tappend("RMT", ("hist",), (top, m["written_at"]))
    else:
        store.tappend("RMT", ("hist",), (top, m["written_at"]))
    try:
        # the manifest is the recovery ROOT, not a checkpoint artifact: it
        # gets its own chaos site so ckpt-corruption storms prove restore
        # fallback rather than trivially erasing the thing being resumed
        # (a corrupted/unreadable manifest is the startup janitor's case)
        integrity.write_framed_atomic(path, pickle.dumps(m), site="manifest")
    except OSError as e:
        obs.REGISTRY.counter("resume.manifest_skipped").inc()
        obs.diag(f"[resume] manifest write to {path} skipped: {e!r}")
    # NO lineage GC here (unlike the stream manifest): the batch recovery
    # contract keeps full lineage because it includes the (0,0,0)
    # full-replay fallback — and a batch query's store dies with the query


# -- supervisor-side directory scan + janitor ----------------------------------

def scan(manifest_dir: str) -> List[str]:
    """Batch manifests in a directory, oldest-written first (recovery
    re-admits in that order: FIFO through normal admission, no barging)."""
    try:
        names = sorted(n for n in os.listdir(manifest_dir)
                       if n.startswith("batch-") and n.endswith(".manifest"))
    except OSError:
        return []
    paths = [os.path.join(manifest_dir, n) for n in names]
    paths.sort(key=lambda p: (os.path.getmtime(p) if os.path.exists(p)
                              else 0.0, p))
    return paths


def quarantine_manifest(path: str, reason: str) -> None:
    """Startup-janitor action: an unreadable or foreign-fingerprint manifest
    is moved aside (``.corrupt``) and counted, never allowed to wedge
    ``recover_orphans()`` for the healthy orphans behind it."""
    obs.REGISTRY.counter("resume.quarantined").inc()
    obs.diag(f"[resume] quarantining manifest {path}: {reason}")
    integrity.quarantine(path, reason)


def load_or_quarantine(path: str) -> Optional[Dict]:
    try:
        return load(path)
    except ManifestMismatch as e:
        quarantine_manifest(path, repr(e))
        return None


# -- batch restart surgery -----------------------------------------------------

def apply_resume(graph, m: Dict) -> Dict:
    """Rewire a freshly lowered batch graph to continue from the manifest.

    The graph must have been built with the manifest's query_id on the same
    spill dir (checkpoint/spill namespaces must line up) and lowered from
    the manifest's OWN plan payload or an identical plan — verified via the
    structural fingerprint, loud ``ManifestMismatch`` on drift.

    Surgery, in order:

    1. **Spill verification fixpoint** — every exec-produced spill the
       resumed run will replay (consumer frontier up to producer recovery
       floor, per edge) is read-verified; a missing/corrupt output rewinds
       its producer to the newest checkpoint covering the first broken seq
       (ultimately ``(0, 0, 0)``) so live re-execution re-emits the gap.
       Input-produced spills are never rewound for: their frozen lineages
       recompute them (``_recompute_object``).
    2. **Inputs** — the initial full-tape task is replaced with one
       starting at the min checkpointed-consumer frontier; the GC floor row
       clamps later in-process recovery to the same start.  Everything
       below the frontier that a state-0 consumer still needs replays from
       the HBQ (or recomputes from lineage) — never from a re-read.
    3. **Checkpointed exec channels** — taped seeding in ORIGINAL
       coordinates (recovery point + history + IRT + EWT + the manifest's
       event-tape copy), with history entries NEWER than the chosen
       recovery point dropped.  Because the tape is durable, a corrupt
       snapshot discovered at restore time falls back through older
       checkpoints — ultimately state 0 + full tape replay — exactly like
       in-process recovery.  Sink channels that already EMITTED output
       before their checkpoint restart at state 0 instead: the fresh
       process's result set is empty, and only a from-scratch run
       re-emits the batches below the checkpointed out frontier.
    4. **Non-checkpointed channels (sinks, relays)** — keep their fresh
       state-0 task and queue a ReplayTask covering everything below each
       producer's floor, so sinks rebuild the full seq-keyed result set
       (replay + live re-emission) and an attached client's cursor drains
       exactly the undelivered tail — no duplicate, no missing batch.

    Returns the resume report ({"execs", "inputs", "replay_specs",
    "verified_spills", "corrupt_spills", "sinks"})."""
    if graph.query_id != m["query_id"]:
        raise ManifestMismatch(
            f"graph namespace {graph.query_id!r} != manifest namespace "
            f"{m['query_id']!r}")
    fp = structural_fingerprint(graph)
    if m.get("plan_fp") is not None and fp != m["plan_fp"]:
        raise ManifestMismatch(
            "the resubmitted plan's structural fingerprint differs from "
            "the manifest's — resuming a DIFFERENT query from this "
            f"checkpoint state would corrupt it (manifest {m['plan_fp']!r},"
            f" plan {fp!r})")
    store = graph.store
    missing = [a for (a, _ch) in m["execs"] if a not in graph.actors]
    if missing:
        raise ManifestMismatch(
            f"manifest actors {sorted(set(missing))} are not in the "
            "lowered plan — actor ids diverged")
    input_actors = {info.id for info in graph.actors.values()
                    if info.kind == "input"}
    exec_channels = [(info.id, ch) for info in graph.actors.values()
                     if info.kind == "exec" for ch in range(info.channels)]
    # recovery-point choice per manifest channel, refined by the fixpoint.
    # A sink that already emitted output restarts at state 0 (fresh task
    # from lowering): restoring it mid-stream would leave the batches
    # below its checkpointed out frontier missing from the empty fresh
    # result set forever.
    choice: Dict[Tuple[int, int], Dict] = {}
    for (a, ch), e in m["execs"].items():
        if (graph.actors[a].blocking_dataset is not None
                and tuple(e["lct"])[1] > 0):
            continue
        choice[(a, ch)] = {
            "lct": tuple(e["lct"]),
            "cands": [(0, 0, 0)] + [tuple(h) for h in e["ckpts"]],
            "rewound": False,
        }

    def consumer_reqs(a: int, ch: int) -> Dict:
        c = choice.get((a, ch))
        if c is not None:
            return m["execs"][(a, ch)]["irts"].get(c["lct"][0], {})
        return store.tget("IRT", (a, ch, 0)) or {}

    # spill listings per consumer channel, keyed by (src, sch, seq); taken
    # once up front — probe results below are what decide coverage
    listing: Dict[Tuple[int, int], Dict] = {}
    if graph.hbq is not None:
        for (a, ch) in exec_channels:
            listing[(a, ch)] = {
                (nm[0], nm[1], nm[2]): nm
                for nm in graph.hbq.names_for_target(a, ch)}
    probe: Dict[Tuple, bool] = {}

    def intact(nm) -> bool:
        if nm not in probe:
            # a corrupt file is quarantined (and counted) right here — the
            # resumed run's replay reads only verified names
            probe[nm] = (graph.hbq is not None
                         and graph.hbq.get(nm) is not None)
        return probe[nm]

    changed = True
    while changed:
        changed = False
        for (a, ch) in exec_channels:
            for src, chans in consumer_reqs(a, ch).items():
                if src in input_actors:
                    continue
                for sch, nxt in chans.items():
                    prod = choice.get((src, sch))
                    if prod is None:
                        continue  # producer restarts at 0: re-emits live
                    for s in range(nxt, prod["lct"][1]):
                        nm = listing.get((a, ch), {}).get((src, sch, s))
                        if nm is not None and intact(nm):
                            continue
                        best = max((h for h in prod["cands"] if h[1] <= s),
                                   key=lambda h: h[0])
                        prod["lct"] = tuple(best)
                        prod["rewound"] = True
                        changed = True
                        break
    corrupt = sum(1 for ok in probe.values() if not ok)
    # min checkpointed-consumer frontier per input channel: where the live
    # input tape restarts (state-0 consumers take the older tail from the
    # HBQ replay below, never from a re-read)
    frontier: Dict[Tuple[int, int], int] = {}
    for (a, ch) in choice:
        for src, chans in consumer_reqs(a, ch).items():
            if src not in input_actors:
                continue
            for sch, nxt in chans.items():
                key = (src, sch)
                frontier[key] = min(frontier.get(key, nxt), nxt)
    report: Dict = {"execs": {}, "inputs": {}, "replay_specs": 0,
                    "verified_spills": len(probe),
                    "corrupt_spills": corrupt,
                    "sinks": dict(m.get("sinks") or {})}
    replayed = 0
    # -- inputs: replace the full tape with the post-frontier tail ----------
    for (src, sch), start in sorted(frontier.items()):
        if start <= 0:
            continue
        last = store.tget("LIT", (src, sch), -1)
        store.ntt_remove_channel(src, sch)
        tape = list(range(start, last + 1))
        store.ntt_push(src, TapedInputTask(src, sch, tape))
        # clamp later in-process recovery rebuilds to the same start
        # (engine._recover_channel reads this floor)
        store.tset("LT", ("gc_floor", src, sch), start)
        replayed += len(tape)
        report["inputs"][(src, sch)] = {
            "replayed_segments": len(tape),
            "skipped_segments": max(0, start),
        }
    # -- checkpointed exec channels: taped replay restores the snapshot
    # (falling back through the seeded history if it reads corrupt) and
    # re-runs any tape tail past it
    for (a, ch), c in sorted(choice.items()):
        e = m["execs"][(a, ch)]
        # history newer than the chosen point would restore PAST the
        # verified-coverage rewind — keep only covered entries
        kept = [h for h in c["cands"] if h != (0, 0, 0)
                and h[0] <= c["lct"][0]]
        store.ntt_remove_channel(a, ch)
        state_seq, out_seq = seed_exec_channel_taped(
            store, a, ch, e, lct=c["lct"], ckpts=kept)
        replayed += 1
        report["execs"][(a, ch)] = {"state_seq": state_seq,
                                    "out_seq": out_seq,
                                    "rewound": c["rewound"]}
    # -- state-0 channels: HBQ replay of everything below each producer's
    # floor (intact exec spill, or input spill with lineage-recompute
    # fallback); seqs at/after the floor arrive from live re-execution
    for (a, ch) in exec_channels:
        if (a, ch) in choice:
            continue
        specs = set()
        for src, chans in (store.tget("IRT", (a, ch, 0)) or {}).items():
            for sch, nxt in chans.items():
                if src in input_actors:
                    floor = frontier.get((src, sch), 0)
                    for s in range(nxt, floor):
                        nm = listing.get((a, ch), {}).get((src, sch, s))
                        # an unlisted (async-spill-lost) input output still
                        # recomputes from its frozen lineage
                        specs.add(nm if nm is not None
                                  else (src, sch, s, a, src, ch))
                else:
                    prod = choice.get((src, sch))
                    if prod is None:
                        continue
                    for s in range(nxt, prod["lct"][1]):
                        nm = listing.get((a, ch), {}).get((src, sch, s))
                        if nm is not None and intact(nm):
                            specs.add(nm)
        if specs:
            store.ntt_push(a, ReplayTask(a, ch, sorted(specs)))
            replayed += len(specs)
            report["replay_specs"] += len(specs)
    obs.REGISTRY.counter("resume.replayed_tasks").inc(replayed)
    obs.RECORDER.record(
        "resume.batch", graph.query_id, q=graph.query_id,
        execs=len(report["execs"]), replayed=replayed,
        verified=len(probe), corrupt=corrupt)
    report["replayed_tasks"] = replayed
    return report
