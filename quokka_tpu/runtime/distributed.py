"""Distributed runtime coordinator: multi-process execution of a TaskGraph.

The reference's Coordinator actor (pyquokka/coordinator.py:131-205) serves the
control plane from Redis, places channels on Ray TaskManagers, detects worker
death through Ray, and drives the recovery barrier.  Here:

- the coordinator process serves the graph's ControlStore (store_service),
- channels are round-robin placed onto N spawned worker processes (CLT),
- liveness = heartbeats written through the store; a silent or dead worker
  triggers recovery: its input channels are re-derived from GIT/LT and its
  exec channels are adopted by survivors (checkpoint + tape + HBQ replay),
- blocking-node results ship back as Arrow IPC and land in the same
  ResultDataset the embedded engine fills, so collect() is oblivious.

Workers are spawned (not forked): executor factories/readers/predicates are
picklable by construction (functools.partial over module-level classes).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import signal
import time
from typing import Dict, List, Optional, Tuple

from quokka_tpu import obs
from quokka_tpu.runtime.dataplane import ipc_to_table
from quokka_tpu.runtime.store_service import CoordinatorStore, serve_store
from quokka_tpu.runtime.worker import worker_main

DEFAULT_RUN_TIMEOUT = 600.0


class StallTimeout(TimeoutError):
    """Coordinator run timeout, enriched with the flight-recorder verdict
    (stuck worker + in-flight task) and dump paths."""


def _resolve_timeout(timeout: Optional[float]) -> float:
    """Explicit caller value wins; else QK_COORD_TIMEOUT; else 600 s (the
    historical default) — so tests can observe a hang in seconds instead
    of minutes without threading a parameter through every entry point."""
    if timeout is not None:
        return timeout
    try:
        return float(os.environ.get("QK_COORD_TIMEOUT", DEFAULT_RUN_TIMEOUT))
    except ValueError:
        return DEFAULT_RUN_TIMEOUT


def _flight_streams(cs: CoordinatorStore) -> Dict[str, list]:
    # the coordinator ring is process-global: scope it to this run (several
    # run_distributed calls share one process under pytest), or stale
    # earlier-run events would dominate the report tail and skew the
    # Chrome-trace time origin minutes before the actual run
    streams = cs.flight_streams()
    streams["coordinator"] = obs.RECORDER.snapshot(since=cs.obs_since)
    return streams


def _stall_dump(cs: CoordinatorStore, reason: str):
    """Merge every worker's shipped flight stream with the coordinator's
    own, write Chrome trace + stall report into QK_DUMP_DIR, and return
    (trace_path, report_path, one-line headline naming the stuck worker)."""
    heartbeats, states, inflight, ntt_depth = cs.stall_snapshot()
    dropped = {"coordinator": obs.RECORDER.dropped}
    for w, st in (states or {}).items():
        dropped[f"worker-{w}"] = getattr(st, "dropped", 0)
    return obs.dump_flight(reason, _flight_streams(cs), heartbeats, states,
                           inflight, ntt_depth, dropped=dropped)


def _build_spec(graph) -> Dict:
    actors = {}
    for aid, info in graph.actors.items():
        actors[aid] = {
            "kind": info.kind,
            "channels": info.channels,
            "stage": info.stage,
            "sorted_actor": info.sorted_actor,
            "reader": info.reader,
            "factory": info.executor_factory,
            "targets": info.targets,
            "source_streams": info.source_streams,
            "sorted_by": info.sorted_by,
            "predicate": info.predicate,
            "projection": info.projection,
            "blocking": info.blocking_dataset is not None,
            "channel_major": getattr(info, "channel_major", False),
            "placement": getattr(info, "placement", None),
        }
    from quokka_tpu import config as qconfig

    return {
        "actors": actors,
        "exec_config": graph.exec_config,
        "hbq_path": graph.hbq.path if graph.hbq is not None else None,
        "ckpt_dir": graph.ckpt_dir,
        # None for today's one-query-per-session distributed runs; workers
        # thread it into their engine for namespaced tagging when set
        "query_id": getattr(graph, "query_id", None),
        # spawned children start with default jax config; mirror the parent's
        # x64 mode or float dtypes diverge between the two runtimes
        "x64": qconfig.x64_enabled(),
    }


def _assign_channels(graph, n_workers: int, worker_tags=None):
    """(actor, channel) -> worker, honoring per-actor placement strategies
    (runtime/placement.py); unplaced actors round-robin."""
    from quokka_tpu.runtime.placement import assign_channels

    return assign_channels(graph.actors, n_workers, worker_tags)


def run_distributed(
    graph,
    n_workers: int = 2,
    timeout: Optional[float] = None,
    kill_after_inputs: Optional[Tuple[int, int]] = None,
    heartbeat_timeout: Optional[float] = None,
    external_workers: int = 0,
    bind: str = "127.0.0.1",
    worker_tags=None,
    store_port: int = 0,
) -> None:
    """Execute the graph over worker processes; fills blocking datasets.
    kill_after_inputs=(worker_id, n): SIGKILL that worker once n input seqs
    exist globally — the kill -9 fault-injection path for tests.

    timeout=None resolves to QK_COORD_TIMEOUT (env, seconds) or 600.  On
    timeout — and on unrecoverable worker death — the coordinator dumps the
    merged flight-recorder timeline (Chrome trace + stall report naming the
    stuck worker and its in-flight task) into QK_DUMP_DIR before raising.

    external_workers: additionally expect that many externally-launched
    workers (`python -m quokka_tpu.runtime.worker --store host:port
    --worker-id K` with K >= n_workers) — the multi-HOST deployment path.
    They fetch the plan from the served store; liveness for them is
    heartbeat-based (heartbeat_timeout defaults to 15s when external workers
    are expected), and they must send a first heartbeat within ~120s.
    bind: serve the store/data plane on this interface (the coordinator's
    routable address for cross-machine workers).  Every connection is
    HMAC-authenticated against the cluster token (runtime/rpc.py); external
    daemons must be launched with the same QUOKKA_RPC_TOKEN (carried by
    TPUPodCluster.worker_commands())."""
    from quokka_tpu.runtime.rpc import default_token

    if (
        external_workers > 0
        and graph.hbq is not None
        and graph.exec_config.get("checkpoint_interval")
        and not graph.exec_config.get("checkpoint_store")
    ):
        # no checkpoint_interval -> nothing is ever written, recovery rewinds
        # to state 0 via tape + peer-HBQ pulls and never reads the store, so
        # that configuration stays legal cross-host
        # cross-host adopters load checkpoints by name; a local default dir
        # exists independently on every host, so recovery would read a
        # different (empty) store than the writer's and die mid-adoption —
        # mirror the reference's mandatory S3 checkpoint bucket
        # (pyquokka/core.py:678-685) and refuse up front
        raise ValueError(
            "fault_tolerance with external (multi-host) workers requires "
            'exec_config["checkpoint_store"] to name a store every host can '
            "reach (an fsspec URL or shared mount); the per-host default "
            f"checkpoint dir {graph.ckpt_dir!r} is not shared"
        )
    # resolve (or mint) the cluster token BEFORE spawning workers so children
    # inherit it through the environment
    default_token()
    # promote the graph's embedded store (already populated by lowering) to a
    # served CoordinatorStore: rebind the same table/kv dicts
    cs = CoordinatorStore()
    cs.kv = graph.store.kv
    cs.tables = graph.store.tables
    graph.store = cs
    # scope this run's coordinator flight stream: dumps/exports include the
    # start marker and everything after, nothing from earlier runs
    cs.obs_since = obs.RECORDER.record("coord.start", "run_distributed") - 1
    try:
        server = serve_store(cs, host=bind, port=store_port)
    except OSError:
        if bind in ("127.0.0.1", "0.0.0.0", "::"):
            raise
        # the declared coordinator address may be NAT'd (workers dial a
        # public IP that is not on any local interface): serve all
        # interfaces instead — connections are HMAC-authenticated, so this
        # is exposure of the handshake only
        server = serve_store(cs, host="0.0.0.0", port=store_port)
    procs: Dict[int, mp.Process] = {}
    completed = False
    try:
        total_workers = n_workers + external_workers
        owned = _assign_channels(graph, total_workers, worker_tags)
        with cs.transaction():
            for w, per_actor in owned.items():
                for aid, chs in per_actor.items():
                    for ch in chs:
                        cs.tset("CLT", (aid, ch), w)
        cs.set("expected_workers", total_workers)
        # unique per query session: persistent daemons join each session at
        # most once (a daemon that crashed out of a session must not rejoin
        # it after its channels were adopted by survivors)
        import uuid

        cs.set("session_id", uuid.uuid4().hex)
        spec = pickle.dumps(_build_spec(graph))
        # externally-launched workers fetch plan + ownership from the store
        cs.set("spec", spec)
        for w, per_actor in owned.items():
            cs.set(("owned", w), per_actor)
        ctx = mp.get_context("spawn")
        # local workers connect via loopback even when serving all interfaces
        connect_addr = (
            ("127.0.0.1", server.address[1])
            if server.address[0] in ("0.0.0.0", "::") else server.address
        )
        for w in range(n_workers):
            p = ctx.Process(
                target=worker_main, args=(spec, connect_addr, w, owned[w]),
                daemon=True,
            )
            p.start()
            procs[w] = p
        external_ids = list(range(n_workers, total_workers))
        if external_ids and heartbeat_timeout is None:
            heartbeat_timeout = 15.0
        if kill_after_inputs is not None and kill_after_inputs[0] >= n_workers:
            raise ValueError(
                "kill_after_inputs targets an external worker — only locally "
                "spawned workers (id < n_workers) can be SIGKILLed"
            )
        _coordinate(graph, cs, procs, owned, _resolve_timeout(timeout),
                    kill_after_inputs, heartbeat_timeout, external_ids)
        completed = True
    finally:
        cs.set("SHUTDOWN", True)
        time.sleep(0.05)
        for p in procs.values():
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        # export AFTER the joins: each worker ships its final flight events
        # (task completions, the worker.shutdown marker) when it observes
        # SHUTDOWN, so exporting earlier would truncate every worker track
        export = obs.trace_export_path()
        if completed and export is not None:
            try:
                obs.write_chrome_trace(
                    export, obs.merge_streams(_flight_streams(cs)))
            except OSError as e:
                obs.diag(f"[flight-recorder] trace export to {export} "
                         f"failed: {e}")
        server.close()
    _drain_results(graph, cs)


def _drain_results(graph, cs: CoordinatorStore) -> None:
    for (actor, channel, seq), ipc in sorted(cs.results.items()):
        info = graph.actors.get(actor)
        if info is not None and info.blocking_dataset is not None:
            info.blocking_dataset.append(channel, ipc_to_table(ipc), seq=seq)


def _stage_undone(graph, cs, stage: int) -> bool:
    for info in graph.actors.values():
        if info.stage != stage:
            continue
        for ch in range(info.channels):
            if not cs.scontains("DST", (info.id, ch), "done"):
                return True
    return False


def _all_done(graph, cs) -> bool:
    for info in graph.actors.values():
        for ch in range(info.channels):
            if not cs.scontains("DST", (info.id, ch), "done"):
                return False
    return True


def _coordinate(graph, cs, procs, owned, timeout, kill_after_inputs,
                heartbeat_timeout, external_ids=()) -> None:
    from quokka_tpu.chaos import CHAOS

    all_ids = list(procs) + list(external_ids)
    # chaos plane (QK_CHAOS kill=N): SIGKILL seeded-random LOCAL workers at
    # seeded-random input boundaries — requires fault tolerance, and the
    # plan always leaves at least one survivor to adopt the channels
    chaos_kills = (
        list(CHAOS.plan_worker_kills(list(procs)))
        if CHAOS.enabled and graph.hbq is not None else []
    )
    stages = sorted({a.stage for a in graph.actors.values()})
    stage_idx = 0
    cs.set("STAGE", stages[0])
    t0 = time.time()
    started = set()
    dead: set = set()
    dbg_at = t0
    while True:
        if time.time() - t0 > timeout:
            _, report, headline = _stall_dump(
                cs, f"distributed run exceeded {timeout:.0f}s timeout")
            raise StallTimeout(
                f"distributed run exceeded timeout ({timeout:.0f}s): "
                f"{headline}"
                + (f"; flight report: {report}" if report else ""))
        if os.environ.get("QUOKKA_DEBUG_COORD") and time.time() - dbg_at > 20:
            dbg_at = time.time()
            # snapshot everything before iterating: RPC handler threads
            # mutate these tables concurrently
            dst = dict(cs.tables.get("DST", {}))
            ntt = {k: len(v) for k, v in dict(cs.tables.get("NTT", {})).items()}
            hbs = dict(cs.heartbeats)
            obs.diag(f"[coord] t={int(dbg_at - t0)}s DST={sorted(dst)} "
                     f"NTT={ntt} dead={sorted(dead)} "
                     f"hb={ {w: round(dbg_at - h, 1) for w, h in hbs.items()} }")
        time.sleep(0.05)
        # merge newly registered worker cache addresses for peers to read
        addrs = dict(cs.get("worker_addrs") or {})
        changed = False
        for w in all_ids:
            a = cs.get(f"worker_addr:{w}")
            if a is not None and addrs.get(w) != tuple(a):
                addrs[w] = tuple(a)
                changed = True
            if w not in started and cs.heartbeats.get(w):
                started.add(w)
        if changed:
            cs.set("worker_addrs", addrs)
        # fault injection: SIGKILL a worker once enough input seqs exist
        if kill_after_inputs is not None or chaos_kills:
            total_inputs = sum(
                len(v) for k, v in cs.tables["GIT"].items()
            )
            if kill_after_inputs is not None:
                wid, n = kill_after_inputs
                if total_inputs >= n and procs[wid].is_alive():
                    os.kill(procs[wid].pid, signal.SIGKILL)
                    kill_after_inputs = None
            while chaos_kills and total_inputs >= chaos_kills[0][0]:
                _, wid = chaos_kills.pop(0)
                if wid in dead or not procs[wid].is_alive():
                    continue
                CHAOS.record_kill(f"SIGKILL worker {wid}")
                os.kill(procs[wid].pid, signal.SIGKILL)
        # failure detection: dead process or stale heartbeat.  External
        # (multi-host) workers have no local PID: heartbeat staleness only.
        # ONE sweep collects every death before any recovery runs, so rewind
        # planning sees the whole co-dead set (a consumer on worker A whose
        # tape needs a producer on co-dead worker B requires joint planning).
        now = time.time()
        newly_dead: List[int] = []
        for w in all_ids:
            p = procs.get(w)
            if w in dead:
                continue
            err = cs.kv.get(f"worker_error:{w}")
            if err is not None:
                raise RuntimeError(f"worker {w} crashed at startup:\n{err}")
            if p is None:
                hb = cs.heartbeats.get(w)
                if hb is None:
                    if now - t0 > 120:
                        raise RuntimeError(
                            f"external worker {w} never sent a heartbeat — "
                            "was it launched with the right --store/--worker-id?"
                        )
                    continue
                stale = (
                    heartbeat_timeout is not None
                    and (now - hb) > heartbeat_timeout
                )
                if stale:
                    if graph.hbq is None:
                        _, report, headline = _stall_dump(
                            cs, f"external worker {w} heartbeat silent "
                                f"{now - hb:.1f}s, no fault tolerance")
                        raise RuntimeError(
                            f"external worker {w} went silent and "
                            "fault_tolerance is not enabled"
                            f" — {headline}"
                            + (f"; flight report: {report}" if report else "")
                        )
                    dead.add(w)
                    newly_dead.append(w)
                continue
            if not p.is_alive() and w not in started:
                raise RuntimeError(
                    f"worker {w} exited (code {p.exitcode}) before its first "
                    "heartbeat — likely an import/spawn failure; if launching "
                    "from a script, guard it with if __name__ == '__main__'"
                )
            hb = cs.heartbeats.get(w)
            # stale-heartbeat detection is opt-in: a long jit compile can
            # legitimately stall heartbeats on a loaded machine; process death
            # (kill -9, crash) is always detected
            stale = (
                heartbeat_timeout is not None
                and hb is not None
                and (now - hb) > heartbeat_timeout
            )
            if (not p.is_alive() and w in started) or stale:
                if stale and p.is_alive():
                    # split-brain guard: a stalled-but-alive worker must die
                    # BEFORE its channels are reassigned, or both processes
                    # would execute (and tape) the same channels
                    p.kill()
                    p.join(timeout=10)
                from quokka_tpu.analysis import sanitize

                if (not p.is_alive()
                        and p.exitcode == sanitize.WATCHDOG_EXIT_CODE):
                    # the worker's sanitizer watchdog shot it after its main
                    # loop stopped beating: fail the run loudly, whatever the
                    # fault-tolerance setting — its stack dump is on stderr
                    _, report, _ = _stall_dump(
                        cs, f"worker {w} killed by QK_SANITIZE watchdog")
                    raise RuntimeError(
                        f"worker {w} was killed by the QK_SANITIZE deadlock "
                        f"watchdog (exit {sanitize.WATCHDOG_EXIT_CODE}): its "
                        "main loop made no progress within the deadline; "
                        "all thread stacks were dumped to the worker's stderr"
                        + (f"; flight report: {report}" if report else "")
                    )
                if graph.hbq is None:
                    _, report, headline = _stall_dump(
                        cs, f"worker {w} died without fault tolerance")
                    raise RuntimeError(
                        f"worker {w} died and fault_tolerance is not enabled "
                        "(no HBQ spill to recover from)"
                        + (f" — {headline}; flight report: {report}"
                           if report else "")
                    )
                dead.add(w)
                newly_dead.append(w)
        if newly_dead:
            obs.RECORDER.record("recover", f"workers {sorted(newly_dead)}")
            if not _recover_workers(graph, cs, newly_dead, owned, procs, dead,
                                    all_ids):
                _, report, _ = _stall_dump(
                    cs, f"workers {sorted(newly_dead)} died, no survivor")
                raise RuntimeError(
                    f"workers {newly_dead} died and no survivor exists"
                    + (f"; flight report: {report}" if report else "")
                )
        if _all_done(graph, cs):
            return
        while stage_idx < len(stages) - 1 and not _stage_undone(
            graph, cs, stages[stage_idx]
        ):
            stage_idx += 1
            cs.set("STAGE", stages[stage_idx])


def _recover_workers(graph, cs, dead_workers: List[int], owned, procs, dead,
                     all_ids=None) -> bool:
    """Reassign every dead worker's channels to survivors and trigger
    adoption (reference: coordinator.py:219-421 recovery barrier).  No shared
    disk is assumed: each worker spills to a PRIVATE HBQ dir and adopters
    pull surviving copies from live peers over the data plane (or re-read
    input lineage when no copy survives); executor checkpoints go to the
    checkpoint store (exec_config["checkpoint_store"], an fsspec URL — the
    reference's S3 bucket, core.py:678-685).  Survivors include live
    EXTERNAL workers.

    Rewind planning runs over the UNION of the dead workers' exec channels:
    a consumer on one dead worker whose tape consumes a co-dead producer's
    pre-checkpoint outputs forces that producer to a deeper checkpoint
    (engine.plan_rewinds)."""
    pool = all_ids if all_ids is not None else list(procs)
    survivors = [
        w for w in pool
        if w not in dead and (procs.get(w) is None or procs[w].is_alive())
    ]
    if not survivors:
        return False
    from quokka_tpu.runtime.engine import plan_rewinds

    dead_exec = [
        (aid, ch)
        for dw in dead_workers
        for aid, chs in owned.get(dw, {}).items()
        if graph.actors[aid].kind == "exec"
        for ch in chs
    ]
    choices = plan_rewinds(cs, dead_exec)
    i = 0
    with cs.transaction():
        for dw in dead_workers:
            for aid, chs in owned.get(dw, {}).items():
                for ch in chs:
                    w = survivors[i % len(survivors)]
                    i += 1
                    cs.tset("CLT", (aid, ch), w)
                    owned[w].setdefault(aid, []).append(ch)
                    cs.mailbox_push(
                        w, ("adopt", aid, ch, choices.get((aid, ch)))
                    )
    for dw in dead_workers:
        owned[dw] = {}
    return True
