"""Task types — the unit of scheduling.

Mirrors the reference taxonomy (pyquokka/task.py:47-172): TapedInputTask reads
one lineage entry per step; ExecutorTask advances an operator channel one
input-batch-set at a time; Taped variants replay a recorded tape during
recovery.  Object names are 6-tuples
(source_actor, source_channel, seq, target_actor, partition_fn, target_channel)
— the recovery granularity (pyquokka/task.py:5-40).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


def object_name(src_actor, src_ch, seq, tgt_actor, pfn, tgt_ch) -> Tuple:
    return (src_actor, src_ch, seq, tgt_actor, pfn, tgt_ch)


@dataclasses.dataclass
class TapedInputTask:
    actor: int
    channel: int
    tape: List[int]  # remaining seq numbers to generate, in order
    name = "input"

    def current_seq(self) -> Optional[int]:
        return self.tape[0] if self.tape else None

    def peek_next_seq(self) -> Optional[int]:
        """Seq after the current one (IO prefetch looks one step ahead)."""
        return self.tape[1] if len(self.tape) > 1 else None

    def advance(self) -> "TapedInputTask":
        return TapedInputTask(self.actor, self.channel, self.tape[1:])


@dataclasses.dataclass
class ExecutorTask:
    actor: int
    channel: int
    state_seq: int
    out_seq: int
    # {source_actor: {source_channel: next_seq_needed}}
    input_reqs: Dict[int, Dict[int, int]]
    name = "exec"

    def advance(self, consumed: Dict[int, Dict[int, int]], new_out_seq: int) -> "ExecutorTask":
        reqs = {a: dict(chs) for a, chs in self.input_reqs.items()}
        for a, chs in consumed.items():
            for ch, nxt in chs.items():
                reqs[a][ch] = nxt
        return ExecutorTask(self.actor, self.channel, self.state_seq + 1, new_out_seq, reqs)

    def drop_source(self, actor: int) -> None:
        self.input_reqs.pop(actor, None)


@dataclasses.dataclass
class TapedExecutorTask:
    """Replay variant: re-run an executor channel following its recorded
    lineage tape (LT events from tape_pos on) up to last_state_seq, then
    convert back to a live ExecutorTask.  Queued into NTT by recovery
    (engine._recover_channel) and executed by whichever worker owns the
    channel after reassignment — the reference's exectape path
    (pyquokka/core.py:702-821)."""

    actor: int
    channel: int
    state_seq: int  # restored checkpoint state
    out_seq: int
    last_state_seq: int  # state after the full tape replays
    input_reqs: Dict[int, Dict[int, int]]
    tape_pos: int = 0  # LT offset the replay starts from (checkpoint trim point)
    name = "exectape"


@dataclasses.dataclass
class ReplayTask:
    """Re-push spilled post-partition objects (HBQ) to their targets."""

    actor: int
    channel: int
    replay_specs: List[Tuple]  # object names to re-push
    name = "replay"
