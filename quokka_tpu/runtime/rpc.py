"""Minimal length-prefixed pickle RPC over TCP.

The multi-worker runtime needs two services the reference gets from Redis and
Arrow Flight (pyquokka/tables.py, flight.py): a served control store and a
per-worker batch data plane.  Both are method-call shaped, so one tiny RPC
layer serves them: each request is (method_name, args) pickled with a 4-byte
length prefix; each response is (ok, value_or_exception).

Single-host localhost trust model (same as the reference's unauthenticated
Redis/Flight inside a cluster).  Threaded server: one thread per connection,
so a blocking call from one worker never stalls another's.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Tuple

_LEN = struct.Struct(">I")


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        target = self.server.target  # type: ignore[attr-defined]
        while True:
            try:
                method, args = _recv_msg(self.request)
            except (ConnectionError, EOFError):
                return
            try:
                if method == "__multi__":
                    # atomic batch (transaction): applied under one lock hold
                    with target._lock:
                        out = [getattr(target, m)(*a) for m, a in args]
                else:
                    out = getattr(target, method)(*args)
                _send_msg(self.request, (True, out))
            except Exception as e:  # noqa: BLE001 — ship the error to the caller
                try:
                    _send_msg(self.request, (False, e))
                except Exception:
                    return


class RpcServer:
    """Serve an object's methods.  The object must expose a `_lock` (RLock)
    for `__multi__` atomic batches."""

    def __init__(self, target: Any, host: str = "127.0.0.1", port: int = 0):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _Handler)
        self._srv.target = target  # type: ignore[attr-defined]
        self.address: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class RpcClient:
    """One persistent connection; thread-safe via a per-client lock."""

    def __init__(self, address: Tuple[str, int], timeout: float = 120.0):
        self.address = tuple(address)
        self._sock = socket.create_connection(self.address, timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, method: str, *args):
        with self._lock:
            _send_msg(self._sock, (method, args))
            ok, out = _recv_msg(self._sock)
        if not ok:
            raise out
        return out

    def call_multi(self, calls):
        """[(method, args), ...] applied atomically server-side."""
        with self._lock:
            _send_msg(self._sock, ("__multi__", list(calls)))
            ok, out = _recv_msg(self._sock)
        if not ok:
            raise out
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except Exception:
            pass
