"""Minimal length-prefixed pickle RPC over TCP with an HMAC handshake.

The multi-worker runtime needs two services the reference gets from Redis and
Arrow Flight (pyquokka/tables.py, flight.py): a served control store and a
per-worker batch data plane.  Both are method-call shaped, so one tiny RPC
layer serves them: each request is (method_name, args) pickled with a 4-byte
length prefix; each response is (ok, value_or_exception).

Pickle deserialization is arbitrary code execution, so every connection is
mutually authenticated before the first pickle byte is read: the server sends
a nonce, the client proves knowledge of the cluster token with
HMAC-SHA256(token, "C" + server_nonce + client_nonce), and the server proves
itself back with the "S"-prefixed HMAC over the same nonces.  The token comes
from QUOKKA_RPC_TOKEN; a coordinator that finds none generates one and
publishes it into its own environ so spawned workers inherit it, and
TPUPodCluster.worker_commands() carries it to external daemons.  (This is a
deliberate improvement over the reference's open Redis/Flight ports.)

Threaded server: one thread per connection, so a blocking call from one
worker never stalls another's.

Transient-failure hardening (the chaos plane, quokka_tpu/chaos): every
request carries an idempotency key ``(client_id, req_id)``.  A client whose
connection dies mid-call reconnects with bounded exponential backoff and
RESENDS the same request id; the server keeps each client's last
``(req_id, response)`` and answers a replayed id from that cache without
re-executing — so a retried mutation (ntt_push, result_append, ...)
applies exactly once even when the response was lost in flight.  Exhausted
retries raise ``RpcTransportError`` (transient, runtime/errors.py),
distinct from the fatal ``RpcAuthError``.
"""

from __future__ import annotations

import hmac
import hashlib
import os
import pickle
import secrets
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict
from typing import Any, Optional, Tuple

from quokka_tpu.runtime.errors import RpcTransportError  # noqa: F401 — re-export

_LEN = struct.Struct(">I")
_MAGIC = b"QRPC1"
_NONCE = 16

# per-server cap on remembered clients (each entry: last req id + response);
# a client needs only its LAST response replayable — requests are serial per
# connection — so this bounds memory at one response per live-ish client
_DEDUP_CLIENTS = 4096
# responses whose PICKLED size exceeds this are tombstoned instead of
# cached — but ONLY for methods the server declared re-executable
# (RpcServer(reexecutable=...): idempotent bulk reads like hbq_get_ipc).
# Everything else is always cached whole, whatever its size: a destructive
# call (ntt_pop returning a huge ReplayTask) must never be re-executed on
# retry — a tombstone there would pop and silently DISCARD a second task.
_DEDUP_MAX_RESP_BYTES = 1 << 20
_DEDUP_LARGE = object()  # tombstone: executed, response too big to replay
_DEDUP_WAIT_S = 600.0


class RpcAuthError(ConnectionError):
    """Peer failed the HMAC handshake (wrong or missing cluster token).
    Fatal: deterministic, never retried (NOT a TransientError)."""


def _token_file() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".config", "quokka_tpu", "cluster_token"
    )


def default_token() -> str:
    """The cluster-wide shared secret.  Resolution order: QUOKKA_RPC_TOKEN
    env var; then a per-user token file (so `worker_commands()` printed from
    one process authenticates against a coordinator started in another); else
    mint one, persist it to the file (0600), and publish it into this
    process's environ so mp-spawned children inherit it."""
    tok = os.environ.get("QUOKKA_RPC_TOKEN")
    if tok:
        return tok
    path = _token_file()
    try:
        with open(path) as f:
            tok = f.read().strip()
    except OSError:
        tok = ""
    if not tok:
        tok = secrets.token_hex(16)
        try:
            os.makedirs(os.path.dirname(path), mode=0o700, exist_ok=True)
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(tok)
        except OSError:
            pass  # no writable home: token lives in this process tree only
    os.environ["QUOKKA_RPC_TOKEN"] = tok
    return tok


def _mac(token: str, tag: bytes, nonce_s: bytes, nonce_c: bytes) -> bytes:
    return hmac.new(
        token.encode(), tag + nonce_s + nonce_c, hashlib.sha256
    ).digest()


def _server_handshake(sock: socket.socket, token: str) -> bool:
    nonce_s = secrets.token_bytes(_NONCE)
    sock.sendall(_MAGIC + nonce_s)
    try:
        reply = _recv_exact(sock, _NONCE + 32)
    except ConnectionError:
        return False
    nonce_c, client_mac = reply[:_NONCE], reply[_NONCE:]
    if not hmac.compare_digest(client_mac, _mac(token, b"C", nonce_s, nonce_c)):
        return False
    sock.sendall(_mac(token, b"S", nonce_s, nonce_c))
    return True


def _client_handshake(sock: socket.socket, token: str) -> None:
    head = _recv_exact(sock, len(_MAGIC) + _NONCE)
    if head[: len(_MAGIC)] != _MAGIC:
        raise RpcAuthError("peer is not a quokka RPC server")
    nonce_s = head[len(_MAGIC):]
    nonce_c = secrets.token_bytes(_NONCE)
    sock.sendall(nonce_c + _mac(token, b"C", nonce_s, nonce_c))
    try:
        server_mac = _recv_exact(sock, 32)
    except ConnectionError:
        raise RpcAuthError(
            "server closed the connection during the auth handshake — "
            "QUOKKA_RPC_TOKEN mismatch?"
        ) from None
    if not hmac.compare_digest(server_mac, _mac(token, b"S", nonce_s, nonce_c)):
        raise RpcAuthError("server failed to prove the cluster token")


def _send_msg(sock: socket.socket, obj: Any) -> None:
    _send_raw(sock, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))


def _send_raw(sock: socket.socket, data: bytes) -> None:
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        target = self.server.target  # type: ignore[attr-defined]
        token = self.server.token  # type: ignore[attr-defined]
        dedup = self.server.dedup  # type: ignore[attr-defined]
        dedup_lock = self.server.dedup_lock  # type: ignore[attr-defined]
        try:
            # a silent peer (port scanner, half-open connect) must not pin
            # this handler thread forever waiting on the handshake reply
            self.request.settimeout(10.0)
            if not _server_handshake(self.request, token):
                return  # unauthenticated peer: no pickle is ever read
            # authenticated: long-poll RPCs may legitimately idle far longer
            self.request.settimeout(None)
        except (ConnectionError, OSError, socket.timeout):
            return
        while True:
            try:
                msg = _recv_msg(self.request)
            except (ConnectionError, EOFError):
                return
            try:
                cid, rid, method, args = msg
            except (TypeError, ValueError):
                return  # malformed request shape: drop the connection
            data = self._execute_idempotent(target, dedup, dedup_lock,
                                            cid, rid, method, args)
            try:
                _send_raw(self.request, data)
            except Exception:
                return

    def _execute_idempotent(self, target, dedup, dedup_lock, cid, rid,
                            method, args):
        """At-most-once execution keyed by (client id, request id).

        The dedup entry is installed BEFORE execution as a
        ``threading.Event`` in-progress marker: a retried request that
        arrives while the original is still executing (its connection died
        after send, the client backed off and reconnected faster than the
        call finished) WAITS for the original instead of re-executing the
        mutation concurrently.  After completion the entry becomes the
        PICKLED cached response (a replay ships it without re-pickling) —
        or a tombstone when it is too large to pin, in which case the
        replay re-executes (large responses are idempotent reads by
        invariant, see _DEDUP_MAX_RESP_BYTES).  Returns the pickled
        response bytes to send."""
        entry = None
        run_it = False
        with dedup_lock:
            hit = dedup.get(cid)
            if hit is not None and hit[0] == rid:
                entry = hit[1]
                dedup.move_to_end(cid)
            else:
                entry = threading.Event()
                dedup[cid] = (rid, entry)
                dedup.move_to_end(cid)
                while len(dedup) > _DEDUP_CLIENTS:
                    dedup.popitem(last=False)
                run_it = True
        if not run_it:
            from quokka_tpu import obs

            obs.REGISTRY.counter("rpc.dedup_hit").inc()
            obs.RECORDER.record("rpc.dedup", f"{method}#{rid}")
            if isinstance(entry, threading.Event):
                # the original execution is in flight on another handler
                # thread: wait for it, then answer from its result
                if not entry.wait(_DEDUP_WAIT_S):
                    return pickle.dumps(
                        (False, RpcTransportError(
                            f"request {method}#{rid} still executing after "
                            f"{_DEDUP_WAIT_S:.0f}s")),
                        protocol=pickle.HIGHEST_PROTOCOL)
                with dedup_lock:
                    hit = dedup.get(cid)
                entry = (hit[1] if hit is not None and hit[0] == rid
                         else _DEDUP_LARGE)  # replaced/evicted: fall through
            if entry is not _DEDUP_LARGE:
                return entry  # cached pickled response
            if method not in self.server.reexecutable:  # type: ignore[attr-defined]
                # the cached entry was replaced (client moved on) or
                # evicted, and the method is destructive: re-executing
                # could double-apply — a named error is the only safe
                # answer to this stale retry
                return pickle.dumps(
                    (False, RpcTransportError(
                        f"retry of {method}#{rid} arrived after its cached "
                        "response was replaced — cannot safely re-execute "
                        "a non-idempotent method")),
                    protocol=pickle.HIGHEST_PROTOCOL)
            # tombstone: re-execute (idempotent-read invariant)
        try:
            if method == "__multi__":
                # atomic batch (transaction): one lock hold
                with target._lock:
                    out = [getattr(target, m)(*a) for m, a in args]
            else:
                out = getattr(target, method)(*args)
            resp = (True, out)
        except Exception as e:  # noqa: BLE001 — ship to the caller
            resp = (False, e)
        try:
            data = pickle.dumps(resp, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:  # noqa: BLE001 — ship a NAMED error instead
            # of dying mid-send (which the client would retry forever)
            data = pickle.dumps(
                (False, RuntimeError(
                    f"unpicklable RPC response for {method!r}: {e!r}")),
                protocol=pickle.HIGHEST_PROTOCOL)
        big = (len(data) > _DEDUP_MAX_RESP_BYTES
               and method in self.server.reexecutable)  # type: ignore[attr-defined]
        with dedup_lock:
            cur = dedup.get(cid)
            # don't clobber a NEWER request's entry (we may be a late
            # tombstone re-execution racing the client's next call)
            if cur is None or cur[0] == rid:
                dedup[cid] = (rid, _DEDUP_LARGE if big else data)
                dedup.move_to_end(cid)
        if run_it and isinstance(entry, threading.Event):
            entry.set()
        return data


class RpcServer:
    """Serve an object's methods.  The object must expose a `_lock` (RLock)
    for `__multi__` atomic batches."""

    def __init__(self, target: Any, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None,
                 reexecutable: Optional[frozenset] = None):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _Handler)
        self._srv.target = target  # type: ignore[attr-defined]
        self._srv.token = token or default_token()  # type: ignore[attr-defined]
        # method names whose responses are idempotent bulk reads: safe to
        # re-execute on a retried request id instead of pinning a huge
        # cached response (see _DEDUP_MAX_RESP_BYTES)
        self._srv.reexecutable = frozenset(reexecutable or ())  # type: ignore[attr-defined]
        # client_id -> (last req_id, last response): the retried-request
        # dedup cache, shared across ALL connections (a retry arrives on a
        # fresh connection after the original died)
        self._srv.dedup = OrderedDict()  # type: ignore[attr-defined]
        self._srv.dedup_lock = threading.Lock()  # type: ignore[attr-defined]
        self.address: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class RpcClient:
    """One persistent connection; thread-safe via a per-client lock.

    Every call is accounted in the observability layer (quokka_tpu/obs):
    a per-method counter always, a flight-recorder event when slow, and a
    per-thread "current activity" marker while blocked in the call — a
    wedged transport (the round-5 blocked tcp_recvmsg) never produces a
    completion event, so the marker is what a stall/watchdog dump shows.

    Transient transport failures (peer reset, chaos-injected drops) are
    absorbed transparently: reconnect with exponential backoff and resend
    the SAME request id, which the server dedups.  A reconnect that cannot
    even re-establish TCP+handshake fails fast (the peer is down, not
    flaky) so dead-peer detection in recovery stays bounded; a receive that
    times out is also NOT retried (the server may still be executing —
    retrying would double-apply)."""

    def __init__(self, address: Tuple[str, int], timeout: float = 120.0,
                 token: Optional[str] = None, max_attempts: Optional[int] = None):
        self.address = tuple(address)
        self._timeout = timeout
        self._token = token or default_token()
        self._client_id = secrets.token_hex(8)
        self._req_id = 0
        self._lock = threading.Lock()
        self._max_attempts = max_attempts if max_attempts is not None else int(
            os.environ.get("QK_RPC_ATTEMPTS", "5"))
        self._sock: Optional[socket.socket] = None
        self._connect()  # first connect: auth/refused errors surface raw

    def _connect(self) -> None:
        s = socket.create_connection(self.address, timeout=self._timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _client_handshake(s, self._token)
        except BaseException:
            s.close()
            raise
        self._sock = s

    def _drop_sock(self) -> None:
        import contextlib

        s, self._sock = self._sock, None
        if s is not None:
            with contextlib.suppress(OSError):
                s.close()

    def call(self, method: str, *args):
        from quokka_tpu import obs

        t0 = time.perf_counter()
        with obs.RECORDER.activity(f"rpc:{method}@{self.address[1]}"):
            with self._lock:
                ok, out = self._request(method, args)
        obs.rpc_event(method, time.perf_counter() - t0)
        if not ok:
            raise out
        return out

    def call_multi(self, calls):
        """[(method, args), ...] applied atomically server-side."""
        from quokka_tpu import obs

        t0 = time.perf_counter()
        with obs.RECORDER.activity(f"rpc:__multi__@{self.address[1]}"):
            with self._lock:
                ok, out = self._request("__multi__", list(calls))
        obs.rpc_event("__multi__", time.perf_counter() - t0)
        if not ok:
            raise out
        return out

    def _request(self, method: str, args) -> Tuple[bool, Any]:
        """One idempotent request: retried verbatim (same req id) across
        reconnects until a response arrives or attempts are exhausted.
        Caller holds self._lock."""
        from quokka_tpu import obs
        from quokka_tpu.chaos import CHAOS

        self._req_id += 1
        payload = (self._client_id, self._req_id, method, args)
        delay = 0.05
        last: Optional[BaseException] = None
        for attempt in range(self._max_attempts):
            if attempt:
                obs.REGISTRY.counter("rpc.reconnect").inc()
                obs.RECORDER.record("rpc.retry",
                                    f"{method}@{self.address[1]}",
                                    attempt=attempt, error=repr(last)[:120])
                time.sleep(delay)
                delay = min(delay * 2.0, 1.0)
            try:
                if self._sock is None:
                    self._connect()
            except RpcAuthError:
                raise
            except (ConnectionError, OSError) as e:
                # can't even re-establish TCP+handshake: the peer is gone,
                # not flaky — fail fast (recovery probes must stay bounded)
                raise RpcTransportError(
                    f"rpc {method!r} to {self.address}: reconnect failed: "
                    f"{e!r}") from e
            sock = self._sock
            mode = CHAOS.rpc_fault() if CHAOS.enabled else None
            try:
                if mode == "pre":
                    sock.close()  # injected: connection died before send
                _send_msg(sock, payload)
                if mode == "post":
                    sock.close()  # injected: died before the response
                return _recv_msg(sock)
            except socket.timeout as e:
                # the server may still be executing this request — retrying
                # could double-apply a mutation whose first execution is
                # merely slow, so a timeout is terminal, never retried
                self._drop_sock()
                raise RpcTransportError(
                    f"rpc {method!r} to {self.address} timed out after "
                    f"{self._timeout}s") from e
            except (ConnectionError, EOFError, OSError) as e:
                last = e
                self._drop_sock()
        raise RpcTransportError(
            f"rpc {method!r} to {self.address} failed after "
            f"{self._max_attempts} attempts: {last!r}") from last

    def close(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except Exception:
            pass
