"""Minimal length-prefixed pickle RPC over TCP with an HMAC handshake.

The multi-worker runtime needs two services the reference gets from Redis and
Arrow Flight (pyquokka/tables.py, flight.py): a served control store and a
per-worker batch data plane.  Both are method-call shaped, so one tiny RPC
layer serves them: each request is (method_name, args) pickled with a 4-byte
length prefix; each response is (ok, value_or_exception).

Pickle deserialization is arbitrary code execution, so every connection is
mutually authenticated before the first pickle byte is read: the server sends
a nonce, the client proves knowledge of the cluster token with
HMAC-SHA256(token, "C" + server_nonce + client_nonce), and the server proves
itself back with the "S"-prefixed HMAC over the same nonces.  The token comes
from QUOKKA_RPC_TOKEN; a coordinator that finds none generates one and
publishes it into its own environ so spawned workers inherit it, and
TPUPodCluster.worker_commands() carries it to external daemons.  (This is a
deliberate improvement over the reference's open Redis/Flight ports.)

Threaded server: one thread per connection, so a blocking call from one
worker never stalls another's.
"""

from __future__ import annotations

import hmac
import hashlib
import os
import pickle
import secrets
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Optional, Tuple

_LEN = struct.Struct(">I")
_MAGIC = b"QRPC1"
_NONCE = 16


class RpcAuthError(ConnectionError):
    """Peer failed the HMAC handshake (wrong or missing cluster token)."""


def _token_file() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".config", "quokka_tpu", "cluster_token"
    )


def default_token() -> str:
    """The cluster-wide shared secret.  Resolution order: QUOKKA_RPC_TOKEN
    env var; then a per-user token file (so `worker_commands()` printed from
    one process authenticates against a coordinator started in another); else
    mint one, persist it to the file (0600), and publish it into this
    process's environ so mp-spawned children inherit it."""
    tok = os.environ.get("QUOKKA_RPC_TOKEN")
    if tok:
        return tok
    path = _token_file()
    try:
        with open(path) as f:
            tok = f.read().strip()
    except OSError:
        tok = ""
    if not tok:
        tok = secrets.token_hex(16)
        try:
            os.makedirs(os.path.dirname(path), mode=0o700, exist_ok=True)
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(tok)
        except OSError:
            pass  # no writable home: token lives in this process tree only
    os.environ["QUOKKA_RPC_TOKEN"] = tok
    return tok


def _mac(token: str, tag: bytes, nonce_s: bytes, nonce_c: bytes) -> bytes:
    return hmac.new(
        token.encode(), tag + nonce_s + nonce_c, hashlib.sha256
    ).digest()


def _server_handshake(sock: socket.socket, token: str) -> bool:
    nonce_s = secrets.token_bytes(_NONCE)
    sock.sendall(_MAGIC + nonce_s)
    try:
        reply = _recv_exact(sock, _NONCE + 32)
    except ConnectionError:
        return False
    nonce_c, client_mac = reply[:_NONCE], reply[_NONCE:]
    if not hmac.compare_digest(client_mac, _mac(token, b"C", nonce_s, nonce_c)):
        return False
    sock.sendall(_mac(token, b"S", nonce_s, nonce_c))
    return True


def _client_handshake(sock: socket.socket, token: str) -> None:
    head = _recv_exact(sock, len(_MAGIC) + _NONCE)
    if head[: len(_MAGIC)] != _MAGIC:
        raise RpcAuthError("peer is not a quokka RPC server")
    nonce_s = head[len(_MAGIC):]
    nonce_c = secrets.token_bytes(_NONCE)
    sock.sendall(nonce_c + _mac(token, b"C", nonce_s, nonce_c))
    try:
        server_mac = _recv_exact(sock, 32)
    except ConnectionError:
        raise RpcAuthError(
            "server closed the connection during the auth handshake — "
            "QUOKKA_RPC_TOKEN mismatch?"
        ) from None
    if not hmac.compare_digest(server_mac, _mac(token, b"S", nonce_s, nonce_c)):
        raise RpcAuthError("server failed to prove the cluster token")


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        target = self.server.target  # type: ignore[attr-defined]
        token = self.server.token  # type: ignore[attr-defined]
        try:
            # a silent peer (port scanner, half-open connect) must not pin
            # this handler thread forever waiting on the handshake reply
            self.request.settimeout(10.0)
            if not _server_handshake(self.request, token):
                return  # unauthenticated peer: no pickle is ever read
            # authenticated: long-poll RPCs may legitimately idle far longer
            self.request.settimeout(None)
        except (ConnectionError, OSError, socket.timeout):
            return
        while True:
            try:
                method, args = _recv_msg(self.request)
            except (ConnectionError, EOFError):
                return
            try:
                if method == "__multi__":
                    # atomic batch (transaction): applied under one lock hold
                    with target._lock:
                        out = [getattr(target, m)(*a) for m, a in args]
                else:
                    out = getattr(target, method)(*args)
                _send_msg(self.request, (True, out))
            except Exception as e:  # noqa: BLE001 — ship the error to the caller
                try:
                    _send_msg(self.request, (False, e))
                except Exception:
                    return


class RpcServer:
    """Serve an object's methods.  The object must expose a `_lock` (RLock)
    for `__multi__` atomic batches."""

    def __init__(self, target: Any, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _Handler)
        self._srv.target = target  # type: ignore[attr-defined]
        self._srv.token = token or default_token()  # type: ignore[attr-defined]
        self.address: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class RpcClient:
    """One persistent connection; thread-safe via a per-client lock.

    Every call is accounted in the observability layer (quokka_tpu/obs):
    a per-method counter always, a flight-recorder event when slow, and a
    per-thread "current activity" marker while blocked in the call — a
    wedged transport (the round-5 blocked tcp_recvmsg) never produces a
    completion event, so the marker is what a stall/watchdog dump shows."""

    def __init__(self, address: Tuple[str, int], timeout: float = 120.0,
                 token: Optional[str] = None):
        self.address = tuple(address)
        self._sock = socket.create_connection(self.address, timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _client_handshake(self._sock, token or default_token())
        self._lock = threading.Lock()

    def call(self, method: str, *args):
        from quokka_tpu import obs

        t0 = time.perf_counter()
        with obs.RECORDER.activity(f"rpc:{method}@{self.address[1]}"):
            with self._lock:
                _send_msg(self._sock, (method, args))
                ok, out = _recv_msg(self._sock)
        obs.rpc_event(method, time.perf_counter() - t0)
        if not ok:
            raise out
        return out

    def call_multi(self, calls):
        """[(method, args), ...] applied atomically server-side."""
        from quokka_tpu import obs

        t0 = time.perf_counter()
        with obs.RECORDER.activity(f"rpc:__multi__@{self.address[1]}"):
            with self._lock:
                _send_msg(self._sock, ("__multi__", list(calls)))
                ok, out = _recv_msg(self._sock)
        obs.rpc_event("__multi__", time.perf_counter() - t0)
        if not ok:
            raise out
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except Exception:
            pass
