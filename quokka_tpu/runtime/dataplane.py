"""Socket data plane: per-worker batch cache service.

Plays the role of the reference's per-machine Arrow Flight server
(pyquokka/flight.py:16-339): producers PUSH partitioned batches to the worker
that owns the consuming channel (channel-location table CLT); consumers read
and plan against their LOCAL cache only.  Batches travel as Arrow IPC bytes
and land on-device (bridge.arrow_to_device) at the receiving worker.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

import pyarrow as pa

from quokka_tpu import obs
from quokka_tpu.ops import bridge
from quokka_tpu.runtime.cache import BatchCache
from quokka_tpu.runtime.rpc import RpcClient, RpcServer


def table_to_ipc(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(data)) as r:
        return r.read_all()


class CacheService:
    """RPC target wrapping a worker's local BatchCache for remote do_put,
    plus read access to the worker's private HBQ spill so an adopter
    elsewhere can replay objects this worker produced (the reference
    co-locates ReplayTasks with an HBQ copy, coordinator.py:424-552; here
    the adopter pulls over the data plane instead)."""

    def __init__(self, cache: BatchCache, hbq=None):
        self.cache = cache
        self.hbq = hbq
        self._lock = threading.RLock()  # for RpcServer __multi__ (unused)

    def put_ipc(self, name: Tuple, ipc: bytes, sorted_by=None):
        t0 = time.perf_counter()
        batch = bridge.arrow_to_device(ipc_to_table(ipc), sorted_by=sorted_by)
        self.cache.put(tuple(name), batch)
        # receiving side of a cross-worker push: lands in THIS worker's
        # flight stream (the RPC handler thread runs here)
        obs.RECORDER.record("pull.batch", f"a{name[0]}c{name[1]}s{name[2]}",
                            dur=time.perf_counter() - t0, nbytes=len(ipc))
        obs.REGISTRY.counter("dataplane.recv_bytes").inc(len(ipc))

    def size(self) -> int:
        return self.cache.size()

    def hbq_names_for_target(self, tgt_actor: int, tgt_ch: int):
        if self.hbq is None:
            return []
        return self.hbq.names_for_target(tgt_actor, tgt_ch)

    def hbq_get_ipc(self, name: Tuple) -> Optional[bytes]:
        if self.hbq is None:
            return None
        table = self.hbq.get(tuple(name))
        if table is None:
            return None
        return table_to_ipc(table)


class DataPlaneClient:
    """Push batches to a peer worker's cache."""

    def __init__(self, address: Tuple[str, int], timeout: float = 30.0):
        # shorter than the RPC default: a DEAD REMOTE host must fail a
        # recovery probe in bounded time, and 30s/recv is still ample for
        # large Arrow IPC puts
        self._rpc = RpcClient(address, timeout=timeout)

    def put(self, name: Tuple, batch, sorted_by=None) -> None:
        t0 = time.perf_counter()
        ipc = table_to_ipc(bridge.device_to_arrow(batch))
        self._rpc.call("put_ipc", tuple(name), ipc, sorted_by)
        obs.RECORDER.record("push.batch", f"a{name[0]}c{name[1]}s{name[2]}",
                            dur=time.perf_counter() - t0, nbytes=len(ipc))
        obs.REGISTRY.counter("dataplane.sent_bytes").inc(len(ipc))

    def hbq_names_for_target(self, tgt_actor: int, tgt_ch: int):
        return [tuple(n) for n in
                self._rpc.call("hbq_names_for_target", tgt_actor, tgt_ch)]

    def hbq_get(self, name: Tuple) -> Optional[pa.Table]:
        ipc = self._rpc.call("hbq_get_ipc", tuple(name))
        return None if ipc is None else ipc_to_table(ipc)

    def close(self) -> None:
        self._rpc.close()


def serve_cache(cache: BatchCache, host: str = "127.0.0.1",
                hbq=None) -> RpcServer:
    # hbq_get_ipc responses are whole serialized tables: declare them
    # re-executable so a retried request re-reads the (idempotent) spill
    # instead of pinning megabytes in the server's dedup cache
    return RpcServer(CacheService(cache, hbq=hbq), host=host,
                     reexecutable=frozenset({"hbq_get_ipc"}))
