"""Checksummed artifact framing: every HBQ spill and checkpoint file is
written as ``MAGIC | payload_len | crc | payload`` and verified on read.

Why framing instead of trusting the container format: a truncated Arrow IPC
file raises somewhere deep in pyarrow, a bit-flipped one may silently parse
into WRONG DATA, and a partially-written pickle can unpickle garbage.  The
frame turns all of those into one named, caught-at-the-boundary
``CorruptArtifactError`` — and the recovery protocol treats that as loss
(quarantine the file, regenerate the data), never as data.

Checksum: crc32c (the S3/GCS integrity standard) when a native module is
available, else zlib.crc32 — both 32-bit, both detect the truncation and
bit-flip classes the chaos plane injects; the frame records which was used
so a mixed-environment cluster never misreads a healthy file as corrupt.
"""

from __future__ import annotations

import os
import struct
import zlib

from quokka_tpu.runtime.errors import CorruptArtifactError

try:  # optional native crc32c (google-crc32c / crc32c packages)
    import crc32c as _crc32c_mod

    def _crc32c(data: bytes) -> int:
        return _crc32c_mod.crc32c(data) & 0xFFFFFFFF

    _HAVE_CRC32C = True
except ImportError:
    _HAVE_CRC32C = False

# one magic per checksum algorithm: a reader never guesses which to verify
MAGIC_CRC32C = b"QKA1c"
MAGIC_CRC32 = b"QKA1z"
_HEADER = struct.Struct(">QI")  # payload length, checksum
_MAGIC_LEN = 5
HEADER_LEN = _MAGIC_LEN + _HEADER.size


def checksum(data: bytes) -> int:
    if _HAVE_CRC32C:
        return _crc32c(data)
    return zlib.crc32(data) & 0xFFFFFFFF


def _crc_update(crc: int, data) -> int:
    if _HAVE_CRC32C:
        return _crc32c_mod.crc32c(data, crc) & 0xFFFFFFFF
    return zlib.crc32(data, crc) & 0xFFFFFFFF


def frame(payload: bytes) -> bytes:
    """Wrap payload bytes with magic + length + checksum."""
    magic = MAGIC_CRC32C if _HAVE_CRC32C else MAGIC_CRC32
    return magic + _HEADER.pack(len(payload), checksum(payload)) + payload


def unframe(data: bytes, source: str = "<bytes>") -> bytes:
    """Verify and strip the frame; raises CorruptArtifactError on any
    mismatch (bad magic, truncation, trailing junk, checksum)."""
    if len(data) < HEADER_LEN:
        raise CorruptArtifactError(source, f"truncated header ({len(data)}B)")
    magic = data[:_MAGIC_LEN]
    if magic == MAGIC_CRC32C:
        if not _HAVE_CRC32C:
            raise CorruptArtifactError(
                source, "crc32c-framed artifact but no crc32c module here")
        algo = _crc32c
    elif magic == MAGIC_CRC32:
        def algo(b):
            return zlib.crc32(b) & 0xFFFFFFFF
    else:
        raise CorruptArtifactError(source, f"bad magic {magic!r}")
    length, want = _HEADER.unpack_from(data, _MAGIC_LEN)
    payload = data[HEADER_LEN:]
    if len(payload) != length:
        raise CorruptArtifactError(
            source, f"length mismatch (header {length}, got {len(payload)})")
    got = algo(payload)
    if got != want:
        raise CorruptArtifactError(
            source, f"checksum mismatch (want {want:#010x}, got {got:#010x})")
    return payload


def write_framed_atomic(path: str, payload: bytes,
                        site: str = "spill") -> None:
    """Frame + write + atomic rename: a crashed writer leaves only a tmp
    file, never a partial artifact under the final name.  ``site`` names
    the chaos injection point ("spill" | "ckpt")."""
    data = maybe_corrupt(frame(payload), site)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class _CrcTee:
    """Write-only file passthrough accumulating length + checksum of every
    byte, so a large artifact streams to disk instead of being materialized
    (the Arrow file format is written strictly sequentially, so no backward
    seek ever crosses this wrapper).  close() is a no-op: the caller owns
    the underlying file (it still has a header to patch)."""

    def __init__(self, f):
        self._f = f
        self.length = 0
        self.crc = 0

    def write(self, b) -> int:
        n = self._f.write(b)
        self.crc = _crc_update(self.crc, b)
        self.length += len(b)
        return n

    def tell(self) -> int:
        return self.length

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        pass

    def writable(self) -> bool:
        return True

    def readable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    @property
    def closed(self) -> bool:
        return False


def write_framed_stream(path: str, writer_cb, site: str = "spill") -> None:
    """Framed write for LARGE artifacts: ``writer_cb(filelike)`` streams
    the payload (e.g. pyarrow writing an IPC file) while length + checksum
    accumulate incrementally; the header is patched in afterwards and the
    tmp file renamed into place.  Peak memory is one write buffer, not
    3x the artifact (serialize + copy + concat) like the bytes-based path."""
    magic = MAGIC_CRC32C if _HAVE_CRC32C else MAGIC_CRC32
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(magic + _HEADER.pack(0, 0))  # placeholder header
        tee = _CrcTee(f)
        writer_cb(tee)
        f.flush()
        f.seek(_MAGIC_LEN)
        f.write(_HEADER.pack(tee.length, tee.crc))
    maybe_corrupt_file(tmp, site)
    os.replace(tmp, path)


def read_framed(path: str) -> bytes:
    """Read + verify a framed artifact.  Raises CorruptArtifactError (the
    caller quarantines via ``quarantine``) or OSError (missing file)."""
    with open(path, "rb") as f:
        data = f.read()
    return unframe(data, source=path)


def quarantine(path: str, reason: BaseException) -> None:
    """Move a corrupt artifact aside (``<path>.corrupt``) so the next
    existence probe reports it gone and recovery regenerates the data; the
    bytes are kept for post-mortem.  Counts + records the detection so a
    chaos soak can assert every injected corruption was caught."""
    from quokka_tpu import obs

    obs.REGISTRY.counter("integrity.corrupt").inc()
    obs.RECORDER.record("integrity.corrupt", os.path.basename(path),
                        reason=str(reason)[:200])
    obs.diag(f"[integrity] quarantining corrupt artifact {path}: {reason}")
    try:
        os.replace(path, path + ".corrupt")
    except OSError as e:
        # already gone (raced a GC) — the loss path proceeds either way
        obs.diag(f"[integrity] quarantine rename of {path} skipped: {e}")


def maybe_corrupt(data: bytes, site: str) -> bytes:
    """Chaos hook: the seeded fault plane may hand back a truncated or
    bit-flipped copy of the framed bytes (simulating torn writes / media
    corruption) — a no-op unless QK_CHAOS enables the ``corrupt`` site."""
    from quokka_tpu.chaos import CHAOS

    mangled = CHAOS.corrupt_artifact(data, site)
    return data if mangled is None else mangled


def maybe_corrupt_file(path: str, site: str) -> None:
    """File-level variant for the streaming write path: truncates or
    bit-flips the on-disk tmp file in place (never buffers the artifact)."""
    from quokka_tpu.chaos import CHAOS

    CHAOS.corrupt_file(path, site)
