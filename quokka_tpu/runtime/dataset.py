"""Result dataset: collects blocking-node outputs.

Equivalent of the reference's ArrowDataset Ray actor + client Dataset handle
(pyquokka/quokka_dataset.py:7,66) for the embedded runtime: outputs accumulate
as host Arrow tables keyed by producing channel.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional

import pyarrow as pa


class ResultDataset:
    def __init__(self, name: str = "result"):
        self.name = name
        self._lock = threading.Lock()
        # keyed by (channel, seq): fault-tolerant tape replay re-emits the
        # same seqs, which must overwrite rather than duplicate
        self._tables: Dict[int, Dict[int, pa.Table]] = defaultdict(dict)

    def append(self, channel: int, table: pa.Table, seq: Optional[int] = None) -> None:
        with self._lock:
            if seq is None:
                seq = len(self._tables[channel])
            self._tables[channel][seq] = table

    def to_arrow(self) -> Optional[pa.Table]:
        with self._lock:
            tables = [
                self._tables[ch][s]
                for ch in sorted(self._tables)
                for s in sorted(self._tables[ch])
            ]
        if not tables:
            return None
        # unify dictionary-encoded vs plain string columns across chunks
        tables = [_decode_dicts(t) for t in tables]
        return pa.concat_tables(tables, promote_options="permissive")

    def to_df(self):
        t = self.to_arrow()
        return None if t is None else t.to_pandas()

    def items_since(self, cursor: Dict[int, int]) -> List:
        """Delta view for standing queries (StreamingHandle.poll_deltas):
        ``(channel, seq, table)`` entries with seq > cursor.get(channel, -1),
        in (channel, seq) order.  Replay re-emissions overwrite their seq
        with byte-identical tables, so a cursor-based reader sees each seq
        exactly once."""
        out: List = []
        with self._lock:
            for ch in sorted(self._tables):
                floor = cursor.get(ch, -1)
                for s in sorted(self._tables[ch]):
                    if s > floor:
                        out.append((ch, s, self._tables[ch][s]))
        return out


def _decode_dicts(t: pa.Table) -> pa.Table:
    cols = []
    changed = False
    for c in t.columns:
        if pa.types.is_dictionary(c.type):
            cols.append(c.cast(c.type.value_type))
            changed = True
        else:
            cols.append(c)
    return pa.table(cols, names=t.column_names) if changed else t
