"""Channel placement strategies.

Reference parity: pyquokka/placement_strategy.py:8-36 — the reference decides
how many channels an actor gets and which cluster nodes host them
(SingleChannelStrategy / CustomChannelsStrategy / TaggedCustomChannelsStrategy
/ DatasetStrategy, consumed at quokka_runtime.py:314-368).  Here the same
objects resolve an actor's channel count at plan lowering and pin channels to
worker processes in the distributed runtime's channel-location table
(runtime/distributed._assign_channels); the embedded engine ignores pinning
(one process) but honors the channel counts.

Workers may carry string tags (run_distributed(worker_tags=...), e.g.
{"tpu"} for chip-bearing hosts vs {"io"} for ingest hosts) and
TaggedCustomChannelsStrategy restricts an actor to tagged workers — the
TPU-pod shape where only some hosts should run device-heavy exec channels.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set


class PlacementStrategy:
    """Base: subclasses define the channel count for a cluster size and the
    channel -> worker pinning."""

    def num_channels(
        self, n_workers: int, default_channels: int, worker_tags=None
    ) -> int:
        raise NotImplementedError

    def assign(
        self,
        n_channels: int,
        n_workers: int,
        worker_tags: Optional[Dict[int, Set[str]]] = None,
    ) -> Dict[int, int]:
        """channel -> worker id map."""
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class SingleChannelStrategy(PlacementStrategy):
    """One channel on one worker — final aggregations, global top-k, any
    operator whose state must not shard (reference placement_strategy.py:8)."""

    def num_channels(self, n_workers, default_channels, worker_tags=None):
        return 1

    def assign(self, n_channels, n_workers, worker_tags=None):
        return {0: 0}


class CustomChannelsStrategy(PlacementStrategy):
    """channels_per_worker channels on EVERY worker (reference
    placement_strategy.py:15)."""

    def __init__(self, channels_per_worker: int = 1):
        if channels_per_worker < 1:
            raise ValueError("channels_per_worker must be >= 1")
        self.channels_per_worker = channels_per_worker

    def num_channels(self, n_workers, default_channels, worker_tags=None):
        return self.channels_per_worker * max(1, n_workers)

    def assign(self, n_channels, n_workers, worker_tags=None):
        return {ch: (ch // self.channels_per_worker) % n_workers
                for ch in range(n_channels)}

    def __repr__(self):
        return f"CustomChannelsStrategy({self.channels_per_worker})"


class TaggedCustomChannelsStrategy(CustomChannelsStrategy):
    """channels_per_worker channels on every worker carrying ``tag``
    (reference placement_strategy.py:32): pin device-heavy actors to
    chip-bearing hosts, ingest actors to IO hosts."""

    def __init__(self, channels_per_worker: int = 1, tag: str = "default"):
        super().__init__(channels_per_worker)
        self.tag = tag

    def _tagged(self, n_workers: int, worker_tags) -> List[int]:
        """Workers carrying the tag.  worker_tags=None (no tag declarations
        anywhere, e.g. the embedded engine) treats every worker as tagged —
        consistently in BOTH num_channels and assign, so a plan that lowers
        also places.  A declared tag map with no match is a configuration
        error and raises at both plan and assign time."""
        if worker_tags is None:
            return list(range(n_workers))
        tagged = [
            w for w in range(n_workers) if self.tag in worker_tags.get(w, ())
        ]
        if not tagged:
            raise ValueError(
                f"no worker carries tag {self.tag!r} "
                f"(tags={worker_tags}); cannot place"
            )
        return tagged

    def num_channels(self, n_workers, default_channels, worker_tags=None):
        return self.channels_per_worker * len(
            self._tagged(max(1, n_workers), worker_tags)
        )

    def assign(self, n_channels, n_workers, worker_tags=None):
        tagged = self._tagged(n_workers, worker_tags)
        return {
            ch: tagged[(ch // self.channels_per_worker) % len(tagged)]
            for ch in range(n_channels)
        }

    def __repr__(self):
        return (
            f"TaggedCustomChannelsStrategy({self.channels_per_worker}, "
            f"tag={self.tag!r})"
        )


class DatasetStrategy(PlacementStrategy):
    """One channel per worker — blocking-output collection actors (reference
    placement_strategy.py:24): results materialize on every host, the client
    drains them all."""

    def num_channels(self, n_workers, default_channels, worker_tags=None):
        return max(1, n_workers)

    def assign(self, n_channels, n_workers, worker_tags=None):
        return {ch: ch % n_workers for ch in range(n_channels)}


def assign_channels(
    actors: Dict[int, object],
    n_workers: int,
    worker_tags: Optional[Dict[int, Set[str]]] = None,
) -> Dict[int, Dict[int, List[int]]]:
    """worker -> {actor: [channels]} honoring per-actor placement strategies;
    actors without one round-robin across all workers (the reference's default
    channel spread, quokka_runtime.py:314-368)."""
    owned: Dict[int, Dict[int, List[int]]] = {w: {} for w in range(n_workers)}
    i = 0
    for aid in sorted(actors):
        info = actors[aid]
        strategy = getattr(info, "placement", None)
        if strategy is not None:
            expected = strategy.num_channels(n_workers, info.channels, worker_tags)
            if info.channels != expected:
                # channel counts were fixed at plan lowering against the
                # cluster the context knew about; running against a different
                # worker count (e.g. external_workers added later) would
                # silently break the per-worker placement contract
                raise ValueError(
                    f"actor {aid} was lowered with {info.channels} channels "
                    f"but {strategy!r} wants {expected} for {n_workers} "
                    "workers — build the plan with a QuokkaContext whose "
                    "cluster matches the worker count it will run on"
                )
            pins = strategy.assign(info.channels, n_workers, worker_tags)
            for ch in range(info.channels):
                owned[pins[ch]].setdefault(aid, []).append(ch)
            continue
        for ch in range(info.channels):
            owned[i % n_workers].setdefault(aid, []).append(ch)
            i += 1
    return owned
