"""In-memory batch cache + input planning — the data plane for a worker.

Plays the role of the reference's per-machine Arrow Flight server cache and its
`do_get("cache")` planner (pyquokka/flight.py:96-264): decide which pending
input batches an executor channel should consume next.  Policy preserved from
the reference:
  - only sources at the minimum execution stage are served (flight.py:115-125);
  - per source channel, batches are delivered contiguously by seq;
  - for sorted actors (SAT), delivery follows global (seq, channel)-interleaved
    order so time order is preserved across channels (flight.py:168-206);
  - accumulation: prefer the source actor with the most ready batches, capped
    at max_batches (flight.py:132-145).

Here the cache holds DeviceBatches (already on-chip), so a "get" is zero-copy.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Set, Tuple


def _batch_nbytes(batch) -> int:
    """Approximate device bytes of a batch (data + limbs + validity)."""
    total = batch.valid.nbytes
    for c in batch.columns.values():
        data = getattr(c, "data", None)
        if data is None:
            data = getattr(c, "codes", None)
        if data is not None:
            total += data.nbytes
        hi = getattr(c, "hi", None)
        if hi is not None:
            total += hi.nbytes
    return total


class BatchCache:
    def __init__(self, mem_limit_batches: int = 10_000,
                 mem_limit_bytes: int = 2 << 30,
                 owner: Optional[str] = None):
        # QK_SANITIZE=1: lock-order recorder (analysis/sanitize.py) — the
        # cache lock and the control-store lock are the two runtime-shared
        # locks a data-plane/exec-loop inversion would deadlock on
        from quokka_tpu.analysis import sanitize

        self._lock = sanitize.maybe_instrument(
            "batchcache", threading.Lock())
        # query id in service mode: tags the plan hit/miss counters and
        # flight-recorder events so merged timelines separate queries
        self.owner = owner
        self._data: Dict[Tuple, object] = {}  # 6-tuple name -> DeviceBatch
        # index: (tgt_actor, tgt_ch) -> (src_actor, src_ch) -> set of seqs
        self._index: Dict[Tuple, Dict[Tuple, Set[int]]] = defaultdict(
            lambda: defaultdict(set)
        )
        self.mem_limit_batches = mem_limit_batches
        # byte-based backpressure (reference gates on memory fraction,
        # flight.py:293-297 — a batch COUNT says nothing about memory)
        self.mem_limit_bytes = mem_limit_bytes
        self._bytes: Dict[Tuple, int] = {}
        self._total_bytes = 0

    def put(self, name: Tuple, batch) -> None:
        src_actor, src_ch, seq, tgt_actor, _, tgt_ch = name
        nb = _batch_nbytes(batch)
        with self._lock:
            old = self._bytes.get(name)
            if old is not None:
                self._total_bytes -= old
            self._data[name] = batch  # dedup: latest write wins (flight.py:67-76)
            self._bytes[name] = nb
            self._total_bytes += nb
            self._index[(tgt_actor, tgt_ch)][(src_actor, src_ch)].add(seq)
        # memory ledger OUTSIDE the cache lock (the _account_plan
        # discipline); track replaces on dedup exactly as the dict did
        from quokka_tpu.obs import memplane

        memplane.LEDGER.track(("cache", id(self), name),
                              memplane.SITE_SHUFFLE, nb, query=self.owner)

    def puttable(self) -> bool:
        with self._lock:
            return (
                len(self._data) < self.mem_limit_batches
                and self._total_bytes < self.mem_limit_bytes
            )

    def plan_get(
        self,
        tgt_actor: int,
        tgt_ch: int,
        input_reqs: Dict[int, Dict[int, int]],
        actor_stages: Dict[int, int],
        sorted_actors: Set[int],
        max_batches: int = 8,
        channel_major: Optional[Set[int]] = None,
    ) -> Optional[Tuple[int, List[Tuple]]]:
        """Return (source_actor, [names...]) to consume next, or None."""
        channel_major = channel_major or set()
        plan = None
        with self._lock:
            idx = self._index.get((tgt_actor, tgt_ch))
            if idx:
                candidates = []  # (stage, ready_count, src_actor, [names])
                for src_actor, chans in input_reqs.items():
                    if src_actor in channel_major:
                        names = self._plan_channel_major(idx, src_actor, tgt_actor, tgt_ch, chans, max_batches)
                    elif src_actor in sorted_actors:
                        names = self._plan_sorted(idx, src_actor, tgt_actor, tgt_ch, chans, max_batches)
                    else:
                        names = self._plan_contiguous(idx, src_actor, tgt_actor, tgt_ch, chans, max_batches)
                    if names:
                        candidates.append(
                            (actor_stages.get(src_actor, 0), -len(names), src_actor, names)
                        )
                if candidates:
                    candidates.sort()
                    min_stage = candidates[0][0]
                    candidates = [c for c in candidates if c[0] == min_stage]
                    _, _, src_actor, names = candidates[0]
                    plan = (src_actor, names)
        self._account_plan((tgt_actor, tgt_ch), plan)
        return plan

    def _account_plan(self, tgt: Tuple[int, int], plan) -> None:
        """Cache hit/miss observability, OUTSIDE the cache lock.  Misses are
        recorded only on a hit->miss transition per consumer channel: an
        executor polling for input retries plan_get in a tight loop, and
        per-retry events would flood the flight ring."""
        from quokka_tpu import obs

        state = getattr(self, "_plan_state", None)
        if state is None:
            state = self._plan_state = {}
        # aggregate counters always; per-query twins when owned (GC'd with
        # the query namespace, TaskGraph.cleanup)
        if plan is not None:
            obs.REGISTRY.counter("cache.plan_hit").inc()
            if self.owner:
                obs.REGISTRY.counter(f"cache.plan_hit.{self.owner}").inc()
            obs.RECORDER.record("cache.hit", f"a{tgt[0]}c{tgt[1]}",
                                src=plan[0], batches=len(plan[1]),
                                **({"q": self.owner} if self.owner else {}))
            state[tgt] = True
        else:
            obs.REGISTRY.counter("cache.plan_miss").inc()
            if self.owner:
                obs.REGISTRY.counter(f"cache.plan_miss.{self.owner}").inc()
            if state.get(tgt, True):
                state[tgt] = False
                obs.RECORDER.record(
                    "cache.miss", f"a{tgt[0]}c{tgt[1]}",
                    **({"q": self.owner} if self.owner else {}))

    def _plan_contiguous(self, idx, src_actor, tgt_actor, tgt_ch, chans, max_batches):
        names = []
        for src_ch, next_seq in chans.items():
            have = idx.get((src_actor, src_ch), ())
            s = next_seq
            while s in have and len(names) < max_batches:
                names.append((src_actor, src_ch, s, tgt_actor, src_actor, tgt_ch))
                s += 1
            if len(names) >= max_batches:
                break
        return names

    def _plan_channel_major(self, idx, src_actor, tgt_actor, tgt_ch, chans,
                            max_batches):
        """Range-partitioned producers (parallel sort): channel c's whole
        output precedes channel c+1's.  Exhausted channels are pruned from
        `chans` by the engine (DST+LIT), so serving only the lowest remaining
        channel converges."""
        if not chans:
            return []
        ch = min(chans)
        return self._plan_contiguous(
            idx, src_actor, tgt_actor, tgt_ch, {ch: chans[ch]}, max_batches
        )

    def _plan_sorted(self, idx, src_actor, tgt_actor, tgt_ch, chans, max_batches):
        """Global (seq, channel) order across all source channels; stop at the
        first missing batch so ordering is never violated.  Channels whose
        stream has ended are already pruned from `chans` by the engine (DST +
        LIT check, engine.handle_exec_task), so every frontier seq here will
        eventually exist; the scan jumps frontier-to-frontier — no unbounded
        walk, no convergence guard."""
        names = []
        frontier = dict(chans)  # channel -> next needed seq
        channels = sorted(frontier.keys())
        if not channels:
            return names
        seq = min(frontier.values())
        while len(names) < max_batches:
            for ch in channels:
                if frontier[ch] != seq:
                    continue
                if seq in idx.get((src_actor, ch), ()):
                    names.append((src_actor, ch, seq, tgt_actor, src_actor, tgt_ch))
                    frontier[ch] = seq + 1
                    if len(names) >= max_batches:
                        return names
                else:
                    return names  # hole: stop to preserve order
            future = [f for f in frontier.values() if f > seq]
            if not future:
                break
            seq = min(future)
        return names

    def get(self, name: Tuple):
        with self._lock:
            return self._data.get(name)

    def gc(self, names: Sequence[Tuple]) -> None:
        removed = []
        with self._lock:
            for name in names:
                self._data.pop(name, None)
                nb = self._bytes.pop(name, None)
                if nb is not None:
                    self._total_bytes -= nb
                    removed.append(name)
                src_actor, src_ch, seq, tgt_actor, _, tgt_ch = name
                chans = self._index.get((tgt_actor, tgt_ch))
                if chans is not None:
                    chans[(src_actor, src_ch)].discard(seq)
        from quokka_tpu.obs import memplane

        for name in removed:
            memplane.LEDGER.retire(("cache", id(self), name))

    def release_ledger(self) -> None:
        """Retire every ledger entry this cache still tracks — graph
        teardown is about to free the batches themselves, so anything left
        here is GC'd residency, not a leak."""
        with self._lock:
            names = list(self._bytes.keys())
        from quokka_tpu.obs import memplane

        for name in names:
            memplane.LEDGER.retire(("cache", id(self), name))

    def size(self) -> int:
        with self._lock:
            return len(self._data)

    def flights_info(self):
        with self._lock:
            return sorted(self._data.keys())
