"""Calibrated cost model over the logical DAG.

Per-node cardinality/byte estimates with a strict precedence the rest of
the planner (and the README knob table) promises:

    measured  >  sampled  >  hint

- **measured** — the opstats cardprofile's per-source table
  (``obs/opstats.py record_cardinalities``), keyed by a plan-independent
  *source signature* (reader identity + pushed predicate + projection).
  Plan fingerprints are only known after lowering, so they cannot key a
  figure the optimizer needs; the source signature is computable from the
  logical ``SourceNode`` at plan time and survives every downstream
  rewrite of the plan.  Measured rows are post-predicate actuals; measured
  ``rows_raw`` (pre-predicate scan rows) gives the observed selectivity.
- **sampled** — ``catalog.Catalog.estimate_source``: predicate selectivity
  measured on an 8K-row sample, scaled to the footer row count.
- **hint** — reader ``size_hint()`` bytes over an assumed row width.

Interior nodes propagate with textbook defaults exactly where no
measurement can exist at plan time (the cardprofile records per-plan
operator rows under the *plan* fingerprint, which a different join order
invalidates): filters keep the parent's basis at FILTER_SELECTIVITY,
joins assume FK-into-PK (output ~= probe side), aggregates reduce by
GROUP_REDUCTION.  Every estimate carries its ``basis`` so decisions made
from it are auditable in the explain output.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Optional

from quokka_tpu import logical

# textbook defaults, used exactly where nothing measured or sampled exists
FILTER_SELECTIVITY = 0.25
GROUP_REDUCTION = 0.1
DEFAULT_COL_BYTES = 8.0  # one device column is a fixed-width vector

BASIS_MEASURED = "measured"
BASIS_SAMPLED = "sampled"
BASIS_HINT = "hint"
_RANK = {BASIS_MEASURED: 2, BASIS_SAMPLED: 1, BASIS_HINT: 0}

# seconds-basis ladder (the devprof plane's extension of the precedence
# above): a *measured* figure is a scan time this exact source signature
# actually took on this backend; *roofline* converts estimated bytes
# through the calibrated/observed device bandwidth (obs/devprof.py);
# *hint* divides by a nominal 1 GB/s when nothing is calibrated.  A
# conversion can never be stronger than the cardinality estimate it
# converts, so the final basis is capped by the rows/bytes basis rank.
SECONDS_MEASURED = "seconds(measured)"
SECONDS_ROOFLINE = "seconds(roofline)"
SECONDS_HINT = "seconds(hint)"
_SRANK = {SECONDS_MEASURED: 2, SECONDS_ROOFLINE: 1, SECONDS_HINT: 0}
_SBY_RANK = {2: SECONDS_MEASURED, 1: SECONDS_ROOFLINE, 0: SECONDS_HINT}
_NOMINAL_BW = 1e9


def seconds_usable(basis: str) -> bool:
    """Decision passes prefer seconds over abstract rows×bytes only when
    the figure is at least roofline-grade — a nominal-bandwidth guess is
    not evidence."""
    return _SRANK.get(basis, 0) >= _SRANK[SECONDS_ROOFLINE]


def _weaker(a: str, b: str) -> str:
    """The weaker of two bases — a derived figure is only as strong as its
    weakest input."""
    return a if _RANK.get(a, 0) <= _RANK.get(b, 0) else b


def _reader_identity(reader) -> str:
    """A stable, path-level identity for a source reader.  Deliberately
    ignores mutable scan state (pushed predicate/columns live on the
    signature separately) so the same table scanned by two queries shares
    one identity."""
    parts = [type(reader).__name__]
    path = getattr(reader, "path", None)
    if path is not None:
        if isinstance(path, (list, tuple)):
            parts += [str(p) for p in path]
        else:
            parts.append(str(path))
    else:
        table = getattr(reader, "table", None)
        if table is not None:
            parts.append(",".join(table.schema.names))
            parts.append(str(table.num_rows))
    return "|".join(parts)


def source_signature(reader, predicate=None,
                     projection=None) -> str:
    """Plan-independent key for one (reader, pushed predicate, projection)
    scan.  Computable both at plan time (from the logical SourceNode) and
    at lowering (from ActorInfo), so measured figures recorded under it in
    one run are addressable by the optimizer in the next — regardless of
    what the rest of that plan looked like."""
    pred_sql = predicate.sql() if predicate is not None else ""
    cols = ",".join(sorted(projection)) if projection else "*"
    raw = f"{_reader_identity(reader)}\x00{pred_sql}\x00{cols}"
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


@dataclasses.dataclass
class Estimate:
    """One node's estimated output: rows, bytes, and the basis that
    produced them (``measured``/``sampled``/``hint``)."""

    rows: float
    bytes: Optional[float]
    basis: str

    def width(self, ncols: int) -> float:
        if self.bytes and self.rows > 0:
            return self.bytes / self.rows
        return DEFAULT_COL_BYTES * max(ncols, 1)


@dataclasses.dataclass
class SecondsEstimate:
    """One node's estimated device seconds, the basis that produced the
    conversion (``seconds(measured)``/``seconds(roofline)``/
    ``seconds(hint)``), and the cardinality estimate it converted."""

    seconds: float
    basis: str
    est: Estimate


def _hint_bytes(reader) -> Optional[int]:
    fn = getattr(reader, "size_hint", None)
    if fn is None:
        return None
    try:
        return int(fn())
    except (OSError, ValueError, TypeError):
        return None


class CostModel:
    """Memoized per-node estimator over one logical plan.

    ``catalog`` is shared with the optimizer so sampling work is paid once
    per (reader, predicate); ``profile`` defaults to the persisted
    cardprofile's source table (measured_sources) and can be injected for
    tests."""

    def __init__(self, sub: Dict[int, logical.Node], catalog=None,
                 profile: Optional[Dict[str, dict]] = None):
        self.sub = sub
        self.cat = catalog
        if profile is None:
            from quokka_tpu.obs import opstats

            profile = opstats.measured_sources()
        self.profile = profile or {}
        self._memo: Dict[int, Estimate] = {}
        self._smemo: Dict[int, SecondsEstimate] = {}

    # -- sources -------------------------------------------------------------

    def _estimate_source(self, node: logical.SourceNode) -> Estimate:
        sig = source_signature(node.reader, node.predicate, node.projection)
        rec = self.profile.get(sig)
        if rec and rec.get("rows") is not None:
            return Estimate(float(rec["rows"]),
                            float(rec["bytes"]) if rec.get("bytes") else None,
                            BASIS_MEASURED)
        # a measurement of the bare scan (no predicate) still beats a
        # sample: scale its actual rows by the sampled selectivity
        if node.predicate is not None:
            bare = self.profile.get(
                source_signature(node.reader, None, node.projection))
        else:
            bare = None
        if self.cat is not None:
            sampled = self.cat.estimate_source(node.reader, node.predicate)
        else:
            sampled = None
        if bare and bare.get("rows") is not None and sampled is not None:
            raw = self.cat.estimate_source(node.reader, None)
            if raw and raw > 0:
                sel = min(1.0, sampled / raw)
                rows = float(bare["rows"]) * sel
                b = float(bare["bytes"]) * sel if bare.get("bytes") else None
                return Estimate(rows, b, BASIS_SAMPLED)
        if sampled is not None:
            width = DEFAULT_COL_BYTES * max(len(node.schema), 1)
            return Estimate(float(sampled), float(sampled) * width,
                            BASIS_SAMPLED)
        hint = _hint_bytes(node.reader)
        width = DEFAULT_COL_BYTES * max(len(node.schema), 1)
        if hint:
            rows = float(hint) / width
            sel = FILTER_SELECTIVITY if node.predicate is not None else 1.0
            return Estimate(rows * sel, float(hint) * sel, BASIS_HINT)
        return Estimate(0.0, None, BASIS_HINT)

    # -- interior propagation -------------------------------------------------

    def estimate(self, nid: int) -> Estimate:
        if nid in self._memo:
            return self._memo[nid]
        # seed the memo against (impossible) cycles, then overwrite
        self._memo[nid] = est = self._derive(self.sub[nid])
        return est

    def _derive(self, node: logical.Node) -> Estimate:
        if isinstance(node, logical.SourceNode):
            return self._estimate_source(node)
        if not node.parents:
            return Estimate(0.0, None, BASIS_HINT)
        parent = self.estimate(node.parents[0])
        ncols = max(len(node.schema), 1)
        if isinstance(node, logical.FilterNode):
            return Estimate(parent.rows * FILTER_SELECTIVITY,
                            (parent.bytes * FILTER_SELECTIVITY
                             if parent.bytes else None), parent.basis)
        if isinstance(node, logical.JoinNode):
            build = self.estimate(node.parents[1])
            basis = _weaker(parent.basis, build.basis)
            if node.how in ("semi", "anti"):
                rows = parent.rows * 0.5
            else:
                # FK-into-PK: each probe row matches ~one build row
                rows = max(parent.rows, 1.0)
            width = (parent.width(len(self.sub[node.parents[0]].schema))
                     + build.width(len(self.sub[node.parents[1]].schema)))
            return Estimate(rows, rows * width, basis)
        if isinstance(node, logical.FusedStageNode):
            return self._derive_fused(node)
        if isinstance(node, (logical.AggNode, logical.DistinctNode)):
            keys = getattr(node, "keys", None)
            rows = parent.rows * GROUP_REDUCTION if keys else 1.0
            limit = getattr(node, "limit", None)
            if limit is not None:
                rows = min(rows, float(limit))
            return Estimate(rows, rows * DEFAULT_COL_BYTES * ncols,
                            parent.basis)
        if isinstance(node, logical.TopKNode):
            rows = min(parent.rows, float(node.k))
            return Estimate(rows, rows * parent.width(ncols), parent.basis)
        if isinstance(node, logical.ProjectionNode):
            pcols = max(len(self.sub[node.parents[0]].schema), 1)
            frac = min(1.0, ncols / pcols)
            return Estimate(parent.rows,
                            parent.bytes * frac if parent.bytes else None,
                            parent.basis)
        # Map / Sort / Window / Asof / Shift / Sink: row-preserving (asof
        # probe-aligned; windows row-preserving) — keep the parent's figure
        return Estimate(parent.rows, parent.bytes, parent.basis)

    def _derive_fused(self, node: logical.FusedStageNode) -> Estimate:
        """Replay the member chain the way derive_schema does: member i's
        main input is member i-1's output, joins consume build parents in
        chain order."""
        cur = self.estimate(node.parents[0])
        builds = iter(node.parents[1:])
        for m in node.members:
            if isinstance(m, logical.JoinNode):
                build = self.estimate(next(builds))
                basis = _weaker(cur.basis, build.basis)
                rows = (cur.rows * 0.5 if m.how in ("semi", "anti")
                        else max(cur.rows, 1.0))
                cur = Estimate(rows, rows * cur.width(len(m.schema)), basis)
            elif isinstance(m, logical.FilterNode):
                cur = Estimate(cur.rows * FILTER_SELECTIVITY,
                               (cur.bytes * FILTER_SELECTIVITY
                                if cur.bytes else None), cur.basis)
            elif isinstance(m, logical.AggNode):
                rows = cur.rows * GROUP_REDUCTION if m.keys else 1.0
                cur = Estimate(rows,
                               rows * DEFAULT_COL_BYTES * len(m.schema),
                               cur.basis)
        return cur

    # -- seconds basis (obs/devprof.py calibration) ---------------------------

    def estimate_seconds(self, nid: int) -> SecondsEstimate:
        """Predicted device seconds for one node's output, with strict
        precedence: a directly measured scan time for this exact source
        signature > the roofline conversion (estimated bytes over the
        calibrated/observed bandwidth) > a nominal-bandwidth hint.  The
        basis is capped by the cardinality basis: converting guessed bytes
        through a calibrated peak still yields ``seconds(hint)``."""
        if nid in self._smemo:
            return self._smemo[nid]
        from quokka_tpu.obs import devprof

        est = self.build_bytes(nid)
        node = self.sub[nid]
        nbytes = est.bytes or 0.0
        seconds: Optional[float] = None
        conv = SECONDS_HINT
        if isinstance(node, logical.SourceNode):
            rec = devprof.measured_source_seconds(
                source_signature(node.reader, node.predicate,
                                 node.projection))
            if rec is not None:
                seconds, conv = rec[0], SECONDS_MEASURED
        if seconds is None:
            bw = devprof.planning_bw()
            if bw:
                seconds, conv = nbytes / bw, SECONDS_ROOFLINE
            else:
                seconds, conv = nbytes / _NOMINAL_BW, SECONDS_HINT
        cap = _RANK.get(est.basis, 0)
        if _SRANK[conv] > cap:
            conv = _SBY_RANK[cap]
        self._smemo[nid] = out = SecondsEstimate(seconds, conv, est)
        return out

    # -- convenience ----------------------------------------------------------

    def build_bytes(self, nid: int) -> Estimate:
        """The estimate decisions quote for a join build side: rows plus a
        bytes figure synthesized from width when the basis carried none."""
        est = self.estimate(nid)
        if est.bytes is None:
            ncols = max(len(self.sub[nid].schema), 1)
            est = Estimate(est.rows, est.rows * est.width(ncols), est.basis)
        return est
