"""Runtime re-optimization: mid-query skew re-partitioning.

When the engine observes one channel of an eligible join-build exchange
receiving ``QK_SKEW_RATIO`` times the mean row volume (the same threshold
the explain skew report uses), it rewrites the exchange's ROUTING — no
executor state moves:

- **build edge ("salt" mode)** — batches from sequence ``from_seq`` on have
  the fat channel's partition ids re-dealt round-robin across ALL build
  channels (``salt_pids``).  Earlier sequences already shipped under plain
  hashing and keep their placement; together every build row lands on
  exactly one channel.
- **probe edge ("replicate" mode)** — each probe channel receives its own
  hash partition PLUS a copy of the fat partition (``replicate_parts``).
  Stage gating means the probe stream has not started when the trigger
  fires (the build side must finish first), so replication applies from
  sequence 0.

Inner-join correctness: a build row of a non-fat key sits on its hash
channel, met there by that key's (unreplicated) probe partition — matched
once.  A fat-key build row sits on exactly one (salted) channel, and the
fat probe partition visits every channel — matched once, on whichever
channel holds the build row.  Non-inner joins are ineligible
(decide.plan_adaptive_exchanges): replication breaks the per-channel
completeness their unmatched-row tracking needs.

Determinism under recovery: the adaptation record is written to the ADT
control-store table BEFORE the first salted batch ships (runtime/tables.py
write-order discipline), and replay paths re-read it — a recovering
channel or adopted worker routes every historical sequence exactly as the
adapted run did.  ``QK_ADAPT=0`` disables eligibility and trigger both.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from quokka_tpu import config
from quokka_tpu.ops.batch import DeviceBatch


def _aot(kind, jit_fn, args, statics=()):
    from quokka_tpu.runtime import compileplane

    return compileplane.aot_kernel_call(kind, jit_fn, args, statics)


# ---------------------------------------------------------------------------
# routing kernels (one fused dispatch each; no host syncs on the push path)
# ---------------------------------------------------------------------------


def _salt_pids(pids, fat, n):
    # re-deal the fat partition round-robin by row position: deterministic
    # in batch content, independent of any runtime state
    deal = jnp.arange(pids.shape[0], dtype=pids.dtype) % n
    return jnp.where(pids == fat, deal, pids)


@functools.lru_cache(maxsize=None)
def _salt_jit():
    # jit built on first dispatch, not at import (lint QK001): adaptation
    # is rare, and a module-level jit object races across engine threads
    return functools.partial(jax.jit, static_argnames=("fat", "n"))(_salt_pids)


def salt_pids(pids: jax.Array, fat: int, n_parts: int) -> jax.Array:
    """Partition ids with the fat partition's rows re-dealt across all
    ``n_parts`` channels."""
    return _aot("adapt_salt", _salt_jit(), (pids,), (int(fat), int(n_parts)))


def _replicate_masks(pids, valid, fat, n):
    masks = tuple(((pids == c) | (pids == fat)) & valid for c in range(n))
    counts = tuple(jnp.sum(m.astype(jnp.int32)) for m in masks)
    return masks, counts


@functools.lru_cache(maxsize=None)
def _replicate_jit():
    return functools.partial(jax.jit,
                             static_argnames=("fat", "n"))(_replicate_masks)


def replicate_parts(batch: DeviceBatch, pids: jax.Array, fat: int,
                    n_parts: int) -> List[DeviceBatch]:
    """Per-channel probe parts: channel c's hash partition plus a copy of
    the fat partition.  Masked views over the source batch (the
    split_by_partition masked idiom): one dispatch, async counts, zero
    blocking readbacks."""
    masks, counts = _aot("adapt_replicate", _replicate_jit(),
                         (pids, batch.valid), (int(fat), int(n_parts)))
    return [
        DeviceBatch(batch.columns, m, None, batch.sorted_by).note_count(c)
        for m, c in zip(masks, counts)
    ]


# ---------------------------------------------------------------------------
# trigger predicate (engine-local, plan-time-proven edges only)
# ---------------------------------------------------------------------------


def skewed_channel(hist: Dict[int, int], n_channels: int,
                   ratio: float) -> Optional[int]:
    """The channel whose delivered rows exceed ``ratio`` x the mean across
    all ``n_channels`` (absent channels count zero), or None.  Mirrors the
    opstats edge-skew report so the trigger and the explain section agree
    on what "skewed" means."""
    if n_channels < 2 or not hist:
        return None
    total = sum(hist.values())
    if total < config.adapt_min_rows():
        return None
    mean = total / n_channels
    if mean <= 0:
        return None
    fat, rows = max(hist.items(), key=lambda kv: kv[1])
    if rows / mean >= ratio:
        return int(fat)
    return None


def build_records(fat: int, build_channels: Dict[int, int],
                  ) -> Tuple[dict, dict]:
    """The (build, probe) ADT records for one fired adaptation.
    ``build_channels`` maps the build source's channel -> the next sequence
    it will push (already-shipped sequences keep their original routing)."""
    return (
        {"mode": "salt", "fat": int(fat),
         "from_seq": {int(c): int(s) for c, s in build_channels.items()}},
        {"mode": "replicate", "fat": int(fat), "from_seq": {}},
    )
