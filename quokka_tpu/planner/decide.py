"""Optimizer passes that consume the cost model (planner/cost.py).

Four passes, spliced into ``optimizer.pass_pipeline`` (and therefore into
planck's per-pass verification and planfuzz's cumulative-prefix matrix):

- ``choose_broadcast_cost`` — broadcast-vs-partition by MEASURED build-side
  bytes (``QK_BROADCAST_BYTES``) when the cardprofile has seen this exact
  scan before; cold plans keep the legacy sampled-row threshold
  (``optimizer.BROADCAST_THRESHOLD``) so a fresh process plans identically
  to the pre-planner pipeline.
- ``reorder_joins_cost`` — the greedy smallest-build-first chain ordering
  (optimizer.reorder_joins), fed by cost-model estimates instead of raw
  catalog samples.  Hint-only estimates decline to reorder: a guess is not
  evidence.
- ``size_channels`` — shrink the channel fan-out of exchanges whose
  measured row volume cannot use the default parallelism (fewer channels =
  fewer partitions, fewer per-channel compiles, denser buckets).
- ``plan_adaptive_exchanges`` — mark the join edges where mid-query skew
  re-partitioning (planner/adapt.py) is semantically safe, so the runtime
  trigger never has to reason about plan shape.

Every choice is recorded through a thread-local decision log — begun by
``context._prepare_plan``, attached to the lowered TaskGraph, surfaced in
``explain()`` as the "planner decisions" section — with the measured vs
hinted figures that drove it.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

from quokka_tpu import config, logical, optimizer
from quokka_tpu.planner import cost as cost_mod

# a channel is worth its compile/dispatch overhead only past this many rows
ROWS_PER_CHANNEL = 1 << 17

# nominal exchange fan-out a broadcast build pays (ships to every channel)
BROADCAST_FANOUT = 2.0

# reserved by the runtime salting rewrite; no user plan may emit it
SALT_COLUMN = "__qk_salt"

# ---------------------------------------------------------------------------
# decision log (thread-local: optimize() runs on the submitting thread)
# ---------------------------------------------------------------------------

_TL = threading.local()


def begin_decisions() -> None:
    """Start collecting decisions for the plan being optimized."""
    _TL.log = []


def record(kind: str, **fields) -> None:
    log = getattr(_TL, "log", None)
    if log is not None:
        log.append({"kind": kind, **fields})


def take_decisions() -> List[dict]:
    """Return and clear the collected decisions (empty when collection was
    never begun — direct optimize() calls in tests and the fuzzer)."""
    log = getattr(_TL, "log", None)
    _TL.log = None
    return list(log or [])


def _model(sub: Dict[int, logical.Node]) -> cost_mod.CostModel:
    return cost_mod.CostModel(sub, catalog=optimizer._get_catalog())


# ---------------------------------------------------------------------------
# broadcast vs partition
# ---------------------------------------------------------------------------


def choose_broadcast_cost(sub: Dict[int, logical.Node], sink_id: int) -> None:
    """Measured build bytes under QK_BROADCAST_BYTES -> broadcast; measured
    above -> partition (even when a stale sample says otherwise).  No
    measurement -> the legacy sampled-rows threshold, unchanged."""
    model = _model(sub)
    cat = optimizer._get_catalog()
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        if not isinstance(node, logical.JoinNode) or node.broadcast:
            continue
        if node.how not in ("inner", "semi", "anti", "left"):
            continue
        est = model.build_bytes(node.parents[1])
        if est.basis == cost_mod.BASIS_MEASURED:
            limit = config.broadcast_bytes_threshold()
            fits = est.bytes is not None and est.bytes <= limit
            extra = {}
            # seconds refinement (devprof-calibrated): a build that fits
            # the byte budget still only broadcasts when shipping it
            # everywhere is predicted no slower than partitioning both
            # sides.  Strictly more conservative than the byte threshold
            # alone — it can only flip broadcast->partition.
            build_s = model.estimate_seconds(node.parents[1])
            if cost_mod.seconds_usable(build_s.basis):
                probe_s = model.estimate_seconds(node.parents[0])
                bcast_s = build_s.seconds * BROADCAST_FANOUT
                part_s = build_s.seconds + probe_s.seconds
                extra = {"est_s_basis": build_s.basis,
                         "probe_s_basis": probe_s.basis,
                         "broadcast_s": round(bcast_s, 6),
                         "partition_s": round(part_s, 6)}
                if fits:
                    fits = bcast_s <= part_s
            node.broadcast = fits
            record("broadcast", node=node.describe(),
                   choice="broadcast" if node.broadcast else "partition",
                   basis=est.basis, build_rows=round(est.rows),
                   build_bytes=round(est.bytes or 0),
                   threshold_bytes=limit, **extra)
            continue
        rows = optimizer._estimate_subtree(sub, node.parents[1], cat)
        if rows is not None and rows <= optimizer.BROADCAST_THRESHOLD:
            node.broadcast = True
        record("broadcast", node=node.describe(),
               choice="broadcast" if node.broadcast else "partition",
               basis=est.basis if rows is not None else "unknown",
               build_rows=round(rows) if rows is not None else None,
               threshold_rows=optimizer.BROADCAST_THRESHOLD)


# ---------------------------------------------------------------------------
# join order
# ---------------------------------------------------------------------------


def reorder_joins_cost(sub: Dict[int, logical.Node], sink_id: int) -> None:
    """optimizer.reorder_joins with cost-model estimates.  The estimator
    returns None for hint-only figures, which makes the chain walk bail
    exactly like the legacy sampler does when it cannot sample."""
    model = _model(sub)

    def estimate(nid: int) -> Optional[float]:
        est = model.estimate(nid)
        if est.basis == cost_mod.BASIS_HINT:
            return None
        # prefer predicted device seconds when the conversion is at least
        # roofline-grade (devprof calibrated); seconds are monotone in
        # bytes so this orders wide-but-short builds after narrow ones
        sec = model.estimate_seconds(nid)
        if cost_mod.seconds_usable(sec.basis):
            return sec.seconds
        return est.rows

    def _fmt(nid: int) -> str:
        sec = model.estimate_seconds(nid)
        return (f"{sub[nid].describe()}"
                f" (~{round(model.estimate(nid).rows)} rows,"
                f" ~{sec.seconds:.4f}s {sec.basis})")

    def on_reorder(chain_ids, before, after, basis):
        record("join_order", chain=[sub[j].describe() for j in chain_ids],
               before=[sub[b].describe() for b in before],
               after=[_fmt(b) for b in after],
               basis=basis,
               est_s_basis=(model.estimate_seconds(after[0]).basis
                            if after else None))

    optimizer.reorder_joins(sub, sink_id, estimate=estimate,
                            on_reorder=on_reorder,
                            basis_of=lambda nid: model.estimate(nid).basis)


# ---------------------------------------------------------------------------
# channel sizing
# ---------------------------------------------------------------------------


def size_channels(sub: Dict[int, logical.Node], sink_id: int,
                  exec_channels: int = 2) -> None:
    """Shrink exchange fan-out where MEASURED volume cannot feed the
    default channel count.  Only ever sizes DOWN, only on measured figures
    (cold plans are untouched), and never touches nodes with an explicit
    channel count or a placement pin."""
    if exec_channels < 2:
        return
    model = _model(sub)
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        if not isinstance(node, (logical.JoinNode, logical.AggNode,
                                 logical.DistinctNode)):
            continue
        if node.channels is not None or node.placement is not None:
            continue
        if isinstance(node, logical.AggNode) and not node.keys:
            continue  # keyless aggs already collapse to one final channel
        est = model.estimate(nid)
        if est.basis != cost_mod.BASIS_MEASURED:
            continue
        want = max(1, min(exec_channels,
                          math.ceil(est.rows / ROWS_PER_CHANNEL)))
        if want < exec_channels:
            node.channels = want
            record("channels", node=node.describe(), basis=est.basis,
                   rows=round(est.rows), channels=want,
                   default=exec_channels)


# ---------------------------------------------------------------------------
# adaptive-exchange eligibility
# ---------------------------------------------------------------------------


def plan_adaptive_exchanges(sub: Dict[int, logical.Node],
                            sink_id: int) -> None:
    """Mark joins whose build exchange may be salted mid-query.

    Eligibility is decided HERE, over the logical plan, so the runtime
    trigger (planner/adapt.py) only ever fires on edges proven safe:

    - inner hash joins only.  Salting scatters one build partition across
      every channel and replicates the matching probe slice, which keeps
      inner matches exactly-once but breaks the per-channel completeness
      that left/semi/anti unmatched-tracking needs.
    - non-broadcast (a broadcast build has no partition to salt), and
    - no claimed output order (QK026: replicated probe slices interleave).
    """
    if not config.adapt_enabled():
        return
    eligible = []
    for nid in optimizer._reachable(sub, sink_id):
        node = sub[nid]
        if not isinstance(node, logical.JoinNode):
            continue
        if SALT_COLUMN in node.schema:
            continue
        if (node.how == "inner" and not node.broadcast
                and not node.sorted_by):
            node.adapt_salt = True
            eligible.append(node.describe())
    if eligible:
        record("adapt_mark", joins=eligible,
               skew_ratio=_skew_threshold())


def _skew_threshold() -> float:
    from quokka_tpu.obs import opstats

    return opstats.skew_ratio_threshold()
