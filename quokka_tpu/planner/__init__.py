"""Cost-based adaptive planning (ISSUE 18 / ROADMAP item 2).

Three layers, each consuming the telemetry planes built in PRs 13-17:

- ``cost``   — a calibrated cost model over the logical DAG.  Per-node
  cardinality estimates prefer MEASURED figures (the opstats cardprofile's
  per-source rows/bytes, keyed by a plan-independent source signature)
  over catalog samples over reader ``size_hint()`` guesses.
- ``decide`` — optimizer passes that consume the model: broadcast-vs-
  partition join choice by measured build-side bytes (QK_BROADCAST_BYTES),
  greedy join-order selection for >=3-way chains, per-node channel-count
  sizing from observed row volumes, and plan-time marking of exchange
  edges eligible for runtime adaptation.  Every choice is recorded with
  the figures that drove it (the "planner decisions" explain section).
- ``adapt``  — runtime re-optimization: when the engine observes a build
  exchange edge skewed past QK_SKEW_RATIO mid-query, it salts the fat
  partition across all build channels and replicates the fat probe slice,
  durably recorded in the ADT control-store table so lineage replay and
  chaos recovery route every batch exactly as the adapted run did.
"""

from quokka_tpu.planner import adapt, cost, decide  # noqa: F401
