"""Adaptive-planning smoke: measured feedback flips a plan, skew triggers a
mid-query re-partition, and neither costs correctness or host syncs.

    python -m quokka_tpu.planner.adapt_smoke      (or: make adapt-smoke)

One process, two phases over seeded parquet (explain_smoke idiom: isolated
cardinality profile, env saved/restored):

**Phase A — plan-time feedback.**  A join whose build side is a scan with a
predicate the catalog's head-rows sample MISestimates (ascending-sorted
column, ``w >= 8192``: the sample sees zero matches, the actual output is
most of the table).  The COLD plan must choose broadcast on the sampled
basis; after one run persists measured cardinalities under the scan's
source signature, the WARM plan must flip to partition on the MEASURED
basis (build bytes over ``QK_BROADCAST_BYTES``).  Both runs must agree
bit-exactly, and the flip must be visible in explain()'s "planner
decisions" section with the measured figures.

**Phase B — runtime adaptation.**  A zipfian-keyed build side (one fat key
holding ~80% of rows) behind a 2-channel hash exchange.  The engine's skew
trigger must fire mid-query (an ``adapt_runtime`` record in the decision
log: fat build partition salted, probe partition replicated), the adapted
result must be BIT-EXACT vs the same query under ``QK_ADAPT=0`` (integer
data), and the adaptive run must add ZERO ``shuffle.host_syncs``.

Exit nonzero on any violation, with the observed figures printed.
"""

from __future__ import annotations

import os
import sys
import tempfile


def _write(tmp: str, name: str, table, row_group_size=None):
    import pyarrow.parquet as pq

    path = os.path.join(tmp, name)
    if row_group_size:
        pq.write_table(table, path, row_group_size=row_group_size)
    else:
        pq.write_table(table, path)
    return path


def _flip_tables(tmp: str, seed: int = 20260807):
    """Phase A: fact + an ascending-keyed dim the head sample misjudges."""
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(seed)
    n_fact, n_dim = 100_000, 400_000
    fact = pa.table({
        "fk": r.integers(0, n_dim, n_fact).astype(np.int64),
        "v": r.integers(0, 1000, n_fact).astype(np.int64),
    })
    dim = pa.table({
        "pk": np.arange(n_dim, dtype=np.int64),
        "w": np.arange(n_dim, dtype=np.int64),  # ascending: head sample
        # of ``w >= 8192`` sees ZERO matches; actually ~98% survive
    })
    return (_write(tmp, "fact.parquet", fact, 1 << 16),
            _write(tmp, "dim.parquet", dim, 1 << 16))


def _flip_query(ctx, fact_path, dim_path):
    from quokka_tpu.expression import col

    fact = ctx.read_parquet(fact_path)
    dim = ctx.read_parquet(dim_path).filter(col("w") >= 8192)
    return (fact.join(dim, left_on="fk", right_on="pk")
            .groupby("v").agg_sql("sum(w) as sw, count(*) as n"))


def _skew_tables(tmp: str, seed: int = 20260808):
    """Phase B: a distinct-keyed probe + a build side with ~80% of rows on
    one fat key (hash-partitions onto one channel -> the skew trigger)."""
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(seed)
    n_build, n_keys = 200_000, 1_000
    keys = r.integers(1, n_keys, n_build).astype(np.int64)
    keys[r.random(n_build) < 0.8] = 0  # the fat key
    build = pa.table({
        "k": keys,
        "v": r.integers(0, 1000, n_build).astype(np.int64),
    })
    probe = pa.table({
        "pk": np.arange(n_keys, dtype=np.int64),
        "g": (np.arange(n_keys, dtype=np.int64) % 50),
    })
    # small row groups: the build streams in many batches, so the trigger
    # fires while batches are still in flight (a real MID-query adaptation)
    return (_write(tmp, "probe.parquet", probe),
            _write(tmp, "build.parquet", build, 1 << 15))


def _skew_query(ctx, probe_path, build_path):
    probe = ctx.read_parquet(probe_path)
    build = ctx.read_parquet(build_path)  # right side = build = skewed
    return (probe.join(build, left_on="pk", right_on="k")
            .groupby("g").agg_sql("sum(v) as sv, count(*) as n"))


def _sorted(table, key: str):
    import pyarrow.compute as pc

    return table.take(pc.sort_indices(table, sort_keys=[(key, "ascending")]))


def _decisions(snap, kind: str):
    return [d for d in (snap or {}).get("planner") or []
            if d.get("kind") == kind]


def main() -> int:  # noqa: C901 — linear proof script, explain_smoke idiom
    env_overrides = {
        "QK_MEMPROFILE_DIR": "",
        "QK_CARDPROFILE_DIR": tempfile.mkdtemp(prefix="qk-adapt-card-"),
        "QK_BROADCAST_BYTES": str(1 << 20),
        "QK_SKEW_RATIO": "1.5",
        "QK_ADAPT_MIN_ROWS": "20000",
    }
    saved = {k: os.environ.get(k) for k in
             (*env_overrides, "QK_ADAPT", "QK_BROADCAST_BYTES")}
    os.environ.update(env_overrides)
    os.environ.pop("QK_ADAPT", None)

    def fail(msg: str) -> int:
        sys.stderr.write(f"adapt-smoke: FAIL — {msg}\n")
        return 1

    try:
        from quokka_tpu import QuokkaContext, obs
        from quokka_tpu.service import QueryService

        def run(svc, build_query, *paths):
            ctx = QuokkaContext(io_channels=2, exec_channels=2)
            h = svc.submit(build_query(ctx, *paths))
            table = h.to_arrow(timeout=600)
            return table, h.explain(as_dict=True), h.explain()

        with tempfile.TemporaryDirectory(prefix="qk-adapt-smoke-") as tmp, \
                QueryService(pool_size=2) as svc:
            # ---- phase A: measured feedback flips broadcast->partition ----
            fact_path, dim_path = _flip_tables(tmp)
            cold_t, cold_snap, _ = run(svc, _flip_query, fact_path, dim_path)
            cold = _decisions(cold_snap, "broadcast")
            if not cold:
                return fail("cold plan recorded no broadcast decision")
            if cold[0].get("choice") != "broadcast":
                return fail(f"cold choice {cold[0]} — the head-rows sample "
                            "should have underestimated the build side into "
                            "a broadcast")
            if cold[0].get("basis") == "measured":
                return fail("cold plan claims a measured basis with an "
                            "empty cardinality profile")
            warm_t, warm_snap, warm_text = run(svc, _flip_query,
                                               fact_path, dim_path)
            warm = _decisions(warm_snap, "broadcast")
            if not warm:
                return fail("warm plan recorded no broadcast decision")
            if warm[0].get("basis") != "measured":
                return fail(f"warm decision basis {warm[0].get('basis')!r} "
                            "— measured cardinalities were not picked up")
            if warm[0].get("choice") != "partition":
                return fail(f"warm choice {warm[0]} — measured build bytes "
                            f"({warm[0].get('build_bytes')}) over "
                            "QK_BROADCAST_BYTES must flip to partition")
            if "planner decisions:" not in warm_text \
                    or "basis=measured" not in warm_text:
                return fail("explain() does not render the planner-decision "
                            "flip")
            if not _sorted(cold_t, "v").equals(_sorted(warm_t, "v")):
                return fail("cold (broadcast) and warm (partition) plans "
                            "disagree — the flip changed results")
            print(f"adapt-smoke: plan flip OK — cold "
                  f"{cold[0]['choice']}/{cold[0]['basis']} -> warm "
                  f"{warm[0]['choice']}/{warm[0]['basis']} "
                  f"(build_bytes={warm[0].get('build_bytes')}, "
                  f"threshold={warm[0].get('threshold_bytes')}), results "
                  "bit-exact")

            # ---- phase B: skew triggers a mid-query re-partition ----------
            os.environ["QK_BROADCAST_BYTES"] = "1"  # keep the join an
            # exchange on BOTH the cold and the now-warm measured basis
            probe_path, build_path = _skew_tables(tmp)
            syncs0 = obs.REGISTRY.snapshot().get("shuffle.host_syncs", 0)
            adapt_t, adapt_snap, adapt_text = run(svc, _skew_query,
                                                  probe_path, build_path)
            syncs = obs.REGISTRY.snapshot().get("shuffle.host_syncs",
                                                0) - syncs0
            fired = _decisions(adapt_snap, "adapt_runtime")
            if not fired:
                return fail("the zipfian build never fired the skew "
                            "trigger (no adapt_runtime decision); edges: "
                            f"{(adapt_snap or {}).get('edges')}")
            if not _decisions(adapt_snap, "adapt_mark"):
                return fail("no adapt_mark decision — plan_adaptive_"
                            "exchanges did not arm the join")
            if "RUNTIME adapt" not in adapt_text:
                return fail("explain() does not render the runtime "
                            "adaptation")
            if syncs:
                return fail(f"the adaptive run added {syncs} host sync(s) "
                            "on the push path")
            os.environ["QK_ADAPT"] = "0"
            static_t, static_snap, _ = run(svc, _skew_query,
                                           probe_path, build_path)
            if _decisions(static_snap, "adapt_runtime") \
                    or _decisions(static_snap, "adapt_mark"):
                return fail("QK_ADAPT=0 still armed/fired adaptation")
            if not _sorted(adapt_t, "g").equals(_sorted(static_t, "g")):
                return fail("adapted result differs from the QK_ADAPT=0 "
                            "run — salt+replicate broke exactly-once")
            f0 = fired[0]
            print(f"adapt-smoke: runtime adaptation OK — {f0['edge']} fat "
                  f"channel {f0['fat_channel']} ({f0['fat_rows']} of "
                  f"{f0['total_rows']} rows, ratio {f0['ratio']}), "
                  f"bit-exact vs QK_ADAPT=0, host_syncs delta {syncs}")
        print("adapt-smoke: OK — measured figures flip broadcast->partition,"
              " skew re-partitions mid-query, both bit-exact")
        return 0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    sys.exit(main())
