# quokka-tpu developer entry points.  The lint gate also runs inside tier-1
# (tests/test_lint_clean.py), so `make test` implies `make lint`.

PY ?= python
export JAX_PLATFORMS ?= cpu

.PHONY: lint lint-baseline verify-static plan-fuzz test test-slow sanitize-demo service-smoke chaos-smoke obs-smoke bench-check bench-trend shuffle-smoke fusion-smoke warmup-smoke multichip-smoke stream-smoke mem-smoke explain-smoke health-smoke adapt-smoke resume-smoke durability-smoke devprof-smoke verify

# engine-invariant static analysis; exits nonzero on findings beyond the
# checked-in baseline (quokka_tpu/analysis/baseline.json)
lint:
	$(PY) -m quokka_tpu.analysis.lint quokka_tpu/

# shrink the baseline after fixing findings (never grows it silently: new
# findings still fail `make lint` until fixed or hand-added with a rationale)
lint-baseline:
	$(PY) -m quokka_tpu.analysis.lint quokka_tpu/ --write-baseline

# the full static-analysis plane, exactly as tier-1 runs it: the lint gate
# (baseline'd, wall-time budgeted), the control-store protocol verifier
# (QK014-QK017, NO baseline — violations fail outright), and the qkflow
# engine's known-answer self-check.  The schedex race explorer also proves
# the shipped rewind rule closes the recovery race over a seeded batch.
verify-static:
	$(PY) -m pytest tests/test_lint_clean.py tests/test_lint_rules.py \
		tests/test_flow.py tests/test_protocol.py tests/test_schedex.py \
		tests/test_planck.py \
		-q -p no:cacheprovider
	$(PY) -m quokka_tpu.analysis.protocol quokka_tpu/
	$(PY) -m quokka_tpu.analysis.schedex --seeds 120
	$(PY) -m quokka_tpu.analysis.planck
	$(MAKE) plan-fuzz

# differential optimizer fuzzer: 200 seeded random plans, each planned
# under the full pass pipeline vs every cumulative pass prefix vs
# QK_STAGE_FUSE=0; plans must verify statically (planck QK021-QK024) and
# execute bit-identically to the unoptimized plan on tiny int data.  A
# failing seed prints a ddmin-shrunk 1-minimal repro op list.
plan-fuzz:
	$(PY) -m quokka_tpu.analysis.planfuzz --seeds 200

test:
	$(PY) -m pytest tests/ -q -m 'not slow'

test-slow:
	$(PY) -m pytest tests/ -q -m slow

# watch the deadlock watchdog shoot a wedged two-worker run (exits nonzero
# in seconds, with every thread's stack on stderr)
sanitize-demo:
	QK_SANITIZE=1 QK_SANITIZE_DEADLINE=5 $(PY) tests/sanitize_deadlock_case.py

# watch the stall detector dump the merged flight-recorder timeline for the
# same wedged run: Chrome trace (Perfetto-loadable) + stall report naming
# the stuck worker and its in-flight task, in QK_DUMP_DIR
stall-demo:
	QK_COORD_TIMEOUT=20 $(PY) tests/sanitize_deadlock_case.py

# query-service smoke: tiny-SF TPC-H queries submitted 2-way through a
# persistent QueryService; exits nonzero if the concurrent run wedges, a
# query fails, or a result comes back empty
service-smoke:
	QUOKKA_BENCH_SF=0.01 QUOKKA_BENCH_CACHE=/tmp/quokka_tpu_bench_smoke \
		$(PY) bench.py --service --smoke

# observability smoke: a profiled query's critical-path buckets must sum to
# the measured wall time within 10%, and /metrics + /status must serve a
# live 2-query service run (Prometheus text with per-query histograms)
obs-smoke:
	$(PY) -m quokka_tpu.obs.smoke

# perf-regression gate: run the bench and compare against the newest
# BENCH_r*.json (override with CHECK_ARGS="--against path --threshold 0.2"
# or compare two artifacts offline with CHECK_ARGS="--current path").
# Exits nonzero when any metric regresses beyond its threshold, printing
# the regressed queries' critical-path diffs.
bench-check:
	$(PY) bench.py --check $(CHECK_ARGS)

# shuffle data-plane smoke: a seeded Q3-shaped join+aggregate (two hash
# exchanges) run twice; the warm run must show ZERO blocking host readbacks
# on the push path (shuffle.host_syncs flat) and ZERO real recompiles (the
# sanitizer sentinel), with nonzero shuffle.bytes proving the exchange ran
shuffle-smoke:
	$(PY) -m quokka_tpu.runtime.shuffle_smoke

# whole-stage-fusion smoke: a Q3-shaped linear join chain must plan into a
# FusedStageExecutor (stagefuse.exec > 0), run warm with ZERO real
# recompiles and ZERO blocking host syncs, and match the QK_STAGE_FUSE=0
# re-plan BIT-EXACTLY on integer-valued data (ops/stagefuse.py)
fusion-smoke:
	$(PY) -m quokka_tpu.runtime.fusion_smoke

# compile-plane smoke: run a Q3-shaped query in one process (populating the
# XLA + AOT executable caches and the plan ledger), then again in a FRESH
# process against the populated cache — the fresh replica must pay zero
# real backend compiles and show AOT prewarm/cache hits (cross-restart
# executable persistence, runtime/compileplane.py)
warmup-smoke:
	$(PY) -m quokka_tpu.runtime.warmup_smoke

# timed multichip smoke: tiny-SF TPC-H Q1/Q3/Q5 + tick-asof through the
# mesh execution plane on 8 XLA-forced host devices, each timed against the
# single-device engine.  Exits nonzero unless the scaling artifact is
# written, every line records the kernel strategies that ran
# (ops/strategy.py), the timed shuffle path stays at ZERO blocking host
# syncs, and no query fell back from the mesh to the embedded engine.
multichip-smoke:
	QUOKKA_BENCH_SF=0.01 QUOKKA_BENCH_CACHE=/tmp/quokka_tpu_bench_mc \
		QUOKKA_MULTICHIP_OUT=/tmp/MULTICHIP_timed_smoke.json \
		$(PY) bench.py --multichip --smoke

# streaming-plane smoke: a continuous asof join + a continuous windowed
# aggregate over tailed CSV sources, under a seeded QK_CHAOS kill plan AND
# a SIGKILL of the hosting service mid-stream; the parent resumes both
# streams from their incremental-checkpoint manifests and the merged pane
# deltas must be BIT-EXACT vs the one-shot batch runs, with the resume
# replaying only the post-frontier segment tail (never the whole stream)
stream-smoke:
	$(PY) -m quokka_tpu.streaming.smoke

# memory-plane smoke: a Q3-shaped service query must GC with ZERO leaked
# ledger entries, the device-buffer ledger must reconcile with
# jax.live_arrays() within QK_MEM_RECONCILE (10%), and a second submission
# of the same plan must be admitted on the MEASURED footprint persisted
# under the plan fingerprint, not the size_hint() guess
mem-smoke:
	$(PY) -m quokka_tpu.obs.mem_smoke

# EXPLAIN ANALYZE smoke: a Q3-shaped service query's operator-statistics
# snapshot must reconcile rows end-to-end (scans == parquet rows, every
# exec intake == its in-edges' delivered totals), carry the per-edge skew
# report, add ZERO shuffle.host_syncs, and a second submission of the same
# plan must be admitted on the MEASURED source cardinalities persisted
# under the plan fingerprint
explain-smoke:
	$(PY) -m quokka_tpu.obs.explain_smoke

# device-profiling smoke: a Q3-shaped service query under an isolated
# devprof dir — calibrated peaks persisted per backend fingerprint (foreign
# fingerprints rejected wholesale), static flops/bytes figures for EVERY
# compiled program (fused stages included), finite roofline efficiency for
# every attributed operator, ZERO added host syncs, and a warm re-plan
# whose broadcast decision quotes a seconds(roofline)-basis estimate
devprof-smoke:
	$(PY) -m quokka_tpu.obs.devprof_smoke

# adaptive-planning smoke: a cold plan decides from hints/samples, the warm
# re-plan must FLIP >= 1 decision from the persisted cardinality profile
# (measured basis, visible in explain's planner-decision section), a seeded
# zipfian build must trigger the mid-query skew re-partition, and both the
# flipped plan and the adapted run must be BIT-EXACT vs their static
# counterparts (QK_ADAPT=0) with ZERO added host syncs (planner/adapt.py)
adapt-smoke:
	$(PY) -m quokka_tpu.planner.adapt_smoke

# chaos plane soak: >= 20 seeded mixed-fault runs (RPC drops/delays, flaky
# store calls, worker kills, spill + checkpoint corruption) each asserting
# BIT-EXACT results vs an undisturbed baseline; every injected corruption
# must be detected via checksum.  A failing run prints its QK_CHAOS spec
# and an exact replay command.  Bounded for the 1-core CI box (~1 min).
chaos-smoke:
	QK_COORD_TIMEOUT=240 $(PY) -m quokka_tpu.chaos.soak --runs 20

# durable-batch smoke: two TPC-H-shaped durable queries SIGKILLed mid-run
# in a child service process; a fresh supervisor must re-admit both from
# their crash-consistent resume manifests and finish BIT-EXACT vs the
# undisturbed run with BOUNDED replay (checkpointed frontiers honored,
# skipped input segments > 0), zero added host syncs, zero admission-byte
# or manifest residue
resume-smoke:
	QK_COORD_TIMEOUT=240 $(PY) -m quokka_tpu.service.resume_smoke

# the durability aggregate: every process-death story in one command —
# batch resume, streaming resume, and the full chaos soak (whose cycle
# includes the batch-resume-under-corruption mode)
durability-smoke: resume-smoke stream-smoke chaos-smoke

# health-plane smoke: two service queries polled live — progress must run
# monotone 0->1 (cold on the size_hint basis, warm on the measured
# cardprofile basis with a finite ETA), /history must accumulate samples
# with derived rates, /health must degrade under an injected skew fault and
# recover when it clears, and the whole plane must add ZERO host syncs
health-smoke:
	$(PY) -m quokka_tpu.obs.health_smoke

# cross-round perf trajectory: every committed BENCH_r*.json as one table
# (vs_baseline per round + slope per metric); exits nonzero when a metric
# declined strictly monotonically over its last 3 consecutive rounds — the
# slow leak each individual bench-check stayed inside its threshold on
bench-trend:
	$(PY) bench.py --trend $(TREND_ARGS)

# the pre-merge aggregate: static analysis, tier-1 tests, and the
# observability smokes a PR most often touches.  Heavier planes (chaos,
# resume, streaming, multichip) keep their own entry points above.
verify: verify-static test explain-smoke devprof-smoke
