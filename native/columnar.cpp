// Native host-side columnar helpers for quokka-tpu.
//
// These cover the host chores that sit off the device path and are too slow in
// Python: bulk FNV-1a string hashing (dictionary encoding feeds every string
// join/group-by) and newline scanning for CSV byte-range readers.  Loaded via
// ctypes (quokka_tpu/utils/native.py); Python fallbacks exist everywhere.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstddef>

extern "C" {

// Hash n strings stored as concatenated utf-8 bytes with (n+1) int64 offsets.
// out[i] = FNV-1a 64 of bytes[offsets[i]..offsets[i+1]).
void qk_fnv1a64_many(const uint8_t* bytes, const int64_t* offsets, int64_t n,
                     uint64_t* out) {
    const uint64_t kOffset = 0xcbf29ce484222325ULL;
    const uint64_t kPrime = 0x100000001b3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h ^= (uint64_t)bytes[j];
            h *= kPrime;
        }
        out[i] = h;
    }
}

// Index of the first '\n' in data[0..len), or -1.
int64_t qk_find_newline(const uint8_t* data, int64_t len) {
    for (int64_t i = 0; i < len; ++i) {
        if (data[i] == '\n') return i;
    }
    return -1;
}

// Histogram of partition ids (for host-side shuffle planning): counts[p] +=
// number of ids equal to p.  ids in [0, n_parts).
void qk_partition_histogram(const int32_t* ids, int64_t n, int32_t n_parts,
                            int64_t* counts) {
    for (int32_t p = 0; p < n_parts; ++p) counts[p] = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t p = ids[i];
        if (p >= 0 && p < n_parts) counts[p]++;
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// As-of merge (the streaming SortedAsofExecutor's CPU hot loop).
//
// The reference walks trade/quote frontiers per key inside polars
// (ts_executors.py:324-383 in the reference tree).  Our TPU kernel is the
// sort+scan program in quokka_tpu/ops/asof.py; on the CPU backend that
// program is bottlenecked by XLA:CPU's slow variadic sort (~340 ns/row),
// while the problem is a textbook O(nt+nq) sequential merge — exactly what
// a native host helper is for.  Both sides must be time-sorted ascending;
// the Python wrapper sorts/compacts and maps indices when they are not.
// ---------------------------------------------------------------------------

#include <unordered_map>

extern "C" {

// Backward as-of: out_idx[i] = index of the LAST quote with
// q_time <= t_time[i] and q_key == t_key[i], else -1 (ties included,
// matching polars join_asof backward).
void qk_asof_backward(const int64_t* t_time, const int64_t* t_key, int64_t nt,
                      const int64_t* q_time, const int64_t* q_key, int64_t nq,
                      int32_t* out_idx) {
    std::unordered_map<int64_t, int32_t> last;
    last.reserve(1024);
    int64_t j = 0;
    for (int64_t i = 0; i < nt; ++i) {
        while (j < nq && q_time[j] <= t_time[i]) {
            last[q_key[j]] = (int32_t)j;
            ++j;
        }
        auto it = last.find(t_key[i]);
        out_idx[i] = it == last.end() ? -1 : it->second;
    }
}

// Forward as-of: out_idx[i] = index of the FIRST quote with
// q_time >= t_time[i] and q_key == t_key[i], else -1.  Walks both sides
// descending; inserting quotes in descending index order means the last
// write per key is the smallest qualifying index.
void qk_asof_forward(const int64_t* t_time, const int64_t* t_key, int64_t nt,
                     const int64_t* q_time, const int64_t* q_key, int64_t nq,
                     int32_t* out_idx) {
    std::unordered_map<int64_t, int32_t> first;
    first.reserve(1024);
    int64_t j = nq - 1;
    for (int64_t i = nt - 1; i >= 0; --i) {
        while (j >= 0 && q_time[j] >= t_time[i]) {
            first[q_key[j]] = (int32_t)j;
            --j;
        }
        auto it = first.find(t_key[i]);
        out_idx[i] = it == first.end() ? -1 : it->second;
    }
}

// 1 if a[0..n) is non-decreasing.
int32_t qk_is_sorted_i64(const int64_t* a, int64_t n) {
    for (int64_t i = 1; i < n; ++i) {
        if (a[i] < a[i - 1]) return 0;
    }
    return 1;
}

}  // extern "C"
