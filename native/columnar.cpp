// Native host-side columnar helpers for quokka-tpu.
//
// These cover the host chores that sit off the device path and are too slow in
// Python: bulk FNV-1a string hashing (dictionary encoding feeds every string
// join/group-by) and newline scanning for CSV byte-range readers.  Loaded via
// ctypes (quokka_tpu/utils/native.py); Python fallbacks exist everywhere.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstddef>

extern "C" {

// Hash n strings stored as concatenated utf-8 bytes with (n+1) int64 offsets.
// out[i] = FNV-1a 64 of bytes[offsets[i]..offsets[i+1]).
void qk_fnv1a64_many(const uint8_t* bytes, const int64_t* offsets, int64_t n,
                     uint64_t* out) {
    const uint64_t kOffset = 0xcbf29ce484222325ULL;
    const uint64_t kPrime = 0x100000001b3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
            h ^= (uint64_t)bytes[j];
            h *= kPrime;
        }
        out[i] = h;
    }
}

// Index of the first '\n' in data[0..len), or -1.
int64_t qk_find_newline(const uint8_t* data, int64_t len) {
    for (int64_t i = 0; i < len; ++i) {
        if (data[i] == '\n') return i;
    }
    return -1;
}

// Histogram of partition ids (for host-side shuffle planning): counts[p] +=
// number of ids equal to p.  ids in [0, n_parts).
void qk_partition_histogram(const int32_t* ids, int64_t n, int32_t n_parts,
                            int64_t* counts) {
    for (int32_t p = 0; p < n_parts; ++p) counts[p] = 0;
    for (int64_t i = 0; i < n; ++i) {
        int32_t p = ids[i];
        if (p >= 0 && p < n_parts) counts[p]++;
    }
}

}  // extern "C"
