"""Time-series tier tests: asof join, windows, shift, CEP — pandas oracles
(pandas.merge_asof for asof, manual rolling/session computations otherwise)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.windows import (
    HoppingWindow,
    OnCompletionTrigger,
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
)


def make_ticks(n_trades=3000, n_quotes=6000, n_symbols=5, seed=3):
    r = np.random.default_rng(seed)
    syms = np.array([f"SYM{i}" for i in range(n_symbols)])
    trades = pa.table(
        {
            "time": np.sort(r.integers(0, 100_000, n_trades)).astype(np.int64),
            "symbol": syms[r.integers(0, n_symbols, n_trades)],
            "size": r.integers(1, 500, n_trades).astype(np.int64),
        }
    )
    # unique quote times: duplicate (symbol, time) quotes make the asof result
    # order-dependent in pandas' oracle too (ties are covered by a dedicated
    # deterministic test below)
    qtimes = np.sort(r.choice(100_000, n_quotes, replace=False)).astype(np.int64)
    quotes = pa.table(
        {
            "time": qtimes,
            "symbol": syms[r.integers(0, n_symbols, n_quotes)],
            "bid": r.uniform(10, 20, n_quotes).round(3),
        }
    )
    return trades, quotes


@pytest.fixture(scope="module")
def ticks(tmp_path_factory):
    root = tmp_path_factory.mktemp("ticks")
    trades, quotes = make_ticks()
    tp, qp = str(root / "trades.parquet"), str(root / "quotes.parquet")
    pq.write_table(trades, tp, row_group_size=512)
    pq.write_table(quotes, qp, row_group_size=512)
    return tp, qp, trades.to_pandas(), quotes.to_pandas()


class TestAsof:
    def test_asof_join_parquet(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        q = ctx.read_sorted_parquet(qp, sorted_by="time")
        got = t.join_asof(q, on="time", by="symbol").collect()
        exp = pd.merge_asof(
            tdf.sort_values("time"),
            qdf.sort_values("time"),
            on="time",
            by="symbol",
            direction="backward",
        ).dropna(subset=["bid"])
        got = got.sort_values(["time", "symbol", "size"]).reset_index(drop=True)
        exp = exp.sort_values(["time", "symbol", "size"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_allclose(got.bid.to_numpy(), exp.bid.to_numpy(), rtol=1e-9)

    def test_asof_then_agg(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        q = ctx.read_sorted_parquet(qp, sorted_by="time")
        got = (
            t.join_asof(q, on="time", by="symbol")
            .with_columns_sql("bid * size as notional")
            .groupby("symbol")
            .agg_sql("sum(notional) as total")
            .collect()
        )
        exp = pd.merge_asof(
            tdf.sort_values("time"), qdf.sort_values("time"), on="time",
            by="symbol", direction="backward",
        ).dropna(subset=["bid"])
        exp = (
            (exp.bid * exp["size"]).groupby(exp.symbol).sum().reset_index(name="total")
        )
        got = got.sort_values("symbol").reset_index(drop=True)
        exp = exp.rename(columns={"symbol": "symbol"}).sort_values("symbol").reset_index(drop=True)
        np.testing.assert_allclose(got.total.to_numpy(), exp.total.to_numpy(), rtol=1e-9)


class TestAsofTies:
    def test_equal_time_quote_wins_and_last_duplicate_used(self):
        ctx = QuokkaContext()
        trades = pa.table(
            {"time": np.array([5, 10], dtype=np.int64), "symbol": ["A", "A"]}
        )
        quotes = pa.table(
            {
                "time": np.array([5, 10, 10], dtype=np.int64),
                "symbol": ["A", "A", "A"],
                "bid": [1.0, 2.0, 3.0],
            }
        )
        t = ctx.from_arrow_sorted(trades, sorted_by="time")
        q = ctx.from_arrow_sorted(quotes, sorted_by="time")
        got = t.join_asof(q, on="time", by="symbol").collect().sort_values("time")
        # equal-time quote matches (backward includes ties); among duplicates
        # at the same time, the later row wins
        assert got.bid.tolist() == [1.0, 3.0]


class TestWindows:
    def _oracle_tumbling(self, df, size):
        d = df.copy()
        d["w"] = (d.time // size) * size
        return (
            d.groupby(["symbol", "w"])
            .agg(total=("size", "sum"), n=("size", "size"))
            .reset_index()
        )

    def test_tumbling(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        got = t.window_agg(
            TumblingWindow(10_000), "sum(size) as total, count(*) as n", by="symbol"
        ).collect()
        exp = self._oracle_tumbling(tdf, 10_000)
        got = got.sort_values(["symbol", "window_start"]).reset_index(drop=True)
        exp = exp.sort_values(["symbol", "w"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_array_equal(got.window_start.to_numpy(), exp.w.to_numpy())
        np.testing.assert_array_equal(got.total.to_numpy(), exp.total.to_numpy())
        np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())

    def test_tumbling_completion_trigger(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        got = t.window_agg(
            TumblingWindow(10_000), "sum(size) as total", by="symbol",
            trigger=OnCompletionTrigger(),
        ).collect()
        exp = self._oracle_tumbling(tdf, 10_000)
        assert len(got) == len(exp)

    def test_hopping(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        size, hop = 20_000, 10_000
        got = t.window_agg(
            HoppingWindow(size, hop), "count(*) as n", by="symbol"
        ).collect()
        # oracle: each row belongs to 2 windows
        d = tdf.copy()
        frames = []
        for j in range(size // hop):
            dd = d.copy()
            dd["w"] = (dd.time // hop - j) * hop
            dd = dd[(dd.w >= 0) & (dd.time < dd.w + size)]
            frames.append(dd)
        exp = (
            pd.concat(frames).groupby(["symbol", "w"]).size().reset_index(name="n")
        )
        got = got.sort_values(["symbol", "window_start"]).reset_index(drop=True)
        exp = exp.sort_values(["symbol", "w"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_array_equal(got.n.to_numpy(), exp.n.to_numpy())

    def test_session(self):
        ctx = QuokkaContext()
        t = pa.table(
            {
                "time": np.array([0, 5, 8, 100, 103, 500, 1000, 1004, 1009], dtype=np.int64),
                "symbol": ["A"] * 9,
                "size": np.arange(1, 10, dtype=np.int64),
            }
        )
        s = ctx.from_arrow_sorted(t, sorted_by="time")
        got = s.window_agg(
            SessionWindow(50), "sum(size) as total, count(*) as n", by="symbol"
        ).collect()
        got = got.sort_values("session_start").reset_index(drop=True)
        # sessions: [0,5,8], [100,103], [500], [1000,1004,1009]
        assert got.session_start.tolist() == [0, 100, 500, 1000]
        assert got.session_end.tolist() == [8, 103, 500, 1009]
        assert got.total.tolist() == [6, 9, 6, 24]
        assert got.n.tolist() == [3, 2, 1, 3]

    def test_sliding(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        size = 5_000
        got = t.window_agg(
            SlidingWindow(size), "sum(size) as roll_sum, count(*) as roll_n",
            by="symbol",
        ).collect()
        d = tdf.sort_values(["symbol", "time"]).reset_index(drop=True)
        exp_rows = []
        for sym, g in d.groupby("symbol"):
            times = g.time.to_numpy()
            sizes = g["size"].to_numpy()
            for i in range(len(g)):
                m = (times >= times[i] - size) & (times <= times[i])
                exp_rows.append((sym, times[i], sizes[i], sizes[m].sum(), m.sum()))
        exp = pd.DataFrame(
            exp_rows, columns=["symbol", "time", "size", "roll_sum", "roll_n"]
        )
        got = got.sort_values(["symbol", "time", "size"]).reset_index(drop=True)
        exp = exp.sort_values(["symbol", "time", "size"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_allclose(
            got.roll_sum.to_numpy(), exp.roll_sum.to_numpy(), rtol=1e-6
        )


class TestOrderedMetadata:
    def test_window_output_sorted_by_window_start(self, ticks):
        tp, qp, tdf, qdf = ticks
        ctx = QuokkaContext()
        t = ctx.read_sorted_parquet(tp, sorted_by="time")
        w = t.window_agg(TumblingWindow(10_000), "sum(size) as vol", by="symbol")
        assert w.sorted_by == ["window_start"]

    def test_select_dropping_time_col_demotes_to_plain_stream(self):
        from quokka_tpu.datastream import OrderedStream

        ctx = QuokkaContext()
        t = pa.table({"time": np.arange(5, dtype=np.int64), "v": np.ones(5)})
        s = ctx.from_arrow_sorted(t, sorted_by="time")
        assert isinstance(s.select(["time", "v"]), OrderedStream)
        plain = s.select(["v"])
        assert not isinstance(plain, OrderedStream)

    def test_ordered_select_validates_columns(self):
        ctx = QuokkaContext()
        t = pa.table({"time": np.arange(5, dtype=np.int64), "v": np.ones(5)})
        s = ctx.from_arrow_sorted(t, sorted_by="time")
        with pytest.raises(ValueError):
            s.select(["nope"])


class TestShift:
    def test_shift_by_key(self):
        ctx = QuokkaContext()
        t = pa.table(
            {
                "time": np.arange(12, dtype=np.int64),
                "sym": (["A", "B"] * 6),
                "px": np.arange(12, dtype=np.float64) * 1.5,
            }
        )
        s = ctx.from_arrow_sorted(t, sorted_by="time")
        got = s.shift("px", n=1, by="sym").collect()
        df = t.to_pandas()
        df["px_shifted_1"] = df.groupby("sym").px.shift(1)
        got = got.sort_values("time").reset_index(drop=True)
        exp = df.sort_values("time").reset_index(drop=True)
        np.testing.assert_allclose(
            got.px_shifted_1.to_numpy(), exp.px_shifted_1.to_numpy(), equal_nan=True
        )


class TestCEP:
    def test_rise_pattern(self):
        ctx = QuokkaContext()
        px = np.array([10, 11, 9, 12, 13, 8, 9, 10, 14, 7], dtype=np.float64)
        t = pa.table(
            {
                "time": np.arange(10, dtype=np.int64),
                "sym": ["A"] * 10,
                "px": px,
            }
        )
        s = ctx.from_arrow_sorted(t, sorted_by="time")
        events = [
            ("low", "px < 10"),
            ("rise", "px > low.px + 2"),
        ]
        got = s.pattern_recognize(events, within=5, by="sym").collect()
        got = got.sort_values("low_time").reset_index(drop=True)
        # low at t=2 (px 9) -> first rise px > 11 within 5: t=3 (12)
        # low at t=5 (px 8) -> rise px > 10: t=8 (14)
        # low at t=6 (px 9) -> rise px > 11: t=8 (14)
        # low at t=9 (px 7): nothing after
        assert got.low_time.tolist() == [2, 5, 6]
        assert got.rise_time.tolist() == [3, 8, 8]


class TestAsofForward:
    def _streamed(self, ctx, table, batch_rows):
        from quokka_tpu import logical
        from quokka_tpu.dataset.readers import InputArrowDataset

        reader = InputArrowDataset(table, batch_rows=batch_rows)
        return ctx.new_stream(
            logical.SourceNode(reader, list(table.column_names), sorted_by=["time"]),
            ordered=True,
        )

    def test_forward_asof_lagging_key(self):
        # key A's quotes arrive far later in global time than its trades: a
        # watermark-style readiness rule would emit A trades unmatched; the
        # matched-is-final rule must hold them until the A quotes arrive
        r = np.random.default_rng(4)
        tt = np.arange(0, 1000, dtype=np.int64)
        syms = np.where(np.arange(1000) % 2 == 0, "A", "B")
        trades = pa.table({"time": tt, "symbol": syms,
                           "size": r.integers(1, 9, 1000).astype(np.int64)})
        qb = np.arange(1, 1000, 2, dtype=np.int64)
        qa = np.arange(5000, 5010, dtype=np.int64)
        quotes = pa.table(
            {
                "time": np.concatenate([qb, qa]),
                "symbol": ["B"] * len(qb) + ["A"] * len(qa),
                "bid": np.concatenate([qb, qa]).astype(np.float64) / 10,
            }
        )
        ctx = QuokkaContext()
        t = self._streamed(ctx, trades, 64)
        q = self._streamed(ctx, quotes, 64)
        got = t.join_asof(q, on="time", by="symbol", direction="forward").collect()
        exp = pd.merge_asof(
            trades.to_pandas(), quotes.to_pandas().sort_values("time"),
            on="time", by="symbol", direction="forward",
        ).dropna(subset=["bid"])
        got = got.sort_values(["symbol", "time"]).reset_index(drop=True)
        exp = exp.sort_values(["symbol", "time"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_allclose(got.bid.to_numpy(), exp.bid.to_numpy())

    def test_forward_asof_single_batch(self):
        trades = pa.table({"time": np.array([1, 5, 9], dtype=np.int64),
                           "symbol": ["A", "A", "A"]})
        quotes = pa.table({"time": np.array([4, 7], dtype=np.int64),
                           "symbol": ["A", "A"], "bid": [1.0, 2.0]})
        ctx = QuokkaContext()
        t = ctx.from_arrow_sorted(trades, sorted_by="time")
        q = ctx.from_arrow_sorted(quotes, sorted_by="time")
        got = t.join_asof(q, on="time", by="symbol", direction="forward").collect()
        got = got.sort_values("time")
        # t=1 -> quote 4 (1.0); t=5 -> quote 7 (2.0); t=9 -> unmatched/dropped
        assert got.time.tolist() == [1, 5]
        assert got.bid.tolist() == [1.0, 2.0]


class TestSlidingMinMax:
    def test_rolling_min_max_matches_pandas(self):
        r = np.random.default_rng(8)
        n = 4000
        t = pa.table({
            "time": np.sort(r.choice(100_000, n, replace=False)).astype(np.int64),
            "sym": np.array(["A", "B"])[r.integers(0, 2, n)],
            "px": r.uniform(10, 20, n).round(4),
        })
        size = 500
        ctx = QuokkaContext()
        s = ctx.from_arrow_sorted(t, sorted_by="time")
        got = s.window_agg(
            SlidingWindow(size),
            "min(px) as lo, max(px) as hi, sum(px) as tot",
            by="sym",
        ).collect()
        df = t.to_pandas()
        exp_rows = []
        for sym, g in df.groupby("sym"):
            g = g.sort_values("time")
            for _, row in g.iterrows():
                w = g[(g.time >= row.time - size) & (g.time <= row.time)]
                exp_rows.append((sym, row.time, w.px.min(), w.px.max(), w.px.sum()))
        exp = pd.DataFrame(exp_rows, columns=["sym", "time", "lo", "hi", "tot"])
        got = got.sort_values(["sym", "time"]).reset_index(drop=True)
        exp = exp.sort_values(["sym", "time"]).reset_index(drop=True)
        assert len(got) == len(exp)
        np.testing.assert_allclose(got.lo.to_numpy(), exp.lo.to_numpy(), rtol=1e-9)
        np.testing.assert_allclose(got.hi.to_numpy(), exp.hi.to_numpy(), rtol=1e-9)
        np.testing.assert_allclose(got.tot.to_numpy(), exp.tot.to_numpy(), rtol=1e-9)
