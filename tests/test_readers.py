"""Reader tests: byte-range boundary ownership, schema inference, pushdown."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.dataset.readers import (
    InputCSVDataset,
    InputJSONDataset,
    InputParquetDataset,
    _read_line_range,
)
from quokka_tpu.expression import col, date


def read_all(reader, channels=3):
    state = reader.get_own_state(channels)
    tables = []
    for ch, lineages in state.items():
        for lin in lineages:
            t = reader.execute(ch, lin)
            if t.num_rows:
                tables.append(t)
    return pa.concat_tables(tables) if tables else reader.schema.empty_table()


class TestLineRangeOwnership:
    def test_every_row_read_exactly_once_all_strides(self, tmp_path):
        p = str(tmp_path / "t.csv")
        lines = [f"{i},{i*i}\n" for i in range(100)]
        with open(p, "w") as f:
            f.write("a,b\n")
            f.writelines(lines)
        size = os.path.getsize(p)
        # exhaustively test every stride incl. ones landing exactly on newlines
        for stride in list(range(3, 40)) + [size - 1, size, size + 10]:
            r = InputCSVDataset(p, stride=stride)
            got = read_all(r).to_pandas().sort_values("a").reset_index(drop=True)
            assert len(got) == 100, f"stride {stride}: {len(got)} rows"
            assert got.a.tolist() == list(range(100)), f"stride {stride}"

    def test_boundary_exactly_on_newline(self, tmp_path):
        p = str(tmp_path / "t2.csv")
        with open(p, "w") as f:
            f.write("a\n")  # header: 2 bytes
            for i in range(10):
                f.write(f"{i}\n")  # 2 bytes each
        # stride 4 puts boundaries exactly on newlines
        r = InputCSVDataset(p, stride=4)
        got = read_all(r).to_pandas()
        assert sorted(got.a.tolist()) == list(range(10))

    def test_json_ranges(self, tmp_path):
        p = str(tmp_path / "t.jsonl")
        with open(p, "w") as f:
            for i in range(50):
                f.write('{"x": %d, "s": "v%d"}\n' % (i, i))
        for stride in (7, 16, 21, 64, 10_000):
            r = InputJSONDataset(p, stride=stride)
            got = read_all(r).to_pandas()
            assert sorted(got.x.tolist()) == list(range(50)), f"stride {stride}"


class TestCSV:
    def test_headerless_with_schema(self, tmp_path):
        p = str(tmp_path / "nh.csv")
        with open(p, "w") as f:
            for i in range(20):
                f.write(f"{i},{i*2}\n")
        r = InputCSVDataset(p, schema=["x", "y"], has_header=False, stride=11)
        got = read_all(r).to_pandas().sort_values("x").reset_index(drop=True)
        assert got.y.tolist() == [2 * i for i in range(20)]

    def test_read_csv_through_engine(self, tmp_path):
        p = str(tmp_path / "e.csv")
        df = pd.DataFrame({"k": np.arange(50) % 5, "v": np.arange(50) * 1.5})
        df.to_csv(p, index=False)
        ctx = QuokkaContext()
        got = ctx.read_csv(p).groupby("k").agg_sql("sum(v) as sv").collect()
        exp = df.groupby("k").v.sum().reset_index(name="sv")
        got = got.sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)


class TestParquetPushdown:
    def test_rowgroup_pruning(self, tmp_path):
        p = str(tmp_path / "p.parquet")
        t = pa.table({"x": np.arange(10_000, dtype=np.int64), "y": np.ones(10_000)})
        pq.write_table(t, p, row_group_size=1000)
        pruned = InputParquetDataset(p, predicate=(col("x") > 8500))
        state = pruned.get_own_state(1)
        n_pieces = sum(len(v) for v in state.values())
        assert n_pieces == 2  # only row groups [8000,9000) and [9000,10000)
        full = InputParquetDataset(p)
        assert sum(len(v) for v in full.get_own_state(1).values()) == 10

    def test_columns_projection(self, tmp_path):
        p = str(tmp_path / "c.parquet")
        pq.write_table(pa.table({"a": [1, 2], "b": [3, 4], "c": [5, 6]}), p)
        r = InputParquetDataset(p, columns=["a", "c"])
        got = read_all(r, channels=1)
        assert got.column_names == ["a", "c"]


class TestSelfJoin:
    def test_direct_self_join(self):
        ctx = QuokkaContext()
        t = pa.table({"k": np.arange(10, dtype=np.int64), "v": np.arange(10) * 1.0})
        s = ctx.from_arrow(t)
        got = s.join(s, on="k", suffix="_r").collect()
        assert len(got) == 10
        np.testing.assert_allclose(
            got.sort_values("k").v_r.to_numpy(), np.arange(10) * 1.0
        )

    def test_self_union(self):
        ctx = QuokkaContext()
        t = pa.table({"k": np.arange(10, dtype=np.int64)})
        s = ctx.from_arrow(t)
        assert s.union(s).count() == 20
