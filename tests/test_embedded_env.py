"""The embedded engine in its PRODUCTION configuration — x64 OFF, one
device, no conftest env — exercised in a clean subprocess.

Regression for a deterministic 'Execution supplied 4 buffers but compiled
program expected 8 buffers' failure: the hashtable module used to be
first-imported lazily INSIDE an active jit trace (FusedPartialAgg's fused
program calls kernels.groupby_limbs), and creating its module-level pjit
objects mid-trace mis-primed jit dispatch for later top-level calls.  The
test suite's x64/8-device conftest masked it, so this guard runs the real
config end to end.
"""

import json
import os
import subprocess
import sys
import textwrap


def test_nonx64_engine_groupby_join_subprocess(tmp_path):
    script = textwrap.dedent("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import pyarrow as pa

        from quokka_tpu import QuokkaContext
        from quokka_tpu.expression import col

        assert not jax.config.jax_enable_x64

        r = np.random.default_rng(0)
        t = pa.table({"k": r.integers(0, 50, 20000).astype(np.int64),
                      "v": r.uniform(0, 1, 20000)})
        dim = pa.table({"k": np.arange(50, dtype=np.int64),
                        "w": np.arange(50, dtype=np.int64) * 2})
        ctx = QuokkaContext()
        got = (ctx.from_arrow(t)
               .join(ctx.from_arrow(dim), on="k")
               .groupby("k").agg_sql("sum(v) as s, sum(w) as ws, count(*) as n")
               .collect())
        assert len(got) == 50, len(got)
        assert int(got.n.sum()) == 20000
        print("SUBPROCESS_OK")
    """)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "QUOKKA_JAX_CACHE_DIR")}
    env["JAX_PLATFORMS"] = "cpu"
    # persistent (per-host-fingerprint) cache: the subprocess compiles the
    # whole non-x64 kernel set, ~60s cold on one core — warm after run 1
    env["QUOKKA_JAX_CACHE_DIR"] = os.path.expanduser(
        "~/.cache/quokka_tpu_test_nonx64_jax")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=420, cwd=str(tmp_path),
    )
    assert "SUBPROCESS_OK" in out.stdout, (out.stdout[-2000:], out.stderr[-2000:])
