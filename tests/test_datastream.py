"""End-to-end DataStream API tests through the full runtime (logical plan ->
TaskGraph -> push engine), pandas as oracle."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, col, date, lit

from conftest import make_table


@pytest.fixture
def ctx():
    return QuokkaContext(io_channels=2, exec_channels=2)


@pytest.fixture
def stream(ctx, table):
    return ctx.from_arrow(table)


def sorted_eq(got: pd.DataFrame, exp: pd.DataFrame, by=None, rtol=1e-9):
    by = by or list(exp.columns)
    got = got.sort_values(by).reset_index(drop=True)[list(exp.columns)]
    exp = exp.sort_values(by).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=rtol)


class TestBasics:
    def test_collect_roundtrip(self, stream, pdf):
        got = stream.collect()
        sorted_eq(got, pdf, by=["k", "v"])

    def test_filter_expr(self, stream, pdf):
        got = stream.filter(col("q") > 25).collect()
        sorted_eq(got, pdf[pdf.q > 25], by=["k", "v"])

    def test_filter_sql(self, stream, pdf):
        got = stream.filter_sql("q > 25 and s = 'apple'").collect()
        sorted_eq(got, pdf[(pdf.q > 25) & (pdf.s == "apple")], by=["k", "v"])

    def test_select_drop(self, stream, pdf):
        got = stream.select(["k", "v"]).collect()
        sorted_eq(got, pdf[["k", "v"]], by=["k", "v"])
        got = stream.drop(["s", "d"]).collect()
        assert set(got.columns) == {"k", "v", "q"}

    def test_with_columns(self, stream, pdf):
        got = stream.with_columns({"z": col("v") * 2 + col("q")}).collect()
        exp = pdf.assign(z=pdf.v * 2 + pdf.q)
        sorted_eq(got, exp, by=["k", "v"])

    def test_with_columns_sql(self, stream, pdf):
        got = stream.with_columns_sql("v * 2 as twice, q + 1 as qq").collect()
        exp = pdf.assign(twice=pdf.v * 2, qq=pdf.q + 1)
        sorted_eq(got, exp, by=["k", "v"])

    def test_rename(self, stream, pdf):
        got = stream.rename({"k": "key"}).collect()
        assert "key" in got.columns and "k" not in got.columns

    def test_count(self, stream, pdf):
        assert stream.count() == len(pdf)

    def test_distinct(self, stream, pdf):
        got = stream.select(["k", "s"]).distinct().collect()
        exp = pdf[["k", "s"]].drop_duplicates()
        assert len(got) == len(exp)

    def test_sort(self, stream, pdf):
        got = stream.sort(["k", "v"], [False, True]).collect()
        exp = pdf.sort_values(["k", "v"], ascending=[True, False]).reset_index(drop=True)
        pd.testing.assert_frame_equal(got[exp.columns.tolist()], exp, check_dtype=False)

    def test_top_k(self, stream, pdf):
        got = stream.top_k(["v"], 5, [True]).collect()
        np.testing.assert_allclose(got.v.to_numpy(), pdf.v.nlargest(5).to_numpy())

    def test_head(self, stream, pdf):
        got = stream.head(17).collect()
        assert len(got) == 17

    def test_union(self, ctx, table, pdf):
        s1 = ctx.from_arrow(table)
        s2 = ctx.from_arrow(table)
        got = s1.union(s2).count()
        assert got == 2 * len(pdf)

    def test_transform_udf(self, stream, pdf):
        got = stream.transform(
            lambda df: df[df.q > 40][["k", "q"]], new_schema=["k", "q"]
        ).collect()
        sorted_eq(got, pdf[pdf.q > 40][["k", "q"]], by=["k", "q"])

    def test_explain_runs(self, stream):
        txt = stream.filter(col("q") > 3).explain()
        # the optimizer pushes the root filter into the source
        assert "Source" in txt and ("Filter" in txt or "filter=" in txt)


class TestAggregations:
    def test_groupby_agg_dict(self, stream, pdf):
        got = stream.groupby("k").agg({"v": ["sum", "max"], "*": "count"}).collect()
        exp = (
            pdf.groupby("k")
            .agg(v_sum=("v", "sum"), v_max=("v", "max"), count=("v", "size"))
            .reset_index()
        )
        sorted_eq(got, exp, by=["k"])

    def test_groupby_agg_sql(self, stream, pdf):
        got = (
            stream.groupby(["k", "s"])
            .agg_sql("sum(v) as sv, avg(q) as aq, count(*) as n")
            .collect()
        )
        exp = (
            pdf.groupby(["k", "s"])
            .agg(sv=("v", "sum"), aq=("q", "mean"), n=("v", "size"))
            .reset_index()
        )
        sorted_eq(got, exp, by=["k", "s"])

    def test_global_agg(self, stream, pdf):
        got = stream.agg_sql("sum(v) as sv, count(*) as n, min(q) as mq").collect()
        assert len(got) == 1
        np.testing.assert_allclose(got.sv[0], pdf.v.sum())
        assert got.n[0] == len(pdf)
        assert got.mq[0] == pdf.q.min()

    def test_sum_shortcut(self, stream, pdf):
        got = stream.sum("q").collect()
        assert got.q_sum[0] == pdf.q.sum()

    def test_count_distinct(self, stream, pdf):
        got = stream.count_distinct("s").collect()
        assert got["count"][0] == pdf.s.nunique()


class TestJoins:
    def test_inner_join(self, ctx):
        r = np.random.default_rng(3)
        left = pa.table(
            {"key": r.integers(0, 40, 500).astype(np.int64), "x": r.normal(size=500)}
        )
        right = pa.table(
            {"key": np.arange(0, 30, dtype=np.int64), "y": r.normal(size=30)}
        )
        got = ctx.from_arrow(left).join(ctx.from_arrow(right), on="key").collect()
        exp = left.to_pandas().merge(right.to_pandas(), on="key", how="inner")
        sorted_eq(got, exp, by=["key", "x"])

    def test_join_left_right_on_and_suffix(self, ctx):
        r = np.random.default_rng(4)
        left = pa.table(
            {"a": r.integers(0, 20, 200).astype(np.int64), "x": r.normal(size=200)}
        )
        right = pa.table(
            {"b": np.arange(0, 20, dtype=np.int64), "x": r.normal(size=20)}
        )
        got = (
            ctx.from_arrow(left)
            .join(ctx.from_arrow(right), left_on="a", right_on="b", suffix="_r")
            .collect()
        )
        exp = (
            left.to_pandas()
            .merge(right.to_pandas(), left_on="a", right_on="b", suffixes=("", "_r"))
            .drop(columns=["b"])
        )
        sorted_eq(got, exp, by=["a", "x"])

    def test_semi_anti(self, ctx):
        r = np.random.default_rng(5)
        left = pa.table({"key": r.integers(0, 50, 300).astype(np.int64)})
        right = pa.table({"key": np.arange(0, 25, dtype=np.int64)})
        ldf = left.to_pandas()
        semi = ctx.from_arrow(left).join(ctx.from_arrow(right), on="key", how="semi").count()
        anti = ctx.from_arrow(left).join(ctx.from_arrow(right), on="key", how="anti").count()
        assert semi == int(ldf.key.isin(range(25)).sum())
        assert anti == int((~ldf.key.isin(range(25))).sum())

    def test_multi_batch_join(self, ctx):
        # force multiple input batches through small reader batch size
        r = np.random.default_rng(6)
        n = 5000
        left = pa.table(
            {"key": r.integers(0, 500, n).astype(np.int64), "x": r.normal(size=n)}
        )
        right = pa.table(
            {"key": np.arange(0, 400, dtype=np.int64), "y": r.normal(size=400)}
        )
        from quokka_tpu.dataset.readers import InputArrowDataset

        ls = ctx.read_dataset(InputArrowDataset(left, batch_rows=512))
        rs = ctx.read_dataset(InputArrowDataset(right, batch_rows=128))
        got = ls.join(rs, on="key").collect()
        exp = left.to_pandas().merge(right.to_pandas(), on="key")
        sorted_eq(got, exp, by=["key", "x"])

    def test_broadcast_join(self, ctx):
        r = np.random.default_rng(7)
        left = pa.table(
            {"key": r.integers(0, 30, 400).astype(np.int64), "x": r.normal(size=400)}
        )
        right = pa.table({"key": np.arange(0, 30, dtype=np.int64), "y": r.normal(size=30)})
        got = ctx.from_arrow(left).broadcast_join(ctx.from_arrow(right), on="key").collect()
        exp = left.to_pandas().merge(right.to_pandas(), on="key")
        sorted_eq(got, exp, by=["key", "x"])

    def test_join_then_groupby(self, ctx):
        r = np.random.default_rng(8)
        left = pa.table(
            {"key": r.integers(0, 10, 1000).astype(np.int64), "x": r.normal(size=1000)}
        )
        right = pa.table(
            {"key": np.arange(0, 10, dtype=np.int64), "grp": [f"g{i%3}" for i in range(10)]}
        )
        got = (
            ctx.from_arrow(left)
            .join(ctx.from_arrow(right), on="key")
            .groupby("grp")
            .agg_sql("sum(x) as sx, count(*) as n")
            .collect()
        )
        exp = (
            left.to_pandas()
            .merge(right.to_pandas(), on="key")
            .groupby("grp")
            .agg(sx=("x", "sum"), n=("x", "size"))
            .reset_index()
        )
        sorted_eq(got, exp, by=["grp"])
