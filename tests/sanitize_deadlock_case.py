"""Deliberately-deadlocked two-worker fixture for the QK_SANITIZE watchdog.

Run by tests/test_sanitize.py as a subprocess with QK_SANITIZE=1 and a short
QK_SANITIZE_DEADLINE.  The placed executor ABBA-deadlocks worker 0's
dispatch thread on its first batch; without the sanitizer the run wedges to
the coordinator's 600 s timeout (the round-5 verdict's
test_placement/test_distributed failure mode).  With it, the worker's
watchdog dumps every thread's stack to stderr and exits, and the
coordinator fails the run within its 50 ms poll — the expected outcome is a
NONZERO exit in seconds, stacks included.

Module-level executor class + __main__ guard: worker processes are spawned
and re-import this script as __mp_main__ to unpickle the factory.
"""

import os
import sys
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable straight from a checkout: the repo root is the package root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa


class DeadlockExecutor:
    """ABBA deadlock on first execute(): the dispatch thread takes A then
    waits for B while a helper thread holds B and waits for A.  Locks are
    created lazily — the instance is pickled into the worker spec."""

    def execute(self, batches, stream_id, channel):
        a, b = threading.Lock(), threading.Lock()
        started = threading.Event()

        def helper():
            with b:
                started.set()
                with a:
                    pass

        t = threading.Thread(target=helper, daemon=True,
                             name="deadlock-helper")
        with a:
            t.start()
            started.wait()
            with b:  # blocks forever: helper holds b, waits for a
                pass
        return None

    def done(self, channel):
        return None

    def source_done(self, stream_id, channel):
        return None


def main():
    from quokka_tpu import QuokkaContext, SingleChannelStrategy
    from quokka_tpu.utils.cluster import LocalCluster

    t = pa.table({"v": np.arange(5000.0)})
    ctx = QuokkaContext(cluster=LocalCluster(n_workers=2))
    got = (
        ctx.from_arrow(t)
        .stateful_transform(DeadlockExecutor(), ["x"],
                            placement=SingleChannelStrategy())
        .collect()
    )
    # only reachable if the deadlock failed to wedge the worker
    print("UNEXPECTED-COMPLETION", got, file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
