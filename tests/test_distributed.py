"""Multi-process runtime: served ControlStore + spawned workers + socket data
plane (VERDICT r1 item 3).  Queries must produce the same results as the
embedded engine, and a kill -9'd worker must be detected by the coordinator
and its channels adopted by the survivor with checkpoint+tape+HBQ recovery."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext
from quokka_tpu.utils.cluster import LocalCluster


def make_data(seed=0, n=20000):
    r = np.random.default_rng(seed)
    fact = pa.table(
        {
            "k": r.integers(0, 200, n).astype(np.int64),
            "s": np.array(["a", "b", "c", "d"])[r.integers(0, 4, n)],
            "v": r.uniform(0, 10, n).round(4),
        }
    )
    dim = pa.table(
        {
            "k": np.arange(200, dtype=np.int64),
            "grp": np.array(["X", "Y"])[np.arange(200) % 2],
        }
    )
    return fact, dim


def q1_shape(ctx, fact):
    return (
        ctx.from_arrow(fact)
        .filter_sql("v > 2")
        .groupby("s")
        .agg_sql("sum(v) as sv, count(*) as n, avg(v) as av")
        .collect()
        .sort_values("s")
        .reset_index(drop=True)
    )


def q3_shape(ctx, fact, dim):
    return (
        ctx.from_arrow(fact)
        .join(ctx.from_arrow(dim), on="k")
        .filter_sql("v < 9")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
        .collect()
        .sort_values("grp")
        .reset_index(drop=True)
    )


class TestTwoWorkers:
    def test_groupby_matches_embedded(self):
        fact, dim = make_data()
        got = q1_shape(QuokkaContext(cluster=LocalCluster(n_workers=2)), fact)
        exp = q1_shape(QuokkaContext(), fact)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_join_matches_embedded(self):
        fact, dim = make_data(seed=1)
        got = q3_shape(QuokkaContext(cluster=LocalCluster(n_workers=2)), fact, dim)
        exp = q3_shape(QuokkaContext(), fact, dim)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)


class TestKill9Recovery:
    def test_kill_worker_mid_run(self, tmp_path):
        import pyarrow.parquet as pq

        fact, dim = make_data(seed=2)
        fp, dp = str(tmp_path / "fact.parquet"), str(tmp_path / "dim.parquet")
        # small row groups -> many input batches, so the SIGKILL lands while
        # the stream is genuinely mid-flight
        pq.write_table(fact, fp, row_group_size=1024)
        pq.write_table(dim, dp)

        def q(ctx):
            return (
                ctx.read_parquet(fp)
                .join(ctx.read_parquet(dp), on="k")
                .filter_sql("v < 9")
                .groupby("grp")
                .agg_sql("sum(v) as sv, count(*) as n")
                .collect()
                .sort_values("grp")
                .reset_index(drop=True)
            )

        ctx = QuokkaContext(
            cluster=LocalCluster(n_workers=2),
            exec_config={
                "fault_tolerance": True,
                "checkpoint_interval": 2,
                # SIGKILL worker 1 once 6 input seqs have been produced
                "inject_kill_worker": (1, 6),
            },
        )
        got = q(ctx)
        exp = q(QuokkaContext())
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)

    def test_kill_worker_private_spill_dirs(self, tmp_path):
        """VERDICT r2 #4: recovery must not assume a shared spill disk.
        Every worker spills post-partition objects into its own PRIVATE
        subdir (multi-host discipline); checkpoints go to the checkpoint
        STORE (standing in for the reference's S3 bucket, core.py:678-685).
        A kill -9'd worker's spill is unreachable — the adopter must pull
        surviving copies from live peers over the data plane or re-read
        input lineage."""
        import os

        import pyarrow.parquet as pq

        fact, dim = make_data(seed=7)
        fp, dp = str(tmp_path / "fact.parquet"), str(tmp_path / "dim.parquet")
        pq.write_table(fact, fp, row_group_size=1024)
        pq.write_table(dim, dp)
        spill = str(tmp_path / "spill")
        ckpt_store = str(tmp_path / "ckpt_store")  # the "object store"

        def q(ctx):
            return (
                ctx.read_parquet(fp)
                .join(ctx.read_parquet(dp), on="k")
                .groupby("grp")
                .agg_sql("sum(v) as sv, count(*) as n")
                .collect()
                .sort_values("grp")
                .reset_index(drop=True)
            )

        ctx = QuokkaContext(
            cluster=LocalCluster(n_workers=2),
            exec_config={
                "fault_tolerance": True,
                "checkpoint_interval": 2,
                "hbq_path": spill,
                "checkpoint_store": ckpt_store,
                "inject_kill_worker": (1, 6),
            },
        )
        # the run dir is wiped on completion: observe the spill layout WHILE
        # the query runs
        import threading

        seen = set()
        stop = threading.Event()

        def watch():
            while not stop.is_set():
                for run in os.listdir(spill) if os.path.isdir(spill) else []:
                    rd = os.path.join(spill, run)
                    try:
                        seen.update(os.listdir(rd))
                    except OSError:
                        pass
                stop.wait(0.05)

        th = threading.Thread(target=watch, daemon=True)
        th.start()
        try:
            got = q(ctx)
        finally:
            stop.set()
            th.join(timeout=5)
        exp = q(QuokkaContext())
        pd.testing.assert_frame_equal(got, exp, check_dtype=False)
        # spills live ONLY in per-worker private dirs — nothing at the run's
        # top level — and checkpoints went to the store
        spilled = {e for e in seen if not e.startswith("ckpt-")}
        assert spilled and all(e.startswith("worker-") for e in spilled), seen
        assert any(f.startswith("ckpt-") for f in os.listdir(ckpt_store))


class TestTPUPodCluster:
    def test_manager_brings_up_pod_and_runs_queries(self):
        """VERDICT r2 #7: one QuokkaClusterManager.start_cluster call brings
        up the worker daemons (two loopback 'hosts' as local subprocesses),
        then the context runs MULTIPLE queries against them — the --persist
        daemons rejoin each query's store session on the same fixed port."""
        import socket

        from quokka_tpu.utils.cluster import QuokkaClusterManager, TPUPodCluster

        with socket.socket() as s:  # pick a free fixed port for the store
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        cluster = TPUPodCluster(["127.0.0.1", "127.0.0.1"],
                                coordinator="127.0.0.1", store_port=port)
        cmds = cluster.worker_commands()
        assert len(cmds) == 2 and f"127.0.0.1:{port}" in cmds[0]
        assert "QUOKKA_RPC_TOKEN=" in cmds[0] and "--persist" in cmds[0]

        fact, dim = make_data(seed=5, n=6000)
        mgr = QuokkaClusterManager()
        mgr.start_cluster(cluster)
        try:
            ctx = QuokkaContext(cluster=cluster)
            got1 = q1_shape(ctx, fact)
            got3 = q3_shape(ctx, fact, dim)  # second query: daemons rejoined
        finally:
            mgr.stop_cluster(cluster)
        exp1 = q1_shape(QuokkaContext(), fact)
        exp3 = q3_shape(QuokkaContext(), fact, dim)
        pd.testing.assert_frame_equal(got1, exp1, check_dtype=False)
        pd.testing.assert_frame_equal(got3, exp3, check_dtype=False)


class TestExternalWorker:
    def test_externally_launched_worker_joins(self, tmp_path):
        """Multi-host path: one spawned worker + one worker launched via
        `python -m quokka_tpu.runtime.worker --store host:port --worker-id 1`
        that fetches the plan from the served store."""
        import os
        import subprocess
        import sys
        import threading

        from quokka_tpu import logical
        from quokka_tpu.runtime.distributed import run_distributed
        from quokka_tpu.runtime.engine import TaskGraph

        fact, dim = make_data(seed=4, n=8000)
        ctx = QuokkaContext()
        q = (
            ctx.from_arrow(fact)
            .join(ctx.from_arrow(dim), on="k")
            .groupby("grp")
            .agg_sql("sum(v) as sv, count(*) as n")
        )
        sub, mapping = ctx._copy_subgraph(q.node_id)
        sink_id = mapping[q.node_id]
        from quokka_tpu.optimizer import optimize

        sink = logical.SinkNode([sink_id], sub[sink_id].schema)
        sid = max(sub) + 1
        sub[sid] = sink
        sink_id = optimize(sub, sid, exec_channels=2)
        ctx._assign_stages(sub, sink_id)
        graph = TaskGraph(ctx.exec_config)
        actor_of = {}
        for nid in ctx._toposort(sub, sink_id):
            sub[nid].lower(ctx, graph, actor_of, nid)

        proc_holder = {}

        def launch_external():
            # wait for the store address file the main thread writes
            for _ in range(200):
                if "addr" in proc_holder:
                    break
                import time as _t

                _t.sleep(0.05)
            host, port = proc_holder["addr"]
            env = dict(os.environ)
            proc_holder["proc"] = subprocess.Popen(
                [sys.executable, "-m", "quokka_tpu.runtime.worker",
                 "--store", f"{host}:{port}", "--worker-id", "1"],
                env=env,
            )

        # intercept the served address by wrapping serve_store
        import quokka_tpu.runtime.distributed as D

        orig = D.serve_store

        def capture(store, host="127.0.0.1", port=0):
            srv = orig(store, host=host, port=port)
            proc_holder["addr"] = srv.address
            return srv

        D.serve_store = capture
        th = threading.Thread(target=launch_external, daemon=True)
        th.start()
        try:
            run_distributed(graph, n_workers=1, external_workers=1, timeout=300)
        finally:
            D.serve_store = orig
            p = proc_holder.get("proc")
            if p is not None:
                p.wait(timeout=30)
        got = (
            graph.result(actor_of[sink_id])
            .to_df()
            .sort_values("grp")
            .reset_index(drop=True)
        )
        exp = (
            fact.to_pandas().merge(dim.to_pandas(), on="k")
            .groupby("grp").v.agg(["sum", "size"]).reset_index()
        )
        np.testing.assert_allclose(got.sv.to_numpy(), exp["sum"].to_numpy(), rtol=1e-9)
        assert got.n.tolist() == exp["size"].tolist()
        graph.cleanup()


class TestGCloudProvisioner:
    """Command construction + response parsing with an injected runner (the
    reference's EC2 create/start/stop/terminate surface, utils.py:191-500,
    mapped onto `gcloud compute tpus tpu-vm`).  No gcloud binary needed."""

    class _FakeRun:
        def __init__(self, describe_json):
            self.calls = []
            self.describe_json = describe_json

        def __call__(self, cmd, capture_output=True, text=True):
            import json
            import types

            self.calls.append(cmd)
            out = ""
            if "describe" in cmd:
                out = json.dumps(self.describe_json)
            return types.SimpleNamespace(returncode=0, stdout=out, stderr="")

    DESC = {
        "name": "projects/p/locations/z/nodes/myslice",
        "state": "READY",
        "networkEndpoints": [
            {"ipAddress": "10.0.0.2", "accessConfig": {"externalIp": "34.1.1.1"}},
            {"ipAddress": "10.0.0.3", "accessConfig": {"externalIp": "34.1.1.2"}},
        ],
    }

    def test_create_builds_cluster_from_endpoints(self):
        from quokka_tpu.utils.cluster import GCloudTPUProvisioner

        fake = self._FakeRun(self.DESC)
        prov = GCloudTPUProvisioner("proj", "us-central2-b", runner=fake)
        cluster = prov.create_cluster("myslice", accelerator_type="v5litepod-8")
        create, describe = fake.calls
        assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
        assert "myslice" in create
        assert "--accelerator-type=v5litepod-8" in create
        assert "--project=proj" in create and "--zone=us-central2-b" in create
        assert "describe" in describe
        assert cluster.hosts == ["10.0.0.2", "10.0.0.3"]
        assert cluster.coordinator == "10.0.0.2"
        # the provisioned cluster plugs straight into daemon bring-up
        cmds = cluster.worker_commands()
        assert len(cmds) == 2 and "--worker-id 1" in cmds[1]

    def test_external_ips_and_lifecycle(self):
        from quokka_tpu.utils.cluster import GCloudTPUProvisioner

        fake = self._FakeRun(self.DESC)
        prov = GCloudTPUProvisioner("proj", "z", runner=fake)
        cluster = prov.get_cluster("myslice", internal_ips=False)
        assert cluster.hosts == ["34.1.1.1", "34.1.1.2"]
        prov.stop_cluster("myslice")
        prov.terminate_cluster("myslice")
        assert any("stop" in c for c in fake.calls)
        assert any("delete" in c and "--quiet" in c for c in fake.calls)

    def test_gcloud_failure_surfaces(self):
        import types

        from quokka_tpu.utils.cluster import GCloudTPUProvisioner

        def boom(cmd, capture_output=True, text=True):
            return types.SimpleNamespace(returncode=1, stdout="",
                                         stderr="PERMISSION_DENIED: nope")

        prov = GCloudTPUProvisioner("proj", "z", runner=boom)
        with pytest.raises(RuntimeError, match="PERMISSION_DENIED"):
            prov.get_cluster("myslice")

    def test_no_endpoints_is_loud(self):
        from quokka_tpu.utils.cluster import GCloudTPUProvisioner

        fake = self._FakeRun({"name": "n", "state": "CREATING"})
        prov = GCloudTPUProvisioner("proj", "z", runner=fake)
        with pytest.raises(RuntimeError, match="no network endpoints"):
            prov.get_cluster("n")

    def test_manager_delegates_with_coordinates(self):
        from quokka_tpu.utils import cluster as C

        fake = self._FakeRun(self.DESC)
        orig = C.GCloudTPUProvisioner
        try:
            C.GCloudTPUProvisioner = lambda project, zone: orig(
                project, zone, runner=fake
            )
            mgr = C.QuokkaClusterManager()
            got = mgr.create_cluster("myslice", project="p", zone="z")
            assert got.hosts == ["10.0.0.2", "10.0.0.3"]
        finally:
            C.GCloudTPUProvisioner = orig
        with pytest.raises(NotImplementedError, match="TPUPodCluster"):
            C.QuokkaClusterManager().create_cluster()
