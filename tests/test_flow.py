"""Known-answer tests for the qkflow interprocedural engine
(analysis/flow.py) over the tests/lint_fixtures/flowpkg/ package.

The fixture files are parse-only: the test labels them with synthetic
``quokka_tpu/flowfix/...`` rel paths so every import form the resolver
handles (relative module binding, from-import alias, absolute alias,
fully-dotted chain) resolves inside the analyzed set."""

import ast
from pathlib import Path

import pytest

from quokka_tpu.analysis.flow import build_context, module_name_of

FIXDIR = Path(__file__).parent / "lint_fixtures" / "flowpkg"
MOD = "quokka_tpu.flowfix"

ALPHA = f"{MOD}.alpha"
BETA = f"{MOD}.beta"
GAMMA = f"{MOD}.gamma"


def _load(name):
    src = (FIXDIR / name).read_text()
    return ast.parse(src, filename=name)


@pytest.fixture(scope="module")
def ctx():
    files = [
        (f"quokka_tpu/flowfix/{n}", _load(n))
        for n in ("__init__.py", "alpha.py", "beta.py", "gamma.py")
    ]
    return build_context(files)


def test_module_name_of():
    assert module_name_of("quokka_tpu/flowfix/alpha.py") == ALPHA
    assert module_name_of("quokka_tpu/flowfix/__init__.py") == MOD
    assert module_name_of("tools/loose_script.py") == "loose_script"


def test_symbol_tables(ctx):
    assert set(ctx.modules) == {MOD, ALPHA, BETA, GAMMA}
    mt = ctx.module_table("quokka_tpu/flowfix/alpha.py")
    assert mt is not None and mt.name == ALPHA
    assert "Engine" in mt.classes
    assert set(mt.class_methods["Engine"]) == {"__init__", "step", "_bump"}


def test_import_edges(ctx):
    """One call edge per import form, all landing on the right callee."""
    calls = ctx.calls
    helper = f"{ALPHA}:helper"
    assert helper in calls[f"{BETA}:call_via_module"]      # from . import alpha
    assert helper in calls[f"{BETA}:call_via_from_alias"]  # from .alpha import helper as hlp
    assert f"{ALPHA}:outer" in calls[f"{BETA}:call_via_import_alias"]  # import ... as qalpha
    assert helper in calls[f"{GAMMA}:dotted_call"]         # fully-dotted chain


def test_class_call_and_self_dispatch(ctx):
    calls = ctx.calls
    # alpha.Engine(v) through a module binding resolves to the constructor
    assert f"{ALPHA}:Engine.__init__" in calls[f"{BETA}:build_engine"]
    # self._bump(v) resolves inside the class, then on to the helper
    assert f"{ALPHA}:Engine._bump" in calls[f"{ALPHA}:Engine.step"]
    assert f"{ALPHA}:helper" in calls[f"{ALPHA}:Engine._bump"]


def test_closures(ctx):
    calls = ctx.calls
    inner = f"{ALPHA}:outer.<locals>.inner"
    add = f"{ALPHA}:make_adder.<locals>.add"
    assert inner in calls[f"{ALPHA}:outer"]       # called nested def
    assert f"{ALPHA}:helper" in calls[inner]      # body resolves lexically
    assert add in calls[f"{ALPHA}:make_adder"]    # escapes by reference only


def test_callback_reference_edge(ctx):
    # map(local_cb, xs): the reference (not a call) still produces an edge
    assert f"{BETA}:local_cb" in ctx.calls[f"{BETA}:passes_callback"]


def test_reachability(ctx):
    seeds = [fid for fid in ctx.funcs if fid.startswith(f"{BETA}:")]
    seen = ctx.reachable(seeds)
    assert f"{ALPHA}:outer.<locals>.inner" in seen   # two hops via alias
    assert f"{ALPHA}:Engine.__init__" in seen
    # never called, never referenced: stays outside the closure
    assert f"{ALPHA}:unreached" not in seen
    # self-dispatch chain is NOT reachable from beta (instance-attr calls on
    # locals are out of scope by design), but is from its own seed
    assert f"{ALPHA}:Engine._bump" not in seen
    assert f"{ALPHA}:helper" in ctx.reachable([f"{ALPHA}:Engine.step"])


def test_static_params(ctx):
    # sized(4, True) + sized(8, False) + sized(k, True): n is tainted by the
    # non-static k, flag is a constant at every site
    assert ctx.static_params(f"{ALPHA}:sized") == {"flag"}
    # helper is fed a plain parameter somewhere -> nothing static
    assert ctx.static_params(f"{ALPHA}:helper") == set()
    # no visible call sites -> conservatively no static params
    assert ctx.static_params(f"{ALPHA}:make_adder") == set()
    # Engine(v): constructor's k is tainted through the call
    assert ctx.static_params(f"{ALPHA}:Engine.__init__") == set()


def test_stem_collision_keeps_both(ctx):
    """Two loose files with one stem: both analyzed, rel paths distinct."""
    tree = _load("alpha.py")
    c = build_context([("a/dup.py", tree), ("b/dup.py", _load("alpha.py"))])
    ta, tb = c.module_table("a/dup.py"), c.module_table("b/dup.py")
    assert ta is not None and tb is not None and ta is not tb
    assert ta.name == "dup" and tb.name.startswith("dup#")
