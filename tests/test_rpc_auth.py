"""RPC HMAC handshake (runtime/rpc.py): unauthorized peers are refused before
any pickle is deserialized; both directions authenticate (VERDICT r2 #8)."""

import socket

import pytest

from quokka_tpu.runtime.rpc import (
    RpcAuthError,
    RpcClient,
    RpcServer,
    default_token,
)


class Target:
    import threading

    def __init__(self):
        import threading

        self._lock = threading.RLock()
        self.calls = []

    def ping(self, x):
        self.calls.append(x)
        return x * 2


class TestHandshake:
    def test_authorized_roundtrip(self):
        t = Target()
        srv = RpcServer(t, token="s3cret")
        try:
            cli = RpcClient(srv.address, token="s3cret")
            assert cli.call("ping", 21) == 42
            cli.close()
        finally:
            srv.close()
        assert t.calls == [21]

    def test_wrong_token_refused(self):
        t = Target()
        srv = RpcServer(t, token="s3cret")
        try:
            with pytest.raises(RpcAuthError):
                RpcClient(srv.address, token="wrong")
        finally:
            srv.close()
        assert t.calls == []  # nothing was ever dispatched

    def test_raw_garbage_never_reaches_pickle(self):
        """A peer that skips the handshake and throws bytes at the port gets
        disconnected; the target object is never touched."""
        t = Target()
        srv = RpcServer(t, token="s3cret")
        try:
            s = socket.create_connection(srv.address, timeout=5)
            s.settimeout(5)
            s.recv(64)  # server's magic + nonce
            # a pickle-shaped payload without the HMAC reply shape would be
            # read AS the handshake reply and fail verification
            s.sendall(b"\x80\x04\x95" + b"A" * 45)
            # server must close without sending its own proof
            tail = b""
            try:
                while True:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    tail += chunk
            except socket.timeout:
                pytest.fail("server kept the unauthenticated connection open")
            assert tail == b""
            s.close()
        finally:
            srv.close()
        assert t.calls == []

    def test_server_must_prove_token_too(self):
        """A fake server that replies with a bogus proof is rejected by the
        client (protects the client's pickle path from a malicious server)."""
        import threading

        fake = socket.socket()
        fake.bind(("127.0.0.1", 0))
        fake.listen(1)

        def serve():
            conn, _ = fake.accept()
            conn.sendall(b"QRPC1" + b"N" * 16)
            conn.recv(48)
            conn.sendall(b"X" * 32)  # wrong proof
            conn.close()

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        try:
            with pytest.raises(RpcAuthError):
                RpcClient(fake.getsockname(), token="s3cret")
        finally:
            fake.close()

    def test_default_token_published_to_environ(self, monkeypatch):
        monkeypatch.delenv("QUOKKA_RPC_TOKEN", raising=False)
        import os

        tok = default_token()
        assert tok and os.environ["QUOKKA_RPC_TOKEN"] == tok
        assert default_token() == tok  # stable within the process
