"""Plan-invariant verifier (analysis/planck.py QK021-QK024) and the
differential optimizer fuzzer (analysis/planfuzz.py).

Three layers:
- known-answer fixtures: hand-broken plans per rule (bad schema, uncovered
  exchange key, illegal fusion, order claimed over unordered input,
  checkpoint barrier inside a fused stage) must raise naming that rule;
- regression tests for the true positives the verifier/fuzzer surfaced
  while being brought up (dead with_columns expr over a pruned source
  column, filter swapped below a sort claiming stale order, union sides
  pruned apart, disconnected leftovers after a rewrite);
- fuzzer harness self-tests: determinism, clean seeds, and injected
  optimizer bugs (BREAKERS) caught with a 1-minimal ddmin repro.
"""

import numpy as np
import pyarrow as pa
import pytest

from quokka_tpu import logical, optimizer
from quokka_tpu.analysis import planck, planfuzz
from quokka_tpu.analysis.shrink import ddmin
from quokka_tpu.context import QuokkaContext
from quokka_tpu.expression import col


def _fact(n=32):
    r = np.random.default_rng(3)
    return pa.table({
        "k": r.integers(0, 5, n).astype(np.int64),
        "j": r.integers(0, 3, n).astype(np.int64),
        "x": r.integers(0, 100, n).astype(np.int64),
    })


def _dim():
    return pa.table({"k": np.arange(5, dtype=np.int64),
                     "w": np.arange(5, dtype=np.int64) * 10})


def _plan(build, optimize=True):
    qc = QuokkaContext(optimize=optimize)
    ds = build(qc)
    sub, sink_id = qc._prepare_plan(ds.node_id)
    return sub, sink_id


def _join_shape(qc):
    return (qc.from_arrow(_fact()).filter(col("x") > 10)
            .join(qc.from_arrow(_dim()), on="k").select(["k", "j", "w"]))


def _rules_of(err: planck.PlanInvariantError):
    return {v.rule for v in err.violations}


# -- known-answer fixtures ----------------------------------------------------


def test_clean_plan_verifies():
    sub, sid = _plan(_join_shape)
    planck.verify_plan(sub, sid)  # no raise


def test_qk021_phantom_schema_column():
    sub, sid = _plan(_join_shape, optimize=False)
    join = next(n for n in sub.values() if isinstance(n, logical.JoinNode))
    join.schema = list(join.schema) + ["__phantom"]
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK021" in _rules_of(e.value)
    assert "__phantom" in str(e.value)


def test_qk021_bare_map_without_schema_metadata():
    sub, sid = _plan(_join_shape, optimize=False)
    fid = next(i for i, n in sub.items()
               if isinstance(n, logical.FilterNode))
    f = sub[fid]
    sub[fid] = logical.MapNode(list(f.parents), list(f.schema),
                               fn=lambda b: b)  # no exprs/rename/declared
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK021" in _rules_of(e.value)
    assert "exprs/rename/declared" in str(e.value)


def test_qk022_uncovered_exchange_key():
    sub, sid = _plan(_join_shape, optimize=False)
    join = next(n for n in sub.values() if isinstance(n, logical.JoinNode))
    join.right_on = ["nope"]
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK022" in _rules_of(e.value)
    assert "nope" in str(e.value)


def test_qk022_stateful_partitioner_on_pruned_column():
    from quokka_tpu.target_info import HashPartitioner

    def build(qc):
        return qc.from_arrow(_fact()).select(["k", "x"])

    sub, sid = _plan(build, optimize=False)
    src = next(i for i, n in sub.items()
               if isinstance(n, logical.SourceNode))
    proj = next(i for i, n in sub.items()
                if isinstance(n, logical.ProjectionNode))
    sub[proj] = logical.StatefulNode(
        [src], ["k", "x"], executor_factory=None,
        partitioners={0: HashPartitioner(["gone"])})
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK022" in _rules_of(e.value)


def test_qk022_sort_boundary_arity():
    def build(qc):
        return qc.from_arrow(_fact(n=64)).sort("x")

    sub, sid = _plan(build)
    srt = next(n for n in sub.values() if isinstance(n, logical.SortNode))
    assert srt.boundaries is not None, "parallel sort planning regressed"
    srt.boundaries = srt.boundaries[:-1]
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK022" in _rules_of(e.value)


def _fused_plan():
    sub, sid = _plan(_join_shape)
    fused = [n for n in sub.values()
             if isinstance(n, logical.FusedStageNode)]
    assert fused, "join+select no longer fuses — fixture shape regressed"
    return sub, sid, fused[0]


def test_qk023_order_carrying_member():
    sub, sid, stage = _fused_plan()
    stage.members[-1].sorted_by = ["k"]
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK023" in _rules_of(e.value)


def test_qk023_single_member_stage():
    sub, sid, stage = _fused_plan()
    keep = next(m for m in stage.members
                if not isinstance(m, logical.JoinNode))
    stage.members = [keep]
    stage.parents = stage.parents[:1]
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK023" in _rules_of(e.value)


def test_qk023_interior_hash_join():
    sub, sid, stage = _fused_plan()
    join = next(m for m in stage.members if isinstance(m, logical.JoinNode))
    if stage.members.index(join) == 0:
        # make the join interior by prepending a trivial member
        head = stage.members[0]
        f = logical.FilterNode(list(head.parents), list(sub[head.parents[0]].schema),
                               col("x") > -1)
        stage.members = [f] + stage.members
    join.broadcast = False
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK023" in _rules_of(e.value)


def test_qk023_fuse_round_trip_drift_caught():
    """verify_pass compares unfuse_stages(after) against the pre-pass
    digest — a pass that fuses AND rewrites a member is caught even when
    the rewritten plan is internally consistent."""
    sub, sid = _plan(_join_shape, optimize=False)
    for name, fn in optimizer.pass_pipeline():
        if name == "fuse_stages":
            before = planck.digest(sub, sid)
            fn(sub, sid)
            stage = next(n for n in sub.values()
                         if isinstance(n, logical.FusedStageNode))
            join = next(m for m in stage.members
                        if isinstance(m, logical.JoinNode))
            join.how = "left"  # semantics changed, schema identical
            with pytest.raises(planck.PlanInvariantError) as e:
                planck.verify_pass(sub, sid, name, before)
            assert "QK023" in _rules_of(e.value)
            assert "not structurally identical" in str(e.value)
            return
        fn(sub, sid)
    raise AssertionError("fuse_stages missing from pass pipeline")


def test_qk024_barrier_inside_fused_stage():
    sub, sid, stage = _fused_plan()
    stage.members[-1].checkpoint_barrier = True
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK024" in _rules_of(e.value)
    assert "checkpoints as one unit" in str(e.value)


def test_qk024_order_claimed_over_unordered_input():
    sub, sid = _plan(_join_shape, optimize=False)
    filt = next(n for n in sub.values() if isinstance(n, logical.FilterNode))
    filt.sorted_by = [filt.schema[0]]
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK024" in _rules_of(e.value)


def test_qk024_unbounded_source_single_channel():
    sub, sid = _plan(_join_shape, optimize=False)
    src = next(n for n in sub.values() if isinstance(n, logical.SourceNode))
    src.reader.UNBOUNDED = True
    src.channels = 2
    with pytest.raises(planck.PlanInvariantError) as e:
        planck.verify_plan(sub, sid)
    assert "QK024" in _rules_of(e.value)


# -- optimizer instrumentation ------------------------------------------------


def test_optimize_names_offending_pass(monkeypatch):
    """Under QK_PLAN_VERIFY a broken pass fails AT that pass, not at the
    end of the pipeline — the error names it."""
    real = optimizer.early_projection

    def broken(sub, sid):
        real(sub, sid)
        # corrupt metadata the post-pass schema recompute can NOT heal:
        # claim order on a filter over an unordered input (QK024)
        for n in sub.values():
            if isinstance(n, logical.JoinNode):
                n.sorted_by = [n.schema[0]]
                return

    monkeypatch.setattr(optimizer, "early_projection", broken)
    with pytest.raises(planck.PlanInvariantError) as e:
        _plan(_join_shape)
    assert e.value.where == "pass early_projection"
    assert "QK024" in _rules_of(e.value)


def test_verify_disabled_skips_checks(monkeypatch):
    monkeypatch.setenv("QK_PLAN_VERIFY", "0")
    assert not planck.enabled()
    before = planck.VERIFY_STATS["plans"]
    _plan(_join_shape)
    assert planck.VERIFY_STATS["plans"] == before


def test_verifier_overhead_within_budget():
    """Acceptance: per-query verifier overhead <= 5 ms at plan time."""
    _plan(_join_shape)
    assert planck.VERIFY_STATS["ms_last_plan"] <= 5.0, planck.VERIFY_STATS


def test_no_disconnected_nodes_after_optimize():
    """Pass pipeline garbage-collects nodes a rewrite disconnects (the
    pushed filter's original node used to linger)."""
    sub, sid = _plan(_join_shape)
    assert set(sub) == set(optimizer._reachable(sub, sid))


# -- regression tests for verifier/fuzzer-found true positives ----------------


def test_dead_with_columns_expr_is_pruned():
    """planfuzz-found: a with_columns output nobody consumes kept its input
    column requirement invisible to early_projection — the source pruned
    the column while the map still computed the expr.  The fix prunes the
    dead expr itself."""
    ops = [("with_columns", 34056, 13305), ("agg", 22200, 3536)]
    assert planfuzz.check_ops(ops) is None

    qc = QuokkaContext(optimize=False)
    ds = planfuzz.build(qc, ops)
    sub, sid = qc._prepare_plan(ds.node_id)
    for _, fn in optimizer.pass_pipeline():
        fn(sub, sid)
    for n in sub.values():
        if isinstance(n, logical.MapNode) and n.exprs is not None:
            assert "e0" not in n.exprs, "dead expr survived early_projection"
    planck.verify_plan(sub, sid)


def test_filter_below_sort_inherits_order():
    """push_filters swapping a filter below an order-producing node must
    re-derive the filter's sorted_by from its NEW input (QK024-found)."""
    def build(qc):
        return qc.from_arrow(_fact(n=64)).sort("x").filter(col("k") > 1)

    sub, sid = _plan(build)
    planck.verify_plan(sub, sid)
    for n in sub.values():
        if isinstance(n, logical.FilterNode) and n.sorted_by is not None:
            parent = sub[n.parents[0]]
            assert parent.sorted_by is not None


def test_union_sides_pruned_apart_rederives_schema():
    """QK021-found: early projection prunes union inputs differently (the
    pushed-predicate side keeps an extra column); the union schema must be
    re-derived as the intersection or the align step selects a missing
    column."""
    def build(qc):
        a = qc.from_arrow(_fact()).filter(col("x") > 50)
        b = qc.from_arrow(_fact())
        return a.union(b).select(["k"]).distinct()

    sub, sid = _plan(build)
    planck.verify_plan(sub, sid)


def test_sorted_source_keeps_order_column():
    """QK024-found: pruning a sorted source's projection must not drop the
    column the order contract names."""
    def build(qc):
        t = pa.table({"time": np.arange(32, dtype=np.int64),
                      "s": np.arange(32, dtype=np.int64) % 3,
                      "size": np.arange(32, dtype=np.int64)})
        return qc.from_arrow_sorted(t, sorted_by="time").select(["s", "size"])

    sub, sid = _plan(build)
    planck.verify_plan(sub, sid)
    src = next(n for n in sub.values() if isinstance(n, logical.SourceNode))
    assert "time" in src.schema


# -- shared ddmin (analysis/shrink.py) ----------------------------------------


def test_ddmin_is_1_minimal():
    trace = list(range(20))
    failing = lambda cand: 3 in cand and 11 in cand
    out = ddmin(trace, failing)
    assert sorted(out) == [3, 11]


def test_ddmin_single_culprit():
    assert ddmin(list(range(50)), lambda c: 37 in c) == [37]


def test_schedex_minimize_still_delegates():
    """schedex.minimize kept its public contract after extracting ddmin
    into analysis/shrink.py (tests/test_schedex.py runs the full check)."""
    from quokka_tpu.analysis import schedex

    assert callable(schedex.minimize)


# -- fuzzer harness self-tests ------------------------------------------------


def test_fuzzer_is_deterministic():
    assert planfuzz.gen_ops(17) == planfuzz.gen_ops(17)
    r1 = planfuzz.run_seed(5, shrink=False)
    r2 = planfuzz.run_seed(5, shrink=False)
    assert r1.ok == r2.ok and r1.ops == r2.ops and r1.summary() == r2.summary()


def test_fuzzer_clean_seed_batch():
    for seed in range(10):
        r = planfuzz.run_seed(seed, shrink=False)
        assert r.ok, r.summary()


def test_injected_drop_filter_caught_differentially_with_1_minimal_repro():
    r = planfuzz.run_seed(5, breaker="drop-filter")
    assert not r.ok and r.kind == "diff", r.summary()
    assert r.shrunk is not None and 1 <= len(r.shrunk) <= len(r.ops)
    # 1-minimality: removing ANY single op from the repro kills the failure
    check = lambda ops: planfuzz.check_ops(
        list(ops), breaker=planfuzz.BREAKERS["drop-filter"])
    assert check(r.shrunk) is not None
    for i in range(len(r.shrunk)):
        assert check(r.shrunk[:i] + r.shrunk[i + 1:]) is None, (
            f"repro is not 1-minimal: op {i} is removable")


def test_injected_phantom_column_caught_statically():
    r = planfuzz.run_seed(5, breaker="phantom-column", shrink=False)
    assert not r.ok and r.kind == "static" and "QK021" in r.detail


def test_injected_claim_order_caught_statically():
    r = planfuzz.run_seed(5, breaker="claim-order", shrink=False)
    assert not r.ok and r.kind == "static" and "QK024" in r.detail
