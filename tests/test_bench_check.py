"""bench.py --check: the perf-regression gate in file-vs-file mode — exits
nonzero on an injected 2x regression, zero on a clean rerun, parses every
baseline artifact shape, and prints the regressed query's critical-path
diff (ISSUE 5)."""

import importlib.util
import json
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("qk_bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _line(metric, value, detail=None):
    return {"metric": metric, "value": value, "unit": "x",
            "vs_baseline": value, "detail": detail or {}}


def _crit(compute, stall=0.0):
    return {"wall_s": compute + stall,
            "buckets": {"compile": 0.0, "scan_read": 0.0, "transfer": 0.0,
                        "compute": compute, "queue_wait": 0.0,
                        "stall": stall, "recovery": 0.0, "other": 0.0}}


def _baseline_lines():
    return [
        _line("tpch_q1_scan_gbps_per_chip", 0.60,
              {"critpath": _crit(0.3)}),
        _line("tpch_q3_speedup_vs_ref_per_chip", 0.33,
              {"critpath": _crit(1.7)}),
        _line("tpch_q135_speedup_geomean_per_chip", 0.57,
              {"queries": {"q3": {"critpath": _crit(1.7)}}}),
    ]


def _write_lines(path, lines):
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(json.dumps(d) for d in lines))
    return str(path)


class TestLoadMetrics:
    def test_json_lines(self, bench, tmp_path):
        p = _write_lines(tmp_path / "a.json", _baseline_lines())
        m = bench.load_metrics(p)
        assert set(m) == {"tpch_q1_scan_gbps_per_chip",
                         "tpch_q3_speedup_vs_ref_per_chip",
                         "tpch_q135_speedup_geomean_per_chip"}

    def test_driver_wrapper_shape(self, bench, tmp_path):
        tail = "\n".join(json.dumps(d) for d in _baseline_lines())
        p = tmp_path / "BENCH_r99.json"
        p.write_text(json.dumps({"n": 99, "rc": 0, "tail": tail,
                                 "parsed": _baseline_lines()[-1]}))
        m = bench.load_metrics(str(p))
        assert len(m) == 3
        assert m["tpch_q1_scan_gbps_per_chip"]["value"] == 0.60

    def test_checked_in_artifacts_parse(self, bench):
        root = os.path.dirname(_BENCH)
        p = os.path.join(root, "BENCH_r05.json")
        m = bench.load_metrics(p)
        assert "tpch_q135_speedup_geomean_per_chip" in m


class TestCheckRegressions:
    def test_clean_when_equal(self, bench):
        base = {d["metric"]: d for d in _baseline_lines()}
        rows, regressed = bench.check_regressions(base, dict(base))
        assert regressed == []
        assert all(st == "ok" for *_x, st in rows)

    def test_2x_regression_trips(self, bench):
        base = {d["metric"]: d for d in _baseline_lines()}
        cur = {k: dict(v) for k, v in base.items()}
        m = "tpch_q3_speedup_vs_ref_per_chip"
        cur[m] = dict(cur[m], value=base[m]["value"] / 2,
                      vs_baseline=base[m]["value"] / 2)
        rows, regressed = bench.check_regressions(base, cur)
        assert regressed == [m]

    def test_small_noise_passes(self, bench):
        base = {d["metric"]: d for d in _baseline_lines()}
        cur = {k: dict(v, value=v["value"] * 0.9,
                       vs_baseline=v["value"] * 0.9)
               for k, v in base.items()}
        _rows, regressed = bench.check_regressions(base, cur)
        assert regressed == []  # -10% is inside every threshold

    def test_missing_metric_is_a_regression(self, bench):
        base = {d["metric"]: d for d in _baseline_lines()}
        cur = dict(base)
        cur.pop("tpch_q1_scan_gbps_per_chip")
        _rows, regressed = bench.check_regressions(base, cur)
        assert regressed == ["tpch_q1_scan_gbps_per_chip"]

    def test_not_run_modes_are_not_missing(self, bench):
        """A fresh --check runs only --measure: service_* metrics captured
        in a fuller baseline must report as not-run, not REGRESSED."""
        base = {d["metric"]: d for d in _baseline_lines()}
        base["service_aggregate_speedup_geomean"] = _line(
            "service_aggregate_speedup_geomean", 0.9)
        cur = {d["metric"]: d for d in _baseline_lines()}
        rows, regressed = bench.check_regressions(
            base, cur, not_run_prefixes=("service_",))
        assert regressed == []
        assert ("service_aggregate_speedup_geomean", 0.9, None, None, None,
                "not-run") in rows

    def test_threshold_override(self, bench):
        base = {d["metric"]: d for d in _baseline_lines()}
        cur = {k: dict(v, value=v["value"] * 0.9,
                       vs_baseline=v["value"] * 0.9)
               for k, v in base.items()}
        _rows, regressed = bench.check_regressions(base, cur,
                                                   threshold=0.05)
        assert len(regressed) == len(base)


class TestCheckMain:
    def test_clean_rerun_exits_zero(self, bench, tmp_path, capsys):
        p = _write_lines(tmp_path / "base.json", _baseline_lines())
        rc = bench.check_main(["--against", p, "--current", p])
        assert rc == 0
        assert "clean" in capsys.readouterr().out

    def test_injected_2x_regression_exits_nonzero(self, bench, tmp_path,
                                                  capsys):
        """ISSUE 5 acceptance: nonzero on an artificially injected 2x
        regression, with the critical-path diff printed for the regressed
        query."""
        base = _write_lines(tmp_path / "base.json", _baseline_lines())
        lines = _baseline_lines()
        for d in lines:
            if d["metric"] == "tpch_q3_speedup_vs_ref_per_chip":
                d["value"] = d["vs_baseline"] = d["value"] / 2
                d["detail"]["critpath"] = _crit(1.7, stall=1.7)
        cur = _write_lines(tmp_path / "cur.json", lines)
        rc = bench.check_main(["--against", base, "--current", cur])
        assert rc == 1
        out = capsys.readouterr().out
        assert "REGRESSED" in out and "tpch_q3_speedup_vs_ref" in out
        # the critical-path diff names where the regression's time went
        assert "critical path" in out
        assert "stall" in out and "baseline" in out

    def test_truncated_wrapper_artifacts_compare_on_intersection(
            self, bench, capsys):
        """The driver's BENCH_r*.json wrappers keep only a 2000-byte
        stdout tail, so which metric lines survive is arbitrary: r04 kept
        q3 while r05 lost it.  Comparing two such artifacts must gate the
        intersection, not flag tail-truncation as MISSING regressions."""
        root = os.path.dirname(_BENCH)
        r04, r05 = (os.path.join(root, f"BENCH_r0{n}.json") for n in (4, 5))
        if not (os.path.exists(r04) and os.path.exists(r05)):
            pytest.skip("driver artifacts not present")
        assert bench._artifact_truncated(r05)
        rc = bench.check_main(["--against", r04, "--current", r05])
        assert rc == 0, capsys.readouterr().out
        assert "not-run" in capsys.readouterr().out

    def test_missing_baseline_file_is_an_error(self, bench, tmp_path):
        rc = bench.check_main(["--against", str(tmp_path / "nope.json"),
                               "--current", str(tmp_path / "nope.json")])
        assert rc == 2


class TestStrategyHonesty:
    """ISSUE 8 satellite: --check fails when a benched line records a
    kernel strategy its platform gates off (the VERDICT r5 finding —
    host-asof timings presented as the accelerator path)."""

    def test_gated_off_strategy_fails(self, bench, tmp_path, capsys):
        lines = _baseline_lines()
        lines.append(_line(
            "tick_asof_rows_per_s_per_chip", 0.48,
            {"platform": "tpu", "strategy": {"asof": "host"}}))
        p = _write_lines(tmp_path / "a.json", lines)
        rc = bench.check_main(["--against", p, "--current", p])
        assert rc == 1
        out = capsys.readouterr().out
        assert "GATED-OFF" in out and "asof=host" in out

    def test_runnable_strategy_passes(self, bench, tmp_path, capsys):
        lines = _baseline_lines()
        lines.append(_line(
            "tick_asof_rows_per_s_per_chip", 0.48,
            {"platform": "cpu", "strategy": {"asof": "host"}}))
        lines.append(_line(
            "tpch_q5_speedup_vs_ref_per_chip", 0.5,
            {"platform": "tpu", "strategy": {
                "join_build": "sort", "groupby": "sort",
                "shuffle": "masked"}}))
        p = _write_lines(tmp_path / "b.json", lines)
        rc = bench.check_main(["--against", p, "--current", p])
        assert rc == 0, capsys.readouterr().out

    def test_nested_geomean_strategies_validated(self, bench, tmp_path,
                                                 capsys):
        lines = _baseline_lines()
        lines.append(_line(
            "tpch_q135_speedup_geomean_per_chip2", 0.6,
            {"platform": "gpu", "queries": {
                "q3": {"strategy": {"asof": "host"}}}}))
        p = _write_lines(tmp_path / "c.json", lines)
        rc = bench.check_main(["--against", p, "--current", p])
        assert rc == 1
        assert "GATED-OFF" in capsys.readouterr().out

    def test_fresh_run_requires_strategy(self, bench):
        """In fresh-run mode the join/asof lines MUST carry strategies;
        exercised via check_strategy_honesty directly (a real fresh run is
        the full bench)."""
        cur = {m: _line(m, 0.5, {"platform": "cpu"})
               for m in bench.STRATEGY_REQUIRED_METRICS}
        rows, bad = bench.check_strategy_honesty(cur, require=True)
        assert len(bad) == len(bench.STRATEGY_REQUIRED_METRICS)
        assert all("MISSING" == status for _, status, _ in rows)
        rows, bad = bench.check_strategy_honesty(cur, require=False)
        assert not bad

    def test_fresh_run_requires_operators(self, bench):
        """Fresh join/asof lines must carry the EXPLAIN ANALYZE block
        (detail.operators); a missing block is a regression."""
        cur = {m: _line(m, 0.5, {"platform": "cpu"})
               for m in bench.STRATEGY_REQUIRED_METRICS}
        rows, bad = bench.check_operators_presence(cur, require=True)
        assert len(bad) == len(bench.STRATEGY_REQUIRED_METRICS)
        assert all(status == "MISSING" for _, status, _ in rows)
        # presence satisfies the gate — flat detail or nested geomean shape
        ops = {"operators": [{"actor": 1, "op": "JoinExecutor"}],
               "skew": [], "rows_unknown": 0}
        cur = {m: _line(m, 0.5, {"operators": ops})
               for m in bench.STRATEGY_REQUIRED_METRICS}
        rows, bad = bench.check_operators_presence(cur, require=True)
        assert not bad and all(status == "ok" for _, status, _ in rows)
        nested = {"tpch_q3_speedup_vs_ref_per_chip": _line(
            "tpch_q3_speedup_vs_ref_per_chip", 0.5,
            {"queries": {"q3": {"operators": ops}}})}
        rows, bad = bench.check_operators_presence(nested, require=True)
        assert not bad
        # --current file-vs-file mode never requires presence
        rows, bad = bench.check_operators_presence(
            {m: _line(m, 0.5) for m in bench.STRATEGY_REQUIRED_METRICS},
            require=False)
        assert not bad and not rows

    def test_fresh_run_requires_fused_stages(self, bench):
        """Fresh join lines must prove the whole-stage-fused plan ran:
        a missing detail.fused_stages OR a zero count is a regression."""
        cur = {m: _line(m, 0.5, {"platform": "cpu"})
               for m in bench.FUSION_REQUIRED_METRICS}
        rows, bad = bench.check_fused_stages_presence(cur, require=True)
        assert len(bad) == len(bench.FUSION_REQUIRED_METRICS)
        assert all(status == "MISSING" for _, status, _ in rows)
        # zero fused stages on a join query is the win evaporating
        cur = {m: _line(m, 0.5, {"fused_stages": 0})
               for m in bench.FUSION_REQUIRED_METRICS}
        rows, bad = bench.check_fused_stages_presence(cur, require=True)
        assert len(bad) == len(bench.FUSION_REQUIRED_METRICS)
        # >= 1 satisfies the gate
        cur = {m: _line(m, 0.5, {"fused_stages": 1})
               for m in bench.FUSION_REQUIRED_METRICS}
        rows, bad = bench.check_fused_stages_presence(cur, require=True)
        assert not bad and all(status == "ok" for _, status, _ in rows)
        # --current file-vs-file mode never requires presence
        rows, bad = bench.check_fused_stages_presence(
            {m: _line(m, 0.5) for m in bench.FUSION_REQUIRED_METRICS},
            require=False)
        assert not bad and not rows


def test_cli_subprocess_roundtrip(tmp_path):
    """The real `python bench.py --check` entry point, end to end."""
    import subprocess

    base = _write_lines(tmp_path / "base.json", _baseline_lines())
    lines = _baseline_lines()
    lines[1]["value"] = lines[1]["vs_baseline"] = lines[1]["value"] / 2
    cur = _write_lines(tmp_path / "cur.json", lines)
    ok = subprocess.run(
        [sys.executable, _BENCH, "--check", "--against", base,
         "--current", base],
        capture_output=True, text=True, timeout=120)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run(
        [sys.executable, _BENCH, "--check", "--against", base,
         "--current", cur],
        capture_output=True, text=True, timeout=120)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "REGRESSION" in bad.stdout
