"""Deterministic-schedule explorer (analysis/schedex.py).

The explorer is the static-analysis-plane record of the TestKill9Recovery
root cause: under the OLD covering rule some seeded interleavings wedge
(a co-dead consumer's live-phase frontier need past every surviving copy),
and under the SHIPPED frontier rule (engine.plan_rewinds) none do."""

import pytest

from quokka_tpu.analysis.schedex import (
    explore, main, minimize, run_schedule)

SEEDS = 300


@pytest.fixture(scope="module")
def covering_wedges():
    return explore("covering", SEEDS)


def test_old_rule_wedges(covering_wedges):
    """The bug is reachable: the covering rule leaves wedging schedules."""
    assert covering_wedges, "explorer lost the repro"


def test_shipped_rule_never_wedges():
    assert explore("frontier", SEEDS) == []


def test_same_seed_same_schedule(covering_wedges):
    seed, r = covering_wedges[0]
    again = run_schedule(seed, "covering")
    assert again.trace == r.trace
    assert again.detail == r.detail
    assert again.wedged


def test_wedging_trace_passes_under_shipped_rule(covering_wedges):
    """The SAME interleaving that wedges under the old rule completes under
    the shipped one — the fix, not schedule luck, closes the race."""
    _seed, r = covering_wedges[0]
    replay = run_schedule(None, "frontier", trace=r.trace)
    assert not replay.wedged, replay.detail


def test_minimize_is_one_minimal(covering_wedges):
    _seed, r = covering_wedges[0]
    mini = minimize(r.trace, "covering")
    assert run_schedule(None, "covering", trace=mini).wedged
    assert len(mini) <= len(r.trace)
    # 1-minimal: removing ANY single action un-wedges
    for i in range(len(mini)):
        cand = mini[:i] + mini[i + 1:]
        assert not run_schedule(None, "covering", trace=cand).wedged, (
            i, mini)
    # the minimal schedule names the protocol steps, and the kill/recover
    # pair is always part of the story
    verbs = [a[0] for a in mini]
    assert "kill" in verbs and "recover" in verbs


def test_minimal_repro_passes_under_shipped_rule(covering_wedges):
    _seed, r = covering_wedges[0]
    mini = minimize(r.trace, "covering")
    assert not run_schedule(None, "frontier", trace=mini).wedged


def test_cli(capsys):
    # compare-both mode: informative about the old rule, clean shipped rule
    assert main(["--seeds", "80"]) == 0
    out = capsys.readouterr().out
    assert "rule=covering" in out and "rule=frontier: 0/80" in out
    # replaying a wedging seed exits nonzero and prints the trace
    wedges = explore("covering", SEEDS)
    seed = wedges[0][0]
    assert main(["--seed", str(seed), "--rule", "covering"]) == 1
    out = capsys.readouterr().out
    assert "WEDGED" in out and "kill" in out
    assert main(["--seed", str(seed), "--rule", "frontier"]) == 0
