"""Writers, vector search, gramian/covariance, approximate quantiles, native lib."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext


@pytest.fixture
def ctx():
    return QuokkaContext()


class TestWriters:
    def test_write_parquet_roundtrip(self, ctx, table, pdf, tmp_path):
        out = str(tmp_path / "out")
        names = ctx.from_arrow(table).write_parquet(out, rows_per_file=300)
        files = sorted(glob.glob(os.path.join(out, "*.parquet")))
        assert len(files) >= 3 and set(names.filename) == set(files)
        back = ctx.read_parquet(os.path.join(out, "*.parquet")).collect()
        assert len(back) == len(pdf)
        pd.testing.assert_frame_equal(
            back.sort_values(["k", "v"]).reset_index(drop=True)[pdf.columns.tolist()],
            pdf.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False,
        )

    def test_write_csv(self, ctx, table, pdf, tmp_path):
        out = str(tmp_path / "csvout")
        ctx.from_arrow(table).select(["k", "q"]).write_csv(out)
        back = ctx.read_csv(os.path.join(out, "*.csv")).collect()
        assert len(back) == len(pdf)
        assert back.k.sum() == pdf.k.sum()


class TestVectors:
    def test_nearest_neighbors(self, ctx):
        r = np.random.default_rng(5)
        n, d, nq, k = 2000, 32, 4, 5
        vecs = r.normal(size=(n, d)).astype(np.float32)
        queries = r.normal(size=(nq, d)).astype(np.float32)
        t = pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "emb": pa.FixedSizeListArray.from_arrays(
                    pa.array(vecs.reshape(-1)), d
                ),
            }
        )
        got = ctx.from_arrow(t).nearest_neighbors(queries, "emb", k).collect()
        # oracle
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        sims = qn @ vn.T
        for qi in range(nq):
            exp_ids = set(np.argsort(-sims[qi])[:k].tolist())
            got_ids = set(got[got.query_idx == qi].id.tolist())
            assert got_ids == exp_ids, f"query {qi}"

    def test_nearest_neighbors_multi_batch(self, ctx):
        from quokka_tpu.dataset.readers import InputArrowDataset

        r = np.random.default_rng(6)
        n, d = 3000, 16
        vecs = r.normal(size=(n, d)).astype(np.float32)
        queries = r.normal(size=(2, d)).astype(np.float32)
        t = pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "emb": pa.FixedSizeListArray.from_arrays(pa.array(vecs.reshape(-1)), d),
            }
        )
        s = ctx.read_dataset(InputArrowDataset(t, batch_rows=256))
        got = s.nearest_neighbors(queries, "emb", 3).collect()
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        sims = qn @ vn.T
        for qi in range(2):
            assert set(got[got.query_idx == qi].id) == set(np.argsort(-sims[qi])[:3])


class TestLinalg:
    def test_gramian(self, ctx, table, pdf):
        got = ctx.from_arrow(table).gramian(["v", "q"]).collect()
        X = pdf[["v", "q"]].to_numpy(dtype=np.float64)
        exp = X.T @ X
        got = got.set_index("column").loc[["v", "q"], ["v", "q"]].to_numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4)

    def test_covariance(self, ctx, table, pdf):
        got = ctx.from_arrow(table).covariance(["v", "q"]).collect()
        X = pdf[["v", "q"]].to_numpy(dtype=np.float64)
        exp = np.cov(X.T, bias=True)
        got = got.set_index("column").loc[["v", "q"], ["v", "q"]].to_numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-3, atol=1e-4)

    def test_approximate_quantile(self, ctx, table, pdf):
        got = ctx.from_arrow(table).approximate_quantile("v", [0.1, 0.5, 0.9]).collect()
        exp = np.quantile(pdf.v, [0.1, 0.5, 0.9])
        got = got.sort_values("quantile").v.to_numpy()
        np.testing.assert_allclose(got, exp, atol=0.15)


class TestNative:
    def test_hash_parity(self):
        from quokka_tpu.ops.batch import fnv1a64
        from quokka_tpu.utils import native

        vals = ["alpha", "beta", "", "äöü", None]
        out = native.fnv1a64_many(vals)
        if out is None:
            pytest.skip("native lib not built")
        exp = [fnv1a64(v) if v is not None else 0 for v in vals]
        np.testing.assert_array_equal(out, np.array(exp, dtype=np.uint64))


class TestCogroup:
    def test_cogroup_udf(self, ctx):
        import pandas as pd
        import pyarrow as pa

        a = pa.table({"k": [1, 1, 2, 3], "v": [1.0, 2.0, 3.0, 4.0]})
        b = pa.table({"k": [1, 2, 2, 4], "w": [10.0, 20.0, 30.0, 40.0]})

        def f(k, l, r):
            return pd.DataFrame({
                "k": [k], "nv": [len(l)], "nw": [len(r)],
                "ratio": [(l.v.sum() + 1) / (r.w.sum() + 1)],
            })

        got = (
            ctx.from_arrow(a)
            .cogroup(ctx.from_arrow(b), f, ["k", "nv", "nw", "ratio"], on="k")
            .collect()
            .sort_values("k")
            .reset_index(drop=True)
        )
        assert got.k.tolist() == [1, 2, 3, 4]
        assert got.nv.tolist() == [2, 1, 1, 0]
        assert got.nw.tolist() == [1, 2, 0, 1]


class TestTDigest:
    def test_mergeable_accuracy(self):
        import numpy as np

        from quokka_tpu.ops.tdigest import TDigest

        r = np.random.default_rng(3)
        x = np.concatenate([r.normal(size=50000), r.exponential(2, 50000)])
        parts = [TDigest() for _ in range(4)]
        for i, p in enumerate(parts):
            p.add(x[i::4])
        d = parts[0]
        for p in parts[1:]:
            d.merge(p)
        for q in (0.05, 0.5, 0.95, 0.99):
            exact = np.quantile(x, q)
            est = d.quantile(q)
            denom = max(abs(exact), 0.1)
            assert abs(est - exact) / denom < 0.02, (q, est, exact)

    def test_quantile_query_partition_independent(self, ctx):
        import numpy as np
        import pyarrow as pa

        r = np.random.default_rng(4)
        x = r.normal(size=30000)
        t = pa.table({"v": x})
        got = ctx.from_arrow(t).approximate_quantile("v", [0.25, 0.5, 0.75]).collect()
        got = got.sort_values("quantile").reset_index(drop=True)
        exp = np.quantile(x, [0.25, 0.5, 0.75])
        np.testing.assert_allclose(got.v.to_numpy(), exp, atol=0.02)

    def test_cogroup_one_sided_channels(self):
        # channels whose hash partition receives rows on only ONE side must
        # still hand fn a schema'd empty frame for the other side
        import pandas as pd
        import pyarrow as pa

        from quokka_tpu import QuokkaContext

        ctx4 = QuokkaContext(exec_channels=4)
        left = pa.table({"k": [1], "v": [7.0]})
        right = pa.table({"k": list(range(20)), "w": [float(i) for i in range(20)]})

        def f(k, l, r):
            return pd.DataFrame({
                "k": [k], "sv": [l["v"].sum() if len(l) else 0.0],
                "sw": [r["w"].sum() if len(r) else 0.0],
            })

        got = (
            ctx4.from_arrow(left)
            .cogroup(ctx4.from_arrow(right), f, ["k", "sv", "sw"], on="k")
            .collect()
            .sort_values("k")
            .reset_index(drop=True)
        )
        assert len(got) == 20
        assert got[got.k == 1].sv.iloc[0] == 7.0
        assert got.sw.sum() == sum(range(20))


class TestMetrics:
    def test_progress_counters(self):
        import numpy as np
        import pyarrow as pa

        from quokka_tpu import QuokkaContext

        r = np.random.default_rng(0)
        t = pa.table({"k": r.integers(0, 10, 5000).astype(np.int64),
                      "v": r.uniform(0, 1, 5000)})
        ctx = QuokkaContext()
        got = ctx.from_arrow(t).groupby("k").agg_sql("sum(v) as s").collect()
        assert len(got) == 10
        m = ctx.latest_graph.metrics()
        assert m, "no metrics flushed"
        actors = {k: v for k, v in m.items() if isinstance(k, tuple)}
        input_rows = sum(v["rows"] for v in actors.values() if v["bytes"] > 0)
        assert input_rows == 5000
        assert all(v["tasks"] > 0 for v in actors.values())
        # the compile-reuse counters ride along under a string key
        assert m["compile"]["traces"] > 0
