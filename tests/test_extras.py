"""Writers, vector search, gramian/covariance, approximate quantiles, native lib."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext


@pytest.fixture
def ctx():
    return QuokkaContext()


class TestWriters:
    def test_write_parquet_roundtrip(self, ctx, table, pdf, tmp_path):
        out = str(tmp_path / "out")
        names = ctx.from_arrow(table).write_parquet(out, rows_per_file=300)
        files = sorted(glob.glob(os.path.join(out, "*.parquet")))
        assert len(files) >= 3 and set(names.filename) == set(files)
        back = ctx.read_parquet(os.path.join(out, "*.parquet")).collect()
        assert len(back) == len(pdf)
        pd.testing.assert_frame_equal(
            back.sort_values(["k", "v"]).reset_index(drop=True)[pdf.columns.tolist()],
            pdf.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False,
        )

    def test_write_csv(self, ctx, table, pdf, tmp_path):
        out = str(tmp_path / "csvout")
        ctx.from_arrow(table).select(["k", "q"]).write_csv(out)
        back = ctx.read_csv(os.path.join(out, "*.csv")).collect()
        assert len(back) == len(pdf)
        assert back.k.sum() == pdf.k.sum()


class TestVectors:
    def test_nearest_neighbors(self, ctx):
        r = np.random.default_rng(5)
        n, d, nq, k = 2000, 32, 4, 5
        vecs = r.normal(size=(n, d)).astype(np.float32)
        queries = r.normal(size=(nq, d)).astype(np.float32)
        t = pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "emb": pa.FixedSizeListArray.from_arrays(
                    pa.array(vecs.reshape(-1)), d
                ),
            }
        )
        got = ctx.from_arrow(t).nearest_neighbors(queries, "emb", k).collect()
        # oracle
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        sims = qn @ vn.T
        for qi in range(nq):
            exp_ids = set(np.argsort(-sims[qi])[:k].tolist())
            got_ids = set(got[got.query_idx == qi].id.tolist())
            assert got_ids == exp_ids, f"query {qi}"

    def test_nearest_neighbors_multi_batch(self, ctx):
        from quokka_tpu.dataset.readers import InputArrowDataset

        r = np.random.default_rng(6)
        n, d = 3000, 16
        vecs = r.normal(size=(n, d)).astype(np.float32)
        queries = r.normal(size=(2, d)).astype(np.float32)
        t = pa.table(
            {
                "id": np.arange(n, dtype=np.int64),
                "emb": pa.FixedSizeListArray.from_arrays(pa.array(vecs.reshape(-1)), d),
            }
        )
        s = ctx.read_dataset(InputArrowDataset(t, batch_rows=256))
        got = s.nearest_neighbors(queries, "emb", 3).collect()
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        sims = qn @ vn.T
        for qi in range(2):
            assert set(got[got.query_idx == qi].id) == set(np.argsort(-sims[qi])[:3])


class TestLinalg:
    def test_gramian(self, ctx, table, pdf):
        got = ctx.from_arrow(table).gramian(["v", "q"]).collect()
        X = pdf[["v", "q"]].to_numpy(dtype=np.float64)
        exp = X.T @ X
        got = got.set_index("column").loc[["v", "q"], ["v", "q"]].to_numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-4)

    def test_covariance(self, ctx, table, pdf):
        got = ctx.from_arrow(table).covariance(["v", "q"]).collect()
        X = pdf[["v", "q"]].to_numpy(dtype=np.float64)
        exp = np.cov(X.T, bias=True)
        got = got.set_index("column").loc[["v", "q"], ["v", "q"]].to_numpy()
        np.testing.assert_allclose(got, exp, rtol=1e-3, atol=1e-4)

    def test_approximate_quantile(self, ctx, table, pdf):
        got = ctx.from_arrow(table).approximate_quantile("v", [0.1, 0.5, 0.9]).collect()
        exp = np.quantile(pdf.v, [0.1, 0.5, 0.9])
        got = got.sort_values("quantile").v.to_numpy()
        np.testing.assert_allclose(got, exp, atol=0.15)


class TestNative:
    def test_hash_parity(self):
        from quokka_tpu.ops.batch import fnv1a64
        from quokka_tpu.utils import native

        vals = ["alpha", "beta", "", "äöü", None]
        out = native.fnv1a64_many(vals)
        if out is None:
            pytest.skip("native lib not built")
        exp = [fnv1a64(v) if v is not None else 0 for v in vals]
        np.testing.assert_array_equal(out, np.array(exp, dtype=np.uint64))
