"""Device hash-table kernels (ops/hashtable.py): parity with the sort-based
paths plus the edge cases the sort paths define the semantics for —
64-bit limbs (the x64 test regime stores ints as one int64 limb), NaN keys
(each its own group; never a join match), -0.0 == 0.0, cross-dtype joins.

Reference behavior matched: polars groupby/join inside the reference's
executors (pyquokka/executors/sql_executors.py:325-378).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quokka_tpu.ops import hashtable as H
from quokka_tpu.ops import join as J
from quokka_tpu.ops import kernels
from quokka_tpu.ops.batch import DeviceBatch, NumCol


def _batch(cols, n, pad=None):
    pad = pad or max(256, 1 << int(np.ceil(np.log2(max(n, 1)))))
    out = {}
    for name, (arr, kind) in cols.items():
        a = np.asarray(arr)
        a = np.pad(a, (0, pad - len(a)))
        out[name] = NumCol(jnp.array(a), kind)
    return DeviceBatch(out, jnp.arange(pad) < n)


def _grouped_to_np(g, names):
    n = g.count_valid()
    d = {m: np.asarray(g.columns[m].data[:n]) for m in names}
    order = np.lexsort([d[names[0]]])
    return {m: v[order] for m, v in d.items()}


@pytest.mark.parametrize("op", ["sum", "min", "max", "mean", "count", "first"])
def test_hash_groupby_matches_sorted(op, monkeypatch):
    r = np.random.default_rng(11)
    n = 3000
    keys = r.integers(0, 500, n)
    vals = r.random(n)
    b = _batch({"k": (keys, "i"), "v": (vals, "f")}, n)
    aggs = [("o", op, b.columns["v"].data)]
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")
    g1 = _grouped_to_np(kernels.groupby_aggregate(b, ["k"], aggs), ["k", "o"])
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "0")
    g2 = _grouped_to_np(kernels.groupby_aggregate(b, ["k"], aggs), ["k", "o"])
    np.testing.assert_array_equal(g1["k"], g2["k"])
    np.testing.assert_allclose(g1["o"], g2["o"], rtol=1e-6)


def test_hash_groupby_wide_int64_keys(monkeypatch):
    """Keys that differ only above bit 31 must stay distinct groups (the x64
    regime stores them as ONE int64 limb; truncation would merge them)."""
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")
    lo = np.array([5, 7, 5, 7], dtype=np.int64)
    keys = lo + (np.array([0, 0, 1, 1], dtype=np.int64) << 32)
    b = _batch({"k": (keys, "i"), "v": (np.ones(4), "f")}, 4)
    g = kernels.groupby_aggregate(b, ["k"], [("s", "sum", b.columns["v"].data)])
    assert g.count_valid() == 4


def test_hash_groupby_nan_and_negzero(monkeypatch):
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")
    keys = np.array([1.5, np.nan, -0.0, np.nan, 0.0, 1.5])
    b = _batch({"k": (keys, "f"), "v": (np.ones(6), "f")}, 6)
    g = kernels.groupby_aggregate(b, ["k"], [("s", "sum", b.columns["v"].data)])
    # groups: {1.5 x2}, {0.0, -0.0}, and each NaN alone -> 4 groups
    assert g.count_valid() == 4
    n = g.count_valid()
    sums = sorted(np.asarray(g.columns["s"].data[:n]).tolist())
    assert sums == [1.0, 1.0, 2.0, 2.0]


def test_pk_join_hash_matches_sorted_and_cross_dtype(monkeypatch):
    r = np.random.default_rng(3)
    bk = r.permutation(4000)[:1500]
    build = _batch({"k": (bk.astype(np.float64), "f"),
                    "pay": (bk * 3, "i")}, 1500)
    pk = r.integers(0, 4000, 2048)
    probe = _batch({"k": (pk, "i")}, 2048)  # int probe vs float build
    results = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("QUOKKA_HASH_TABLES", flag)
        bcopy = DeviceBatch(dict(build.columns), build.valid)
        out = J.hash_join_pk(probe, bcopy, ["k"], ["k"], "inner", ["pay"])
        v = np.asarray(out.valid)
        results[flag] = (v, np.asarray(out.columns["pay"].data)[v])
    np.testing.assert_array_equal(results["1"][0], results["0"][0])
    np.testing.assert_array_equal(results["1"][1], results["0"][1])
    assert results["1"][0].sum() > 0


def test_pk_join_nan_never_matches(monkeypatch):
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")
    build = _batch({"k": (np.array([1.0, np.nan, 3.0]), "f"),
                    "pay": (np.array([10, 20, 30]), "i")}, 3)
    probe = _batch({"k": (np.array([np.nan, 1.0, 3.0]), "f")}, 3)
    out = J.hash_join_pk(probe, build, ["k"], ["k"], "inner", ["pay"])
    v = np.asarray(out.valid)
    assert v.tolist()[:3] == [False, True, True]


def test_insert_claims_are_stable():
    """Regression: a later-round scatter of a smaller row id must not evict
    an earlier claim (the round-packed priority makes claims stable); every
    inserted key must be findable by its own probe sequence."""
    r = np.random.default_rng(1)
    for n, space in ((900, 2000), (4000, 10**6), (5000, 6000)):
        keys = r.permutation(space)[:n].astype(np.int64)
        pad = 1 << int(np.ceil(np.log2(n)))
        limbs = H.canonical_limbs(
            (jnp.array(np.pad(keys, (0, pad - n))),), nan_unique=False)
        valid = jnp.arange(pad) < n
        capbits = H.capbits_for(pad)
        _, tbl, converged = H._insert(limbs, valid, capbits)
        assert bool(converged)
        plimbs = H.canonical_limbs((jnp.array(keys),), nan_unique=False)
        bidx, ok = H._probe(tbl, limbs, plimbs, jnp.ones(n, bool), capbits)
        assert bool(np.asarray(ok).all())
        np.testing.assert_array_equal(np.asarray(bidx), np.arange(n))


def test_hash_groupby_empty_and_all_invalid():
    b = DeviceBatch({"k": NumCol(jnp.zeros(256, jnp.int32), "i")},
                    jnp.zeros(256, bool))
    g = kernels.groupby_aggregate(
        b, ["k"], [("c", "count", None)])
    assert g.count_valid() == 0


# -- insert non-convergence must never fail silently ------------------------
# (advisor finding hashtable.py:178: unplaced rows used to keep myslot=0 and
# silently merge into slot 0's group; now the flag routes untraced callers
# to the sort path / a loud error.)


def test_hash_groupby_falls_back_to_sort_on_nonconvergence(monkeypatch):
    r = np.random.default_rng(5)
    n = 2000
    keys = r.integers(0, 300, n)
    vals = r.random(n)
    b = _batch({"k": (keys, "i"), "v": (vals, "f")}, n)
    aggs = [("o", "sum", b.columns["v"].data)]
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "0")
    want = _grouped_to_np(kernels.groupby_aggregate(b, ["k"], aggs),
                          ["k", "o"])

    # force the jitted body to report non-convergence: hash_groupby must
    # answer through sorted_groupby, not through the (fake-)broken table
    real = H._hash_groupby_jit

    def broken(limbs, arrays, ops, valid, capbits):
        outs, counts, rep, num, _ = real(limbs, arrays, ops, valid, capbits)
        return (tuple(jnp.zeros_like(o) for o in outs), counts, rep,
                jnp.int64(1), jnp.array(False))

    monkeypatch.setattr(H, "_hash_groupby_jit", broken)
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")
    got = _grouped_to_np(kernels.groupby_aggregate(b, ["k"], aggs),
                         ["k", "o"])
    np.testing.assert_array_equal(got["k"], want["k"])
    np.testing.assert_allclose(got["o"], want["o"], rtol=1e-9)


def test_build_table_raises_on_nonconvergence(monkeypatch):
    build = _batch({"k": (np.arange(100), "i")}, 100)

    def broken_insert(limbs, valid, capbits):
        myslot, tbl, _ = H._insert_jit(limbs, valid, capbits)
        return myslot, tbl, jnp.array(False)

    calls = []

    def counting_broken_insert(limbs, valid, capbits):
        calls.append(1)
        return broken_insert(limbs, valid, capbits)

    monkeypatch.setattr(H, "_insert", counting_broken_insert)
    with pytest.raises(H.HashTableConvergenceError):
        H.build_table(build, ["k"],
                      lambda b, ks: [b.columns[k].data for k in ks],
                      lambda: build.valid)
    # non-convergence is negatively cached on the batch: the next probe
    # batch must NOT re-run the failed insert loop
    with pytest.raises(H.HashTableConvergenceError):
        H.build_table(build, ["k"],
                      lambda b, ks: [b.columns[k].data for k in ks],
                      lambda: build.valid)
    assert len(calls) == 1


def test_pk_join_survives_nonconvergent_build(monkeypatch):
    """hash_join_pk must answer THROUGH THE SORT PATH when the table build
    reports non-convergence — same rows as the sort-only run."""
    r = np.random.default_rng(9)
    bk = r.permutation(3000)[:1000]
    build = _batch({"k": (bk, "i"), "pay": (bk * 2, "i")}, 1000)
    probe = _batch({"k": (r.integers(0, 3000, 1024), "i")}, 1024)
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "0")
    want = J.hash_join_pk(probe, build, ["k"], ["k"], "inner", ["pay"])

    def always_diverges(*a, **kw):
        raise H.HashTableConvergenceError("forced by test")

    monkeypatch.setattr(H, "build_table", always_diverges)
    monkeypatch.setenv("QUOKKA_HASH_TABLES", "1")
    bcopy = DeviceBatch(dict(build.columns), build.valid)
    probe2 = DeviceBatch(dict(probe.columns), probe.valid)
    got = J.hash_join_pk(probe2, bcopy, ["k"], ["k"], "inner", ["pay"])
    np.testing.assert_array_equal(np.asarray(got.valid),
                                  np.asarray(want.valid))
    v = np.asarray(want.valid)
    np.testing.assert_array_equal(
        np.asarray(got.columns["pay"].data)[v],
        np.asarray(want.columns["pay"].data)[v])


def test_use_host_asof_gated_to_cpu(monkeypatch):
    """Satellite config.py:114: auto mode enables the host as-of walk ONLY
    where np.asarray is zero-copy (CPU); GPU/TPU keep the device kernel.
    The env override still wins everywhere."""
    from quokka_tpu import config

    monkeypatch.delenv("QUOKKA_HOST_ASOF", raising=False)
    for plat, want in (("cpu", True), ("gpu", False), ("tpu", False)):
        monkeypatch.setattr(config, "_platform", lambda p=plat: p)
        assert config.use_host_asof() is want, plat
    monkeypatch.setattr(config, "_platform", lambda: "gpu")
    monkeypatch.setenv("QUOKKA_HOST_ASOF", "1")
    assert config.use_host_asof() is True
    monkeypatch.setenv("QUOKKA_HOST_ASOF", "0")
    monkeypatch.setattr(config, "_platform", lambda: "cpu")
    assert config.use_host_asof() is False
