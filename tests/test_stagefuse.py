"""Whole-stage fusion tests: linear operator chains collapse into ONE
FusedStage actor (optimizer.fuse_stages -> ops/stagefuse.py) and the fused
plan is BIT-EXACT vs the unfused one — integer-valued columns with group
sums far below 2**53, so equality is exact, not a tolerance story.  Chain
boundaries (multi-consumer producers, blocking operators) must NOT fuse,
and a chaos kill mid-stage must recover to the identical answer."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from quokka_tpu import QuokkaContext, col, logical
from quokka_tpu.dataset.readers import InputArrowDataset
from quokka_tpu.optimizer import _reachable, optimize


def make_tables(seed=9, n=20_000, n1=300, n2=40):
    r = np.random.default_rng(seed)
    fact = pa.table({
        "fk": r.integers(0, n1, n).astype(np.int64),
        "v": r.integers(0, 1000, n).astype(np.int64),
        "flag": r.integers(0, 4, n).astype(np.int64),
    })
    dim1 = pa.table({
        "pk": np.arange(n1, dtype=np.int64),
        "ck": r.integers(0, n2, n1).astype(np.int64),
        "w": r.integers(1, 5, n1).astype(np.int64),
    })
    dim2 = pa.table({
        "pk2": np.arange(n2, dtype=np.int64),
        "grp": r.integers(0, 8, n2).astype(np.int64),
    })
    return fact, dim1, dim2


def q3_stream(ctx, fact, dim1, dim2):
    """Q3 shape: filter -> broadcast join -> broadcast join -> group agg —
    one maximal fusible chain."""
    fs = ctx.read_dataset(InputArrowDataset(fact, batch_rows=1024))
    d1 = ctx.read_dataset(InputArrowDataset(dim1, batch_rows=128))
    d2 = ctx.read_dataset(InputArrowDataset(dim2, batch_rows=128))
    return (
        fs.filter(col("flag") < 3)
        .join(d1, left_on="fk", right_on="pk")
        .join(d2, left_on="ck", right_on="pk2")
        .groupby("grp")
        .agg_sql("sum(v) as sv, count(*) as n")
    )


def q5_stream(ctx, fact, dim1, dim2):
    """Q5 shape: the Q3 chain plus a map (revenue-style product) and a
    post-join filter riding inside the same fused stage."""
    fs = ctx.read_dataset(InputArrowDataset(fact, batch_rows=1024))
    d1 = ctx.read_dataset(InputArrowDataset(dim1, batch_rows=128))
    d2 = ctx.read_dataset(InputArrowDataset(dim2, batch_rows=128))
    return (
        fs.filter(col("flag") < 3)
        .join(d1, left_on="fk", right_on="pk")
        .with_columns({"rev": col("v") * col("w")})
        .filter(col("w") > 1)
        .join(d2, left_on="ck", right_on="pk2")
        .groupby("grp")
        .agg_sql("sum(rev) as rev, count(*) as n")
    )


def _canon(df):
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _fused_vs_unfused(monkeypatch, build):
    fused = _canon(build(QuokkaContext()).collect())
    monkeypatch.setenv("QK_STAGE_FUSE", "0")
    unfused = _canon(build(QuokkaContext()).collect())
    monkeypatch.delenv("QK_STAGE_FUSE")
    return fused, unfused


def optimized_plan(stream):
    ctx = stream.ctx
    sub, _ = ctx._copy_subgraph(stream.node_id)
    sink = logical.SinkNode([stream.node_id], sub[stream.node_id].schema)
    sid = max(sub) + 1
    sub[sid] = sink
    optimize(sub, sid)
    return sub, sid


def find_nodes(sub, sid, cls):
    return [sub[n] for n in _reachable(sub, sid) if isinstance(sub[n], cls)]


class TestFusionPlanning:
    def test_q3_chain_collapses_to_one_fused_stage(self):
        fact, dim1, dim2 = make_tables()
        sub, sid = optimized_plan(q3_stream(QuokkaContext(), fact, dim1, dim2))
        fused = find_nodes(sub, sid, logical.FusedStageNode)
        assert len(fused) == 1
        # the members left the graph: the chain is ONE actor now
        assert not find_nodes(sub, sid, logical.JoinNode)
        assert not find_nodes(sub, sid, logical.AggNode)
        assert len(fused[0].members) == 3  # join, join, agg (filter pushed)

    def test_kill_switch_disables_fusion(self, monkeypatch):
        fact, dim1, dim2 = make_tables()
        monkeypatch.setenv("QK_STAGE_FUSE", "0")
        sub, sid = optimized_plan(q3_stream(QuokkaContext(), fact, dim1, dim2))
        assert not find_nodes(sub, sid, logical.FusedStageNode)
        assert find_nodes(sub, sid, logical.JoinNode)

    def test_multi_consumer_producer_is_a_chain_boundary(self):
        """A producer feeding TWO consumers must stay a real node: fusing
        it into either chain would duplicate its work (and its lineage)."""
        fact, dim1, _ = make_tables()
        ctx = QuokkaContext()
        fs = ctx.read_dataset(InputArrowDataset(fact, batch_rows=1024))
        d1 = ctx.read_dataset(InputArrowDataset(dim1, batch_rows=128))
        f = fs.join(d1, left_on="fk", right_on="pk")  # 2 consumers below
        a = f.groupby("fk").agg_sql("sum(v) as sv")
        q = f.join(a, on="fk").groupby("ck").agg_sql("sum(sv) as t")
        sub, sid = optimized_plan(q)
        # the shared join survives as its own node — it was not absorbed
        # into either downstream chain
        assert find_nodes(sub, sid, logical.JoinNode)

    def test_blocking_operator_is_a_chain_boundary(self):
        fact, dim1, _ = make_tables()
        ctx = QuokkaContext()
        fs = ctx.read_dataset(InputArrowDataset(fact, batch_rows=1024))
        d1 = ctx.read_dataset(InputArrowDataset(dim1, batch_rows=128))
        q = (fs.join(d1, left_on="fk", right_on="pk")
             .distinct(["fk", "ck"])
             .groupby("ck").agg_sql("count(*) as n"))
        sub, sid = optimized_plan(q)
        # distinct is stateful/blocking: it must never ride inside a fused
        # stage, and no fused stage may span across it
        assert find_nodes(sub, sid, logical.DistinctNode)
        for f in find_nodes(sub, sid, logical.FusedStageNode):
            assert not any(isinstance(m, logical.DistinctNode)
                           for m in f.members)


class TestFusionBitExactness:
    def test_q3_shape(self, monkeypatch):
        fact, dim1, dim2 = make_tables()
        fused, unfused = _fused_vs_unfused(
            monkeypatch, lambda ctx: q3_stream(ctx, fact, dim1, dim2))
        pd.testing.assert_frame_equal(fused, unfused, check_exact=True)
        assert fused["n"].sum() > 0

    def test_q5_shape(self, monkeypatch):
        fact, dim1, dim2 = make_tables(seed=17)
        fused, unfused = _fused_vs_unfused(
            monkeypatch, lambda ctx: q5_stream(ctx, fact, dim1, dim2))
        pd.testing.assert_frame_equal(fused, unfused, check_exact=True)
        assert fused["n"].sum() > 0

    def test_all_rows_filtered(self, monkeypatch):
        """Every probe batch dies in the fused filter: the chain must emit
        nothing from its interior — no phantom rows, no crash at done()."""
        fact, dim1, dim2 = make_tables()

        def build(ctx):
            fs = ctx.read_dataset(InputArrowDataset(fact, batch_rows=1024))
            d1 = ctx.read_dataset(InputArrowDataset(dim1, batch_rows=128))
            d2 = ctx.read_dataset(InputArrowDataset(dim2, batch_rows=128))
            return (fs.filter(col("flag") < 0)
                    .join(d1, left_on="fk", right_on="pk")
                    .join(d2, left_on="ck", right_on="pk2")
                    .groupby("grp").agg_sql("sum(v) as sv, count(*) as n"))

        fused, unfused = _fused_vs_unfused(monkeypatch, build)
        assert len(fused) == 0
        pd.testing.assert_frame_equal(fused, unfused, check_exact=True)

    def test_empty_input_table(self, monkeypatch):
        fact, dim1, dim2 = make_tables()
        empty = fact.slice(0, 0)

        def build(ctx):
            return q3_stream(ctx, empty, dim1, dim2)

        fused, unfused = _fused_vs_unfused(monkeypatch, build)
        assert len(fused) == 0
        pd.testing.assert_frame_equal(fused, unfused, check_exact=True)

    def test_duplicate_build_keys_multiply_rows(self, monkeypatch):
        """Dup keys on the broadcast build side fan each probe row out —
        the fused interior join must multiply exactly like the unfused
        actor pipeline does."""
        fact, dim1, dim2 = make_tables()
        dup = pa.concat_tables([dim1, dim1])  # every pk twice

        def build(ctx):
            return q3_stream(ctx, fact, dup, dim2)

        fused, unfused = _fused_vs_unfused(monkeypatch, build)
        pd.testing.assert_frame_equal(fused, unfused, check_exact=True)
        # sanity: the duplication actually multiplied the join output
        base = _canon(q3_stream(QuokkaContext(), fact, dim1, dim2).collect())
        assert fused["n"].sum() == 2 * base["n"].sum()


class TestFusedStageRecovery:
    def test_chaos_kill_mid_stage_bit_exact(self, tmp_path):
        """Kill the fused actor's channel mid-query: recovery (stage-
        granular checkpoints + HBQ replay) must land on the identical
        integer answer."""
        fact, dim1, dim2 = make_tables(seed=23)
        baseline = _canon(
            q3_stream(QuokkaContext(), fact, dim1, dim2).collect())
        ctx = QuokkaContext()
        ctx.set_config("fault_tolerance", True)
        ctx.set_config("hbq_path", str(tmp_path))
        ctx.set_config("checkpoint_interval", 3)
        # actor 3 is the FusedStage (0-2 are the sources; 4 final agg)
        ctx.set_config("inject_failure",
                       {"after_tasks": 8, "channels": [(3, 0)]})
        got = _canon(q3_stream(ctx, fact, dim1, dim2).collect())
        pd.testing.assert_frame_equal(got, baseline, check_exact=True)

    def test_opstats_sees_the_fused_stage(self):
        """EXPLAIN ANALYZE keeps working at stage granularity: the fused
        actor reports under its member-chain OP_NAME with per-member row
        notes (ops/stagefuse.FusedStageExecutor._note_rows)."""
        from quokka_tpu.obs import opstats

        fact, dim1, dim2 = make_tables()
        res = q3_stream(QuokkaContext(), fact, dim1, dim2).collect()
        assert len(res) > 0
        snap = opstats.OPSTATS.last_finished()
        assert snap is not None
        names = [o["op"] for o in snap["operators"]]
        assert any(n.startswith("FusedStage[") for n in names), names
